"""Shim for legacy ``pip install -e .`` / ``python setup.py`` workflows.

All metadata lives in ``pyproject.toml`` (the reference carries its
metadata in ``setup.py`` + ``torchmetrics/setup_tools.py``; here the
modern single-source layout replaces both).
"""
from setuptools import setup

setup()
