"""Sphinx configuration for the metrics-tpu documentation site.

The equivalent of the reference's ``docs/source/conf.py`` (sphinx +
readthedocs): the existing markdown guides and the generated per-symbol API
pages (``docs/generate_api.py``) are built into one site via MyST. Build
with ``make docs`` from the repo root (installs come from the ``[docs]``
extra); doctests in the package run separately in CI via
``pytest --doctest-modules``.
"""
import os
import sys

sys.path.insert(0, os.path.abspath(".."))

project = "metrics-tpu"
copyright = "2026, metrics-tpu contributors"
author = "metrics-tpu contributors"

try:
    from metrics_tpu import __version__ as release
except Exception:  # building docs without the package importable
    release = "0.0"

extensions = [
    "myst_parser",
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
    "sphinx.ext.intersphinx",
]

myst_enable_extensions = ["colon_fence", "deflist"]
source_suffix = {".md": "markdown", ".rst": "restructuredtext"}

master_doc = "index"
exclude_patterns = ["_build", "Thumbs.db", ".DS_Store"]

html_theme = "furo"
html_title = f"metrics-tpu {release}"

intersphinx_mapping = {
    "python": ("https://docs.python.org/3", None),
    "jax": ("https://docs.jax.dev/en/latest", None),
}

# the generated API pages document every symbol already; autodoc is only
# used opportunistically, so missing optional deps must not fail the build
autodoc_mock_imports = ["flax", "transformers", "orbax", "optax", "torch"]
nitpicky = False
