"""Load generator: 1k+ simulated clients through a multi-level tree.

The serving tier's bench instrument (and the acceptance harness for the
ROADMAP "metrics-as-a-service" lane): simulate ``n_clients`` independent
clients, each folding its own score/label stream into a bounded sketch
collection and shipping cumulative snapshots into a leaf of an in-process
:class:`~metrics_tpu.serve.tree.AggregationTree`; pump the tree after each
ship round; read the sustained throughput off the obs counters the
aggregators already maintain:

* ``serve_ingest_merges_per_s`` — client-snapshot merges folded per
  second, summed over every node of the tree (the ``serve.merges``
  counter family delta over the timed window).
* ``serve_ingest_p99_ms`` — p99 of the per-payload ingest latency
  histogram (``serve.ingest_ms``: decode + validate + queue wait + dedup
  + snapshot store).

Payload bytes are pre-encoded outside the timed window — the client-side
fold/encode cost is a *client* budget; the rows measure the aggregation
tier. ``verify=True`` (tests/smoke) additionally pins the whole run
against a flat single-aggregator merge of every client's final snapshot,
bitwise on the merged state leaves — the tree invariant end to end.

Bench rows ride ``bench.py --json`` with ``process_count`` attached and
participate in the ``--compare`` gate as a **rate row** (higher is
better; ``benchmarks/compare.py`` inverts the gate direction for ``/s``
units and normalizes by the elementwise chip probe).
"""
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

__all__ = ["run_loadgen"]


def _client_stream(rng: np.random.Generator, n: int) -> Dict[str, np.ndarray]:
    preds = rng.uniform(0.0, 1.0, n).astype(np.float32)
    target = (rng.uniform(0.0, 1.0, n) < 0.25 + 0.5 * preds).astype(np.int32)
    return {"preds": preds, "target": target}


def run_loadgen(
    n_clients: int = 1000,
    fan_out: Sequence[int] = (4, 16),
    payloads_per_client: int = 2,
    samples_per_payload: int = 256,
    num_bins: int = 256,
    seed: int = 0,
    verify: bool = False,
    tenant: str = "loadgen",
) -> Dict[str, Any]:
    """Drive the tree and return the ``serve_*`` row values.

    Returns a dict with ``serve_ingest_merges_per_s``,
    ``serve_ingest_p99_ms`` and run accounting (clients, payload counts,
    tree shape, elapsed seconds). With ``verify=True`` the merged root
    state is additionally compared bitwise against a flat fold of every
    client's final snapshot (raises on any mismatch).
    """
    import jax.numpy as jnp

    from metrics_tpu import obs
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.serve.aggregator import Aggregator
    from metrics_tpu.serve.tree import AggregationTree
    from metrics_tpu.serve.wire import encode_state
    from metrics_tpu.streaming import StreamingAUROC

    def factory() -> MetricCollection:
        return MetricCollection({"auroc": StreamingAUROC(num_bins=num_bins)})

    # pre-encode every ship round for every client (client-side cost,
    # outside the timed aggregation window)
    rng = np.random.default_rng(seed)
    rounds: list = [[] for _ in range(payloads_per_client)]
    final_payloads = []
    for c in range(n_clients):
        client = factory()
        client_id = f"client-{c:05d}"
        for r in range(payloads_per_client):
            batch = _client_stream(rng, samples_per_payload)
            client.update(jnp.asarray(batch["preds"]), jnp.asarray(batch["target"]))
            payload = encode_state(client, tenant=tenant, client_id=client_id, watermark=(0, r))
            rounds[r].append((c, payload))
        final_payloads.append(payload)

    tree = AggregationTree(fan_out=fan_out, tenants={tenant: factory})
    was_enabled = obs.enable()
    merges_before = obs.sum_counter("serve.merges")
    try:
        t0 = time.perf_counter()
        for round_payloads in rounds:
            for c, payload in round_payloads:
                tree.leaf_for(c).ingest(payload)
            tree.pump()
        elapsed = time.perf_counter() - t0
        merges = obs.sum_counter("serve.merges") - merges_before
        hist = obs.get_histogram("serve.ingest_ms", tenant=tenant)
        p99 = hist.p99 if hist is not None else None
    finally:
        obs.enable(was_enabled)

    out: Dict[str, Any] = {
        "serve_ingest_merges_per_s": merges / elapsed if elapsed > 0 else float("nan"),
        "serve_ingest_p99_ms": float("nan") if p99 is None else float(p99),
        "clients": int(n_clients),
        "payloads": int(n_clients * payloads_per_client),
        "merges": float(merges),
        "tree_levels": len(tuple(fan_out)) + 1,
        "elapsed_s": elapsed,
    }

    if verify:
        flat = Aggregator("flat-reference")
        flat.register_tenant(tenant, factory)
        for payload in final_payloads:
            flat.ingest(payload)
        flat.flush()
        root_tenant = tree.root.aggregator._tenant(tenant)
        flat_tenant = flat._tenant(tenant)
        tree.root.aggregator.flush()
        if root_tenant.merged_leaves is None:
            root_tenant.fold()
        if flat_tenant.merged_leaves is None:
            flat_tenant.fold()
        for (path, _), a, b in zip(
            root_tenant.spec, root_tenant.merged_leaves, flat_tenant.merged_leaves
        ):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                raise AssertionError(
                    f"tree fold != flat fold at leaf {'/'.join(path)}"
                )
        out["verified_bitwise"] = True
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m metrics_tpu.serve.loadgen [--clients N] ...``"""
    import argparse
    import json

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--clients", type=int, default=1000)
    parser.add_argument("--fan-out", type=int, nargs="*", default=[4, 16])
    parser.add_argument("--payloads-per-client", type=int, default=2)
    parser.add_argument("--num-bins", type=int, default=256)
    parser.add_argument("--verify", action="store_true")
    args = parser.parse_args(argv)
    result = run_loadgen(
        n_clients=args.clients,
        fan_out=tuple(args.fan_out),
        payloads_per_client=args.payloads_per_client,
        num_bins=args.num_bins,
        verify=args.verify,
    )
    print(json.dumps(result, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
