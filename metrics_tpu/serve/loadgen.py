"""Load generator: 1k+ simulated clients through a multi-level tree.

The serving tier's bench instrument (and the acceptance harness for the
ROADMAP "metrics-as-a-service" lane): simulate ``n_clients`` independent
clients, each folding its own score/label stream into a bounded sketch
collection and shipping cumulative snapshots into a leaf of an in-process
:class:`~metrics_tpu.serve.tree.AggregationTree`; pump the tree after each
ship round; read the sustained throughput off the obs counters the
aggregators already maintain:

* ``serve_ingest_merges_per_s`` — client-snapshot merges folded per
  second, summed over every node of the tree (the ``serve.merges``
  counter family delta over the timed window).
* ``serve_ingest_p99_ms`` — p99 of the per-payload ingest latency
  histogram (``serve.ingest_ms``: decode + validate + queue wait + dedup
  + snapshot store). Steady-state: the first-fold compile chain is paid
  by one UNTIMED warmup flush before the window and reported as its own
  ``serve_cold_first_fold_ms`` row — the cold-start cost
  ``metrics_tpu.engine`` warm revival exists to eliminate.
* ``serve_e2e_freshness_ms`` — p99 end-to-end freshness at the ROOT
  (client encode wall time -> queryable after every hop), off the wire
  trace context armed payloads carry; ``serve_hop_fold_p99_ms`` is the
  root's fold-latency p99 (``serve.hop_fold_ms{node=root}``). The obs
  layer is armed for the whole run (including the pre-encode) so every
  payload carries trace provenance.

Each round's payload bytes are encoded immediately before that round's
delivery, outside the timed segments — the client-side fold/encode cost
is a *client* budget; the rows measure the aggregation tier, and the
freshness row's ``encoded_at`` anchor reflects real staleness (delivery +
folds + hops), not harness staging time. ``verify=True`` (tests/smoke) additionally pins the whole run
against a flat single-aggregator merge of every client's final snapshot,
bitwise on the merged state leaves — the tree invariant end to end.

``fault_rate > 0`` runs the same stream under a **seeded chaos schedule**
(:class:`metrics_tpu.ft.faults.WireChaos`: the rate split evenly across
drop / duplicate / reorder / corrupt, tree nodes armed with the
resilience firewall) — the ``serve_ingest_degraded_merges_per_s`` bench
row, and the row the chaos smoke pins bitwise: with ``verify=True`` the
oracle is a flat merge of **exactly the accepted snapshots** — per
client, the highest-watermark payload that was delivered uncorrupted
(corrupt payloads are refused by the wire crc32 and never accepted;
dropped ones never arrive; duplicates and reorders are absorbed by
keep-latest dedup).

Bench rows ride ``bench.py --json`` with ``process_count`` attached and
participate in the ``--compare`` gate as a **rate row** (higher is
better; ``benchmarks/compare.py`` inverts the gate direction for ``/s``
units and normalizes by the elementwise chip probe).
"""
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

__all__ = ["run_loadgen", "run_region_loadgen"]


def _client_stream(rng: np.random.Generator, n: int) -> Dict[str, np.ndarray]:
    preds = rng.uniform(0.0, 1.0, n).astype(np.float32)
    target = (rng.uniform(0.0, 1.0, n) < 0.25 + 0.5 * preds).astype(np.int32)
    return {"preds": preds, "target": target}


def run_loadgen(
    n_clients: int = 1000,
    fan_out: Sequence[int] = (4, 16),
    payloads_per_client: int = 2,
    samples_per_payload: int = 256,
    num_bins: int = 256,
    seed: int = 0,
    verify: bool = False,
    tenant: str = "loadgen",
    fault_rate: float = 0.0,
    churn: bool = False,
) -> Dict[str, Any]:
    """Drive the tree and return the ``serve_*`` row values.

    Returns a dict with ``serve_ingest_merges_per_s``,
    ``serve_ingest_p99_ms`` and run accounting (clients, payload counts,
    tree shape, elapsed seconds). With ``verify=True`` the merged root
    state is additionally compared bitwise against a flat fold of every
    client's final ACCEPTED snapshot (raises on any mismatch). With
    ``fault_rate > 0`` delivery runs under a seeded
    :class:`~metrics_tpu.ft.faults.WireChaos` schedule (rate split evenly
    over drop/duplicate/reorder/corrupt) against resilience-armed nodes;
    the refused/dropped accounting rides the returned dict.

    With ``churn=True`` the tree runs under an
    :class:`~metrics_tpu.serve.elastic.ElasticFleet`: clients consult the
    consistent-hash :class:`~metrics_tpu.serve.elastic.Router` **per
    ship**, one node JOINS after the first round and one intermediate is
    HARD-KILLED (and supervisor-healed) after the second — all inside the
    timed window, so the returned ``serve_churn_merges_per_s`` rate is
    throughput *sustained through topology churn* (the inverted-gate
    bench row). Use ``payloads_per_client >= 3`` so both churn events
    land mid-window; ``verify=True`` still pins the root bitwise (the
    rebalance must be invisible, which is the point).
    """
    import jax.numpy as jnp

    from metrics_tpu import obs
    from metrics_tpu.ft.faults import WireChaos
    from metrics_tpu.serve.aggregator import Aggregator
    from metrics_tpu.serve.resilience import ResilienceConfig
    from metrics_tpu.serve.tree import AggregationTree
    from metrics_tpu.serve.wire import WireFormatError, encode_state

    if not 0.0 <= fault_rate <= 1.0:
        raise ValueError(f"fault_rate must be in [0, 1], got {fault_rate}")

    def factory():
        from metrics_tpu.collections import MetricCollection
        from metrics_tpu.streaming import StreamingAUROC

        return MetricCollection({"auroc": StreamingAUROC(num_bins=num_bins)})

    # the obs layer is armed for the WHOLE run — including client encodes,
    # so every payload carries wire trace context; the try/finally covers
    # setup too, so a failed run can never leak an enabled registry into
    # later bench rows in the same process
    was_enabled = obs.enable()
    try:
        rng = np.random.default_rng(seed)
        # persistent per-client collections: each ship round folds a fresh
        # batch into its client and encodes JUST BEFORE delivery, so the
        # trace context's encoded_at anchors the freshness row to the
        # serving tier (delivery + folds + hops), not to harness staging —
        # a globally pre-encoded round would charge every earlier round's
        # run time to the later rounds' freshness.
        clients = [(f"client-{c:05d}", factory()) for c in range(n_clients)]
        payloads_by_client: Dict[str, list] = {cid: [] for cid, _ in clients}
        # blob -> (client_id, step, leaf index): identities are known at
        # encode time, so the timed window never parses a header for
        # bookkeeping — the degraded bench row must measure the serving
        # tier, not the harness
        identity: Dict[bytes, tuple] = {}

        chaos = None if fault_rate <= 0 else WireChaos(
            seed=seed + 1,
            p_drop=fault_rate / 4,
            p_duplicate=fault_rate / 4,
            p_reorder=fault_rate / 4,
            p_corrupt=fault_rate / 4,
            p_delay=0.0,
        )
        # oracle bookkeeping (chaos only): the set of (client, step)
        # payloads delivered UNCORRUPTED at least once — keep-latest makes
        # the highest step per client the accepted snapshot. A successfully
        # ingested blob is always an original (corruption is refused by the
        # crc32), so its identity comes off the pre-built map.
        delivered: set = set()
        refused = 0
        refused_circuit = 0
        churn_events: Dict[str, Any] = {}

        def deliver(blobs, c: int) -> None:
            nonlocal refused, refused_circuit
            from metrics_tpu.serve.resilience import CircuitOpenError

            for blob in blobs:
                try:
                    _ingest_for(c, identity[blob][0] if blob in identity else None, blob)
                except WireFormatError:
                    refused += 1  # corrupt-in-flight, refused by the crc32
                except CircuitOpenError:
                    # a client unlucky enough to draw consecutive
                    # corruptions opened its circuit — its next CLEAN
                    # payload is refused too. A refusal is a non-delivery
                    # (consistent with the oracle), never a harness crash.
                    refused_circuit += 1
                else:
                    client_id, step, _ = identity[blob]
                    delivered.add((client_id, step))

        tree = AggregationTree(
            fan_out=fan_out,
            tenants={tenant: factory},
            resilience=None if chaos is None else ResilienceConfig(),
        )
        fleet = None
        if churn:
            from metrics_tpu.serve.elastic import ElasticFleet

            fleet = ElasticFleet(tree, seed=seed + 2)

        def _ingest_for(c: int, client_id, blob: bytes) -> None:
            # elastic mode routes by the ring (the per-ship Router consult
            # the elasticity contract requires); static mode keeps the
            # round-robin leaf so the established rows stay comparable
            if fleet is not None and client_id is not None:
                fleet.router.route(client_id).ingest(blob)
            else:
                tree.leaf_for(c).ingest(blob)
        # UNTIMED warmup flush: one identity (freshly-reset) snapshot from a
        # throwaway client through leaf 0 and a full pump. The cold cost —
        # the first fold's trace+compile chain down every level — is its own
        # row (``serve_cold_first_fold_ms``) instead of smearing into the
        # timed window's tail (``serve_ingest_p99_ms`` is steady-state
        # again). The identity contribution is bitwise-neutral to every
        # fold (sum+0; min/max against their identities; empty sketch
        # counts — the same argument the pow-2 fold padding relies on), so
        # the verify oracle and every later merged value are unchanged.
        warm_payload = encode_state(
            factory(), tenant=tenant, client_id="client-warmup", watermark=(0, 0)
        )
        t0 = time.perf_counter()
        tree.leaf_for(0).ingest(warm_payload)
        tree.pump()
        cold_first_fold_ms = (time.perf_counter() - t0) * 1000.0
        merges_before = obs.sum_counter("serve.merges")
        # elapsed sums only the DELIVERY + PUMP segments; the per-round
        # client fold/encode between them is client-side budget
        elapsed = 0.0
        for r in range(payloads_per_client):
            round_payloads = []
            for c, (client_id, client) in enumerate(clients):
                batch = _client_stream(rng, samples_per_payload)
                client.update(jnp.asarray(batch["preds"]), jnp.asarray(batch["target"]))
                payload = encode_state(
                    client, tenant=tenant, client_id=client_id, watermark=(0, r)
                )
                round_payloads.append((c, payload))
                payloads_by_client[client_id].append(payload)
                identity[payload] = (client_id, r, c)
            t0 = time.perf_counter()
            for c, payload in round_payloads:
                if chaos is None:
                    _ingest_for(c, identity[payload][0], payload)
                else:
                    _, now_blobs = chaos.plan(payload)
                    deliver(now_blobs, c)
            if chaos is not None:
                # round boundary: reordered payloads land shuffled; held
                # blobs are always originals, so routing comes off the
                # identity map too
                for blob in chaos.end_round():
                    deliver([blob], identity[blob][2])
            tree.pump()
            if fleet is not None and r == 0:
                # churn event 1, INSIDE the timed window: a node joins —
                # admission protocol + ring re-homing all count against the
                # sustained rate (that is what the churn row measures)
                churn_events["joined"] = fleet.join_node().name
            elif fleet is not None and r == 1 and len(tree.levels) > 2:
                # churn event 2: an intermediate is hard-killed and healed
                # by supervision; its state reconstructs from the children's
                # next cumulative ships on the pump below
                from metrics_tpu.ft import faults
                from metrics_tpu.serve.resilience import Supervisor

                victim = tree.levels[1][len(tree.levels[1]) // 2]
                faults.kill_node(victim)
                Supervisor(tree, warn=False).heal()
                churn_events["killed"] = victim.name
                tree.pump()
            elapsed += time.perf_counter() - t0
        if chaos is not None:
            t0 = time.perf_counter()
            for blob in chaos.flush():
                deliver([blob], identity[blob][2])
            tree.pump()
            elapsed += time.perf_counter() - t0
        merges = obs.sum_counter("serve.merges") - merges_before
        hist = obs.get_histogram("serve.ingest_ms", tenant=tenant)
        p99 = hist.p99 if hist is not None else None
        # per-hop provenance rows, read at the ROOT: end-to-end freshness
        # (client encode wall time -> state queryable at the root) and the
        # root's fold latency — the two new fleet-observability bench rows
        fresh_hist = obs.get_histogram("serve.e2e_freshness_ms", node="root")
        fold_hist = obs.get_histogram("serve.hop_fold_ms", node="root")
        freshness_p99 = fresh_hist.p99 if fresh_hist is not None else None
        fold_p99 = fold_hist.p99 if fold_hist is not None else None
    finally:
        obs.enable(was_enabled)

    # per-hop provenance accounting (outside the timed window): total
    # payloads ACCEPTED (watermark-advancing) across every tree node — the
    # number the serve.hop_queue_wait_ms{node=} histograms must account
    # for exactly, chaos or no chaos (tests/serve/test_trace.py pins it)
    accepted_payloads = sum(
        node.aggregator._tenant(tenant).folded_payloads for node in tree.nodes
    )

    out: Dict[str, Any] = {
        "serve_ingest_merges_per_s": merges / elapsed if elapsed > 0 else float("nan"),
        "serve_ingest_p99_ms": float("nan") if p99 is None else float(p99),
        "serve_cold_first_fold_ms": float(cold_first_fold_ms),
        "serve_e2e_freshness_ms": float("nan") if freshness_p99 is None else float(freshness_p99),
        "serve_hop_fold_p99_ms": float("nan") if fold_p99 is None else float(fold_p99),
        "clients": int(n_clients),
        "payloads": int(n_clients * payloads_per_client),
        "merges": float(merges),
        "accepted_payloads": int(accepted_payloads),
        "tree_levels": len(tuple(fan_out)) + 1,
        "elapsed_s": elapsed,
    }
    if chaos is not None:
        out["chaos_counts"] = dict(chaos.counts)
        out["refused_corrupt"] = int(refused)
        out["refused_circuit"] = int(refused_circuit)
    if churn:
        # the same sustained rate, named as the churn row: merges/s held
        # while a node joined and an intermediate died mid-window
        out["serve_churn_merges_per_s"] = out["serve_ingest_merges_per_s"]
        out["churn_events"] = dict(churn_events)

    if verify:
        # the oracle: per client, the highest-watermark snapshot that was
        # delivered uncorrupted — EXACTLY the set keep-latest accepted.
        # Fault-free, that is simply every client's final snapshot.
        accepted: Dict[str, int] = {}
        if chaos is None:
            accepted = {cid: payloads_per_client - 1 for cid in payloads_by_client}
        else:
            for client_id, step in delivered:
                if client_id not in accepted or step > accepted[client_id]:
                    accepted[client_id] = step
        flat = Aggregator("flat-reference")
        flat.register_tenant(tenant, factory)
        for client_id, step in sorted(accepted.items()):
            flat.ingest(payloads_by_client[client_id][step])
        flat.flush()
        root_tenant = tree.root.aggregator._tenant(tenant)
        flat_tenant = flat._tenant(tenant)
        tree.root.aggregator.flush()
        if root_tenant.merged_leaves is None:
            root_tenant.fold()
        if flat_tenant.merged_leaves is None:
            flat_tenant.fold()
        for (path, _), a, b in zip(
            root_tenant.spec, root_tenant.merged_leaves, flat_tenant.merged_leaves
        ):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                raise AssertionError(
                    f"tree fold != flat fold at leaf {'/'.join(path)}"
                )
        out["verified_bitwise"] = True
    return out


def run_region_loadgen(
    n_regions: int = 3,
    n_clients: int = 300,
    fan_out: Sequence[int] = (2,),
    payloads_per_client: int = 2,
    samples_per_payload: int = 256,
    num_bins: int = 256,
    seed: int = 0,
    verify: bool = False,
    tenant: str = "loadgen",
) -> Dict[str, Any]:
    """Drive a :class:`~metrics_tpu.serve.RegionalMesh` and return the
    multi-region bench row values.

    ``n_clients`` clients are split across ``n_regions`` regions (each an
    in-region tree of shape ``fan_out``); every ship round folds a fresh
    batch per client, delivers regionally, pumps each region's tree and
    runs one full cross-region replication sweep — delivery + pump +
    replicate are the timed segments (client fold/encode stays a client
    budget, like :func:`run_loadgen`). Rows:

    * ``serve_cross_region_merges_per_s`` — accepted ``region:*`` replica
      merges per second summed over every region's global view (the
      ``serve.cross_region_merges`` counter delta): the cross-root
      replication throughput, an inverted-gate rate row.
    * ``serve_global_query_staleness_ms`` — p99 of the worst-peer replica
      age observed by global queries (``serve.global_query_staleness_ms``,
      one sample per :meth:`Region.query_global` — each round queries
      every region): the freshness cost of answering globally.

    ``verify=True`` pins every region's global view bitwise against ONE
    flat merge of every client's final snapshot — the multi-region
    extension of the tree-equals-flat invariant.
    """
    import jax.numpy as jnp

    from metrics_tpu import obs
    from metrics_tpu.serve.aggregator import Aggregator
    from metrics_tpu.serve.region import Region, RegionalMesh
    from metrics_tpu.serve.wire import encode_state

    if n_regions < 2:
        raise ValueError(f"n_regions must be >= 2 (a mesh), got {n_regions}")

    def factory():
        from metrics_tpu.collections import MetricCollection
        from metrics_tpu.streaming import StreamingAUROC

        return MetricCollection({"auroc": StreamingAUROC(num_bins=num_bins)})

    was_enabled = obs.enable()
    try:
        rng = np.random.default_rng(seed)
        names = [f"r{i}" for i in range(n_regions)]
        mesh = RegionalMesh(
            [Region(name, {tenant: factory}, fan_out=fan_out) for name in names]
        )
        clients = [(f"client-{c:05d}", factory(), names[c % n_regions]) for c in range(n_clients)]
        final_payloads: Dict[str, bytes] = {}

        merges_before = obs.sum_counter("serve.cross_region_merges")
        elapsed = 0.0
        for r in range(payloads_per_client):
            round_payloads = []
            for client_id, client, region_name in clients:
                batch = _client_stream(rng, samples_per_payload)
                client.update(jnp.asarray(batch["preds"]), jnp.asarray(batch["target"]))
                payload = encode_state(
                    client, tenant=tenant, client_id=client_id, watermark=(0, r)
                )
                round_payloads.append((client_id, region_name, payload))
                final_payloads[client_id] = payload
            t0 = time.perf_counter()
            for client_id, region_name, payload in round_payloads:
                mesh.region(region_name).ingest(payload, client_id=client_id)
            for name in names:
                mesh.region(name).pump()
            mesh.replicate()
            elapsed += time.perf_counter() - t0
            # every region answers globally each round — the staleness row
            # is one worst-peer sample per (region, round) query, taken
            # OUTSIDE the timed window: the rate row measures replication
            # throughput, and folding query cost into it would let a read-
            # path regression fire the replication gate
            for name in names:
                mesh.region(name).query_global(tenant)
        merges = obs.sum_counter("serve.cross_region_merges") - merges_before
        stale_p99 = 0.0
        for name in names:
            hist = obs.get_histogram("serve.global_query_staleness_ms", node=name)
            if hist is not None and hist.count:
                stale_p99 = max(stale_p99, float(hist.p99))
    finally:
        obs.enable(was_enabled)

    out: Dict[str, Any] = {
        "serve_cross_region_merges_per_s": merges / elapsed if elapsed > 0 else float("nan"),
        "serve_global_query_staleness_ms": stale_p99,
        "regions": int(n_regions),
        "clients": int(n_clients),
        "cross_region_merges": float(merges),
        "elapsed_s": elapsed,
    }
    if verify:
        flat = Aggregator("flat-reference")
        flat.register_tenant(tenant, factory)
        for client_id in sorted(final_payloads):
            flat.ingest(final_payloads[client_id])
        flat.flush()
        flat_tenant = flat._tenant(tenant)
        if flat_tenant.merged_leaves is None:
            flat_tenant.fold()
        for name in names:
            gt = mesh.region(name).global_view._tenant(tenant)
            if gt.merged_leaves is None:
                gt.fold()
            for (path, _), ours, oracle in zip(
                gt.spec, gt.merged_leaves, flat_tenant.merged_leaves
            ):
                if not np.array_equal(np.asarray(ours), np.asarray(oracle)):
                    raise AssertionError(
                        f"region {name} global view != flat fold at leaf {'/'.join(path)}"
                    )
        out["verified_bitwise"] = True
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m metrics_tpu.serve.loadgen [--clients N] ...``"""
    import argparse
    import json

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--clients", type=int, default=1000)
    parser.add_argument("--fan-out", type=int, nargs="*", default=[4, 16])
    parser.add_argument("--payloads-per-client", type=int, default=2)
    parser.add_argument("--num-bins", type=int, default=256)
    parser.add_argument("--verify", action="store_true")
    parser.add_argument("--fault-rate", type=float, default=0.0)
    parser.add_argument("--churn", action="store_true")
    args = parser.parse_args(argv)
    result = run_loadgen(
        n_clients=args.clients,
        fan_out=tuple(args.fan_out),
        payloads_per_client=args.payloads_per_client,
        num_bins=args.num_bins,
        verify=args.verify,
        fault_rate=args.fault_rate,
        churn=args.churn,
    )
    print(json.dumps(result, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
