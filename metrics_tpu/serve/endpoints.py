"""Scrape / query / ingest HTTP surface for an aggregator node.

Stdlib-only (``http.server``): the serving tier must not grow dependencies
the container doesn't bake. One :class:`MetricsServer` wraps one
:class:`~metrics_tpu.serve.Aggregator` with four routes:

* ``GET /metrics`` — Prometheus text exposition. The body is
  :func:`metrics_tpu.obs.to_prometheus` over the process-wide obs
  registry — the per-tenant ``serve.ingests`` / ``serve.merges`` /
  ``serve.dedup_drops`` counters, ``serve.ingest_ms`` latency histograms
  and queue/tenant gauges land there at ingest/fold time — plus
  per-tenant **value gauges** (``serve.value{tenant=,metric=}``) refreshed
  from the merged state at scrape time (scalar values only; structured
  values ride ``/query``).
* ``GET /query?tenant=ID`` — JSON merged values with the streaming
  metrics' rigorous ``error_bound`` / ``bounds`` envelopes, plus client
  and watermark accounting (:meth:`Aggregator.query`). With a
  ``region=`` wired, ``&scope=global`` answers the region's GLOBAL view
  instead — merged across every region's replica, carrying per-region
  freshness and the ``degraded`` verdict; a ``stale_reads="reject"``
  policy violation answers 503 naming the stale regions (the
  multi-region degraded-read contract, ``docs/serving.md`` §9).
  ``&start=&end=`` (epoch seconds, plus optional ``&step=`` and
  ``&mode=delta|cumulative``) switches to the TIME-TRAVEL surface
  (:meth:`Aggregator.history_query` over the retention rings,
  ``docs/serving.md`` §10): per-interval deltas or as-of cumulative
  values with per-interval error envelopes. Range-specific refusals map
  to dedicated statuses — **400** for a delta query over a
  non-invertible max/min state (``DeltaUndefinedError``), **416** for a
  range older than the retention horizon (``HistoryRetentionError``),
  **409** for a delta spanning a failover generation boundary
  (``GenerationFencedRangeError``: re-query per generation, or
  ``mode=cumulative``).
* ``POST /ingest`` — the wire payload as the request body; 200 on accept,
  400 on malformed/schema-mismatched payloads, 404 for unknown tenants,
  503 on queue backpressure, 409 for a generation-fenced zombie ship
  (a superseded pre-failover root: retrying can never succeed). Tree
  nodes cross process boundaries by pointing
  :class:`~metrics_tpu.serve.tree.AggregatorNode`'s ``send`` at
  this route — the bytes are identical to the in-process path.
* ``GET /experiment/<id>`` — JSON report for one registered experiment
  (:meth:`~metrics_tpu.experiment.DecisionEngine.report`): per-arm
  tenants, test configuration, the always-valid p-value, evaluation /
  fencing counts, the latest evidence cut and — once the engine has
  decided — the durable ship/stop decision record. **404** for an
  unknown experiment id, **400** when no decision engine is attached to
  this aggregator (experimentation is a ROOT concern; leaves serve only
  their tenants).
* ``GET /slo`` — the tenant-facing SLO report
  (:meth:`~metrics_tpu.obs.slo.SLOEngine.report`): definitions, per-tenant
  SLI values, fast/slow burn rates, budget remaining and the
  currently-firing alerts. **400** when no SLO engine is attached
  (``SLOEngine(aggregator, ...)`` — an SLO plane is a root concern).
* ``GET /tenants`` — metered usage per tenant (wire bytes, resident state
  bytes, history-ring bytes, client/ingest counts from the ``meter.*``
  families) plus the fleet's sketch-backed top-consumer ranking
  (:func:`metrics_tpu.obs.meter.top_consumers`) with its overestimate
  bounds.
* ``GET /trace`` — Chrome-trace JSON (:func:`metrics_tpu.obs.to_chrome_trace`):
  host spans plus per-hop payload lifecycles (queue-wait / fold / ship /
  e2e per trace id), loadable in Perfetto — the debug view behind the
  ``serve.hop_*_ms`` histograms.
* ``POST /admin/drain`` — run the drain protocol on this node. With a
  ``fleet=`` wired (an :class:`~metrics_tpu.serve.elastic.ElasticFleet`
  member) the FULL protocol runs — ring exit, queue folded to empty,
  client handoff, tombstoned retirement; otherwise the node-local half
  (:meth:`Aggregator.drain`): admission refused from the first byte, the
  ingest queue folded to empty, the worker stopped; ``/healthz/ready``
  answers 503 from then on so load balancers route away. Optional JSON
  body ``{"timeout_s": N}``. ``POST /admin/unquarantine`` — lift a
  poisoned-state quarantine (JSON body ``{"tenant": ..., "client": ...}``;
  400 on a malformed body or unarmed firewall, 404 for an unknown tenant
  — consistent with ``/ingest``). Operator levers, deliberately narrow:
  they change *this node's* admission state, never tenant data.
* ``GET /healthz`` — full health JSON (tenant/client/queue counts plus the
  readiness detail). Kubernetes-style split probes:
  ``GET /healthz/live`` — pure liveness (the process answers); and
  ``GET /healthz/ready`` — readiness, 200/503 off queue saturation,
  flush-worker liveness and last-flush age, reporting queue depth,
  last-flush age and the firewall's open-circuit / quarantined clients. A
  node that is alive but drowning answers live=200 / ready=503 — restart
  nothing, route traffic elsewhere.

The server arms the obs layer by default (``arm_obs=True``): a scrape
endpoint over a disabled registry would export silence, which reads as
"healthy fleet, zero traffic" — the failure mode observability exists to
prevent.
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from metrics_tpu.serve.aggregator import (
    Aggregator,
    BackpressureError,
    DrainingError,
    FencedGenerationError,
    ServeError,
    UnknownTenantError,
)
from metrics_tpu.serve.resilience import CircuitOpenError, QuarantinedClientError
from metrics_tpu.serve.wire import MAX_WIRE_BYTES, SchemaMismatchError, WireFormatError

__all__ = ["MetricsServer"]


class MetricsServer:
    """Serve one aggregator over HTTP (scrape / query / ingest / health).

    Args:
        aggregator: the node to expose.
        host / port: bind address; ``port=0`` picks a free port (read it
            back from :attr:`port` — the pattern tests and the in-process
            tree smoke use).
        arm_obs: enable the obs registry so serve counters/histograms are
            actually recorded and exported (default True; pass False when
            the operator manages ``obs.enable`` globally).
        ready_max_queue_frac: ``/healthz/ready`` flips to 503 when the
            ingest queue is at or above this fill fraction.
        ready_max_flush_age_s: ``/healthz/ready`` flips to 503 when the
            last completed flush is older than this (None derives
            ``max(1.0, 20 * flush_interval_s)`` for nodes with a
            background worker — a worker that stopped folding is not
            ready even while its thread is technically alive).
        fleet: the :class:`~metrics_tpu.serve.elastic.ElasticFleet` this
            aggregator is a member of, when it is. ``POST /admin/drain``
            then runs the FULL fleet drain protocol (ring exit, client
            handoff, tombstoned retirement) instead of only closing local
            admission — draining a ring member without re-homing its keys
            would blackhole ~1/n of the keyspace behind 503s.
        region: the :class:`~metrics_tpu.serve.region.Region` this node
            fronts, when multi-region serving is wired.
            ``GET /query?tenant=ID&scope=global`` then answers the
            region's GLOBAL view (:meth:`Region.query_global`): merged
            values across every region's replica, plus per-region
            freshness, the ``degraded`` verdict and ``stale_regions``
            under the region's ``max_staleness_s`` policy. With
            ``stale_reads="reject"`` a policy violation answers **503**
            with the stale regions named in the body (and a
            ``Retry-After`` hinting the staleness bound) — the
            degraded-read contract, over HTTP. ``scope=local`` (the
            default) keeps answering this aggregator's own view.

    Example::

        server = MetricsServer(agg, port=0).start()
        print(urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics").read().decode())
        server.stop()
    """

    def __init__(
        self,
        aggregator: Aggregator,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        arm_obs: bool = True,
        ready_max_queue_frac: float = 0.9,
        ready_max_flush_age_s: Optional[float] = None,
        fleet: Optional[Any] = None,
        region: Optional[Any] = None,
    ) -> None:
        self.aggregator = aggregator
        self.fleet = fleet
        self.region = region
        self.ready_max_queue_frac = float(ready_max_queue_frac)
        self.ready_max_flush_age_s = ready_max_flush_age_s
        if arm_obs:
            from metrics_tpu import obs

            obs.enable()
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MetricsServer":
        """Serve on a daemon thread; returns self for chaining."""
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"serve-http-{self.aggregator.name}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._httpd.server_close()

    # ------------------------------------------------------------------
    # Route bodies (also the in-process API the handler delegates to)
    # ------------------------------------------------------------------

    def render_metrics(self) -> str:
        """The ``/metrics`` body: refresh per-tenant value gauges from the
        merged state, then export the obs registry — FEDERATED when remote
        node snapshots have arrived (the root of a multi-process tree
        renders the whole fleet: counters summed, gauges per-node-labeled,
        histograms merged bucketwise), plain local otherwise. The scrape
        observes itself into the ``obs.scrape_ms`` histogram."""
        import time as _time

        from metrics_tpu import obs

        t0 = _time.perf_counter()
        agg = self.aggregator
        agg.flush()
        if obs.enabled():
            for tenant_id in agg.tenants():
                view = agg.collection(tenant_id, flush=False)
                try:
                    # view_lock: a concurrent background fold() must not swap
                    # state leaves mid-compute (same torn-read hazard query()
                    # guards against)
                    with agg._tenant(tenant_id).view_lock:
                        computed = view.compute()
                except Exception:  # noqa: BLE001 — a tenant with no data yet must not kill the scrape
                    continue
                for name, value in computed.items():
                    arr = np.asarray(value)
                    if arr.ndim == 0 and np.issubdtype(arr.dtype, np.number):
                        obs.set_gauge(
                            "serve.value", float(arr), tenant=tenant_id, metric=name
                        )
        if obs.enabled():
            # self-sample BEFORE the snapshot is taken, so THIS scrape's
            # exposition carries its own cost — the timed section covers
            # the flush + per-tenant gauge refresh that dominate a scrape;
            # only the final text render is excluded (an exporter cannot
            # time a string it has not built yet). Observing after the
            # snapshot hid every scrape's cost until the NEXT scrape, and
            # the final scrape's cost forever.
            obs.observe("obs.scrape_ms", (_time.perf_counter() - t0) * 1000.0)
        # federated_snapshot() already degrades to the plain local snapshot
        # when the table is empty — one table read either way
        return obs.to_prometheus(obs.federated_snapshot())

    def render_query(
        self,
        tenant: str,
        scope: str = "local",
        *,
        start: Any = None,
        end: Any = None,
        step: Any = None,
        mode: Optional[str] = None,
    ) -> Dict[str, Any]:
        import time as _time

        from metrics_tpu import obs

        t0 = _time.perf_counter()
        if scope not in ("local", "global"):
            raise ValueError(f"scope must be 'local' or 'global', got {scope!r}")
        if scope == "global" and self.region is None:
            raise ValueError(
                "scope=global requires a region-wired server"
                " (MetricsServer(..., region=...)); this node serves only its"
                " local view"
            )
        if start is not None or end is not None or step is not None or mode is not None:
            # time-travel branch: ?start=&end= select the retention-ring
            # range surface. scope=global reads the region's GLOBAL view's
            # history (the replica the cross-region ships repaired), so a
            # range answer after failover is generation-fenced exactly like
            # the local one.
            if start is None or end is None:
                raise ValueError(
                    "range queries need BOTH ?start= and ?end= (epoch seconds);"
                    " ?step= and ?mode=delta|cumulative are optional"
                )
            agg = self.region.global_view if scope == "global" else self.aggregator
            out = agg.history_query(
                tenant,
                float(start),
                float(end),
                step=None if step is None else float(step),
                mode="delta" if mode is None else str(mode),
            )
        elif scope == "global":
            out = self.region.query_global(tenant)
        else:
            out = self.aggregator.query(tenant)
        if obs.enabled():
            obs.observe("serve.query_ms", (_time.perf_counter() - t0) * 1000.0, tenant=tenant)
        return out

    def render_experiment(self, exp_id: str) -> Dict[str, Any]:
        """The ``GET /experiment/<id>`` body: the decision engine's full
        report (arms, test config, always-valid p-value, evidence cut,
        durable decision). Raises :class:`ServeError` when no engine is
        attached (400) and ``KeyError`` for an unknown id (404)."""
        engine = self.aggregator.experiments
        if engine is None:
            raise ServeError(
                f"aggregator {self.aggregator.name!r} has no decision engine"
                " attached (DecisionEngine(aggregator, ...)); experiments are"
                " served at the root"
            )
        return engine.report(exp_id)

    def render_slo(self) -> Dict[str, Any]:
        """The ``GET /slo`` body: the attached engine's full report
        (definitions, per-tenant SLIs, burn rates, budgets, active
        alerts). Raises :class:`ServeError` when no engine is attached
        (400 — SLOs are evaluated at the root, like experiments)."""
        engine = self.aggregator.slo
        if engine is None:
            raise ServeError(
                f"aggregator {self.aggregator.name!r} has no SLO engine attached"
                " (SLOEngine(aggregator, ...)); the SLO plane is served at the"
                " root"
            )
        return engine.report()

    def render_tenants(self, top: int = 10) -> Dict[str, Any]:
        """The ``GET /tenants`` body: per-registered-tenant metered usage
        from the ``meter.*`` families plus the bounded sketch ranking —
        the ranking covers tenants the cardinality cap may have dropped
        from the registry, each row carrying its overestimate bound."""
        from metrics_tpu import obs
        from metrics_tpu.obs import meter as _meter

        agg = self.aggregator
        tenants: Dict[str, Any] = {}
        for tenant_id in agg.tenants():
            entry: Dict[str, Any] = {
                "clients": len(agg._tenant(tenant_id).clients),
                "ingests": obs.get_counter("serve.ingests", tenant=tenant_id),
                "wire_bytes": obs.get_counter("meter.wire_bytes", tenant=tenant_id),
            }
            for family in ("meter.state_bytes", "meter.history_bytes"):
                value = obs.get_gauge(family, tenant=tenant_id)
                if value is not None:
                    entry[family.split(".", 1)[1]] = value
            tenants[tenant_id] = entry
        return {
            "node": agg.name,
            "tenants": tenants,
            "top_consumers": _meter.top_consumers(int(top)),
        }

    def render_trace(self) -> str:
        """The ``/trace`` body: host spans + per-hop payload lifecycles as
        Chrome-trace JSON (load it in Perfetto / ``chrome://tracing``)."""
        from metrics_tpu import obs

        return obs.to_chrome_trace()

    def render_health(self) -> Dict[str, Any]:
        agg = self.aggregator
        health = {
            "node": agg.name,
            "tenants": len(agg.tenants()),
            "clients": {t: len(agg._tenant(t).clients) for t in agg.tenants()},
            "queue_depth": agg._queue.qsize(),
        }
        health.update(self.render_ready())
        return health

    def render_live(self) -> Dict[str, Any]:
        """Pure liveness: if this executes, the process is up. Worker
        liveness is REPORTED here but gates only readiness — restarting a
        process to fix a dead thread the Supervisor can restart in place
        would throw away every client snapshot for nothing."""
        return {"live": True, "node": self.aggregator.name, "worker_alive": self.aggregator.worker_alive()}

    def admin_drain(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """The ``POST /admin/drain`` body. With a ``fleet=`` wired and this
        aggregator a live member of its tree, the FULL fleet drain protocol
        runs (:meth:`~metrics_tpu.serve.elastic.ElasticFleet.drain_node` —
        ring exit, queue folded to empty, final ship, client handoff,
        tombstoned retirement): a ring member whose admission merely closed
        would blackhole its share of the keyspace behind 503s, since the
        router would keep assigning it clients nothing re-homes. Without a
        fleet, the node-local half runs
        (:meth:`~metrics_tpu.serve.Aggregator.drain` — admission refused,
        queue folded to empty, worker stopped; the coordinator watching
        ``/healthz/ready`` owns the re-homing). Either way the node answers
        ``/healthz/ready`` 503 from the first call on."""
        # validate BEFORE any topology mutation: a malformed timeout must be
        # a 400, never a ring exit + rollback churn
        timeout_s = None if timeout_s is None else float(timeout_s)
        if self.fleet is not None:
            # resolve by NAME, not object identity: a Supervisor heal swaps
            # a fresh Aggregator into the node, and an identity miss that
            # silently fell back to the local drain would close admission
            # while the name stayed in the ring — the keyspace blackhole
            # the fleet path exists to prevent, reported as success
            node = next(
                (n for n in self.fleet.tree.nodes if n.name == self.aggregator.name),
                None,
            )
            if node is None:
                raise ValueError(
                    f"aggregator {self.aggregator.name!r} is not a member of the"
                    " wired fleet's tree; refusing a local-only drain that would"
                    " leave a ring member refusing ingest"
                )
            summary = self.fleet.drain_node(node, timeout_s=timeout_s)
            return {
                "node": summary["node"],
                "draining": True,
                "drained": summary["drained"],
                "rehomed_clients": summary["rehomed_clients"],
                "reparented": summary["reparented"],
                "protocol": "fleet",
            }
        kwargs = {} if timeout_s is None else {"timeout_s": timeout_s}
        drained = self.aggregator.drain(**kwargs)
        return {
            "node": self.aggregator.name,
            "draining": True,
            "drained": int(drained),
            "queue_depth": self.aggregator._queue.qsize(),
            "protocol": "local",
        }

    def admin_unquarantine(self, tenant: str, client: str) -> Dict[str, Any]:
        """The ``POST /admin/unquarantine`` body: lift a poisoned-state
        quarantine (:meth:`~metrics_tpu.serve.resilience.ClientFirewall.unquarantine`
        — the operator lever; quarantine never expires on its own).
        Raises for an unknown tenant (404) or an unarmed firewall (400)."""
        agg = self.aggregator
        agg._tenant(tenant)  # unknown tenant -> UnknownTenantError -> 404
        if agg.firewall is None:
            raise ValueError(
                f"aggregator {agg.name!r} has no resilience firewall armed"
                " (Aggregator(resilience=...)); nothing can be quarantined here"
            )
        lifted = agg.firewall.unquarantine(tenant, client)
        return {"node": agg.name, "tenant": str(tenant), "client": str(client), "lifted": bool(lifted)}

    def render_ready(self) -> Dict[str, Any]:
        """Readiness verdict + the signals behind it (queue depth, last
        flush age, worker liveness, circuit/quarantine states)."""
        agg = self.aggregator
        queue_depth = agg._queue.qsize()
        max_queue = agg._queue.maxsize
        flush_age = agg.last_flush_age_s()
        worker = agg.worker_alive()
        firewall = agg.firewall
        status = firewall.status() if firewall is not None else {"open_circuits": [], "quarantined": []}
        max_flush_age = self.ready_max_flush_age_s
        if max_flush_age is None and worker is not None:
            max_flush_age = max(1.0, 20.0 * agg._flush_interval_s)
        reasons = []
        if getattr(agg, "draining", False):
            # a draining node refuses ingest by contract — load balancers
            # must route away NOW, before clients see DrainingError
            reasons.append("node is draining (admission closed; clients re-route)")
        if worker is False:
            reasons.append("background flush worker died (Supervisor heal / start() restarts it)")
        if max_queue > 0 and queue_depth >= self.ready_max_queue_frac * max_queue:
            reasons.append(
                f"ingest queue at {queue_depth}/{max_queue}"
                f" (>= {self.ready_max_queue_frac:.0%} watermark)"
            )
        if worker is True and max_flush_age is not None and flush_age is not None and flush_age > max_flush_age:
            reasons.append(f"last flush completed {flush_age:.1f}s ago (> {max_flush_age:.1f}s)")
        out = {
            "ready": not reasons,
            "reasons": reasons,
            "queue_depth": queue_depth,
            "max_queue": max_queue,
            "worker_alive": worker,
            "last_flush_age_s": flush_age,
            "open_circuits": status["open_circuits"],
            "quarantined": status["quarantined"],
        }
        if agg.history is not None:
            # surfaced, NOT gating: a firing metric alert (AUROC regressed)
            # is a data-quality page, not a routing signal — flipping ready
            # would shift traffic off a perfectly serviceable node
            out["history_alerts"] = agg.history.active_alerts()
        if agg.slo is not None:
            # same stance: a tenant burning ITS budget is that tenant's
            # page, not a reason to route every other tenant away
            out["slo_alerts"] = agg.slo.active_alerts()
        if agg.canary is not None:
            # the black-box correctness verdict: a bitwise MISMATCH is the
            # one signal here that does mean "this node's answers are
            # wrong" — still surfaced (the operator decides), with the
            # healthy flag front and center for automation
            out["canary"] = agg.canary.status()
        from metrics_tpu.obs import federation as _federation

        if _federation.remote_count():
            # fleet detail (federated roots only): which nodes have reported
            # and how stale each snapshot is — a silent subtree shows up
            # here as a growing age, not as a missing line nobody notices
            out["fleet_nodes"] = {k: round(v, 3) for k, v in _federation.node_ages().items()}
        return out


def _make_handler(server: MetricsServer):
    class Handler(BaseHTTPRequestHandler):
        # socket timeout: a client that declares Content-Length N but sends
        # fewer bytes (and keeps the connection open) would otherwise pin
        # this handler's thread in rfile.read() forever — N such clients
        # exhaust the pool and starve scrapes. On timeout the connection is
        # closed (handle_one_request treats it as an error), never hung.
        timeout = 30.0

        # quiet: request logging at scrape cadence would drown real logs
        def log_message(self, fmt: str, *args: Any) -> None:  # noqa: A003
            pass

        def _reply(
            self,
            status: int,
            body: bytes,
            content_type: str,
            headers: Optional[Dict[str, str]] = None,
        ) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(
            self, status: int, obj: Dict[str, Any], headers: Optional[Dict[str, str]] = None
        ) -> None:
            self._reply(status, (json.dumps(obj) + "\n").encode(), "application/json", headers)

        def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
            parsed = urlparse(self.path)
            from metrics_tpu.serve.region import StaleGlobalViewError

            try:
                if parsed.path == "/metrics":
                    body = server.render_metrics().encode()
                    self._reply(200, body, "text/plain; version=0.0.4; charset=utf-8")
                elif parsed.path == "/trace":
                    self._reply(200, server.render_trace().encode(), "application/json")
                elif parsed.path == "/query":
                    from metrics_tpu.serve.history import (
                        DeltaUndefinedError,
                        GenerationFencedRangeError,
                        HistoryRetentionError,
                    )

                    params = parse_qs(parsed.query)
                    tenant = (params.get("tenant") or [None])[0]
                    scope = (params.get("scope") or ["local"])[0]
                    if tenant is None:
                        self._reply_json(400, {"error": "missing ?tenant= parameter"})
                        return
                    try:
                        self._reply_json(
                            200,
                            server.render_query(
                                tenant,
                                scope,
                                start=(params.get("start") or [None])[0],
                                end=(params.get("end") or [None])[0],
                                step=(params.get("step") or [None])[0],
                                mode=(params.get("mode") or [None])[0],
                            ),
                        )
                    except StaleGlobalViewError as err:
                        # the degraded-read contract's REJECT arm: peers
                        # aged out past the region's max_staleness_s and
                        # the policy forbids answering — 503 naming the
                        # stale regions, so the caller can fail over to a
                        # healthy region (or re-query scope=local)
                        headers = None
                        if err.retry_after_s is not None:
                            headers = {
                                "Retry-After": str(max(1, int(err.retry_after_s + 0.999)))
                            }
                        self._reply_json(
                            503,
                            {
                                "error": str(err),
                                "degraded": True,
                                "stale_regions": err.stale_regions,
                            },
                            headers=headers,
                        )
                    except DeltaUndefinedError as err:
                        # a delta over a non-invertible max/min state is a
                        # CONTRACT refusal, not a server fault: the caller
                        # should re-ask mode=cumulative
                        self._reply_json(400, {"error": str(err), "mode_hint": "cumulative"})
                    except HistoryRetentionError as err:
                        # 416 Range Not Satisfiable: the asked-for range
                        # predates the retention horizon (evicted intervals
                        # cannot be resurrected — widen the ring caps)
                        self._reply_json(416, {"error": str(err)})
                    except GenerationFencedRangeError as err:
                        # 409 Conflict: the delta spans a failover boundary;
                        # per-generation sub-ranges (or mode=cumulative)
                        # stay answerable
                        self._reply_json(409, {"error": str(err), "fenced": True})
                    except UnknownTenantError:
                        raise  # outer handler maps to 404
                    except ServeError as err:
                        # e.g. a range query against a node with no history
                        # armed — client-addressable, not a server fault
                        self._reply_json(400, {"error": str(err)})
                    except ValueError as err:
                        self._reply_json(400, {"error": str(err)})
                elif parsed.path == "/slo":
                    try:
                        self._reply_json(200, server.render_slo())
                    except ServeError as err:
                        # no engine attached: client-addressable (ask the
                        # root), not a server fault
                        self._reply_json(400, {"error": str(err)})
                elif parsed.path == "/tenants":
                    params = parse_qs(parsed.query)
                    top = (params.get("top") or ["10"])[0]
                    try:
                        self._reply_json(200, server.render_tenants(int(top)))
                    except ValueError as err:
                        self._reply_json(400, {"error": str(err)})
                elif parsed.path.startswith("/experiment/"):
                    exp_id = parsed.path[len("/experiment/") :]
                    try:
                        self._reply_json(200, server.render_experiment(exp_id))
                    except KeyError:
                        self._reply_json(404, {"error": f"unknown experiment {exp_id!r}"})
                    except ServeError as err:
                        # no engine attached: client-addressable (ask the
                        # root), not a server fault
                        self._reply_json(400, {"error": str(err)})
                elif parsed.path == "/healthz/live":
                    self._reply_json(200, server.render_live())
                elif parsed.path == "/healthz/ready":
                    ready = server.render_ready()
                    self._reply_json(200 if ready["ready"] else 503, ready)
                elif parsed.path == "/healthz":
                    self._reply_json(200, server.render_health())
                else:
                    self._reply_json(404, {"error": f"no route {parsed.path!r}"})
            except UnknownTenantError as err:
                self._reply_json(404, {"error": str(err)})
            except Exception as err:  # noqa: BLE001 — the server must answer, not die
                self._reply_json(500, {"error": f"{type(err).__name__}: {err}"})

        def _read_json_body(self, max_len: int = 65536) -> Dict[str, Any]:
            """Small-JSON POST body (admin routes). Empty body -> {};
            malformed JSON / non-object / oversized raises ValueError
            (mapped to 400, consistent with /ingest's malformed-payload
            handling)."""
            length = int(self.headers.get("Content-Length", "0"))
            if length < 0 or length > max_len:
                raise ValueError(f"admin request body of {length} bytes refused (cap {max_len})")
            if length == 0:
                return {}
            raw = self.rfile.read(length)
            obj = json.loads(raw.decode())
            if not isinstance(obj, dict):
                raise ValueError(f"admin request body must be a JSON object, got {type(obj).__name__}")
            return obj

        def do_POST(self) -> None:  # noqa: N802
            parsed = urlparse(self.path)
            if parsed.path == "/admin/drain":
                from metrics_tpu.serve.elastic import RebalancePreconditionError

                try:
                    body = self._read_json_body()
                    timeout_s = body.get("timeout_s")
                    self._reply_json(200, server.admin_drain(timeout_s))
                except (ValueError, TypeError) as err:
                    self._reply_json(400, {"error": str(err)})
                except RebalancePreconditionError as err:
                    # NOT retryable as-is (root / last ring member / dead
                    # node or parent): 409, so automation keying on 5xx
                    # does not hammer an operation that can never succeed
                    self._reply_json(409, {"error": str(err)})
                except ServeError as err:
                    # the drain TIMED OUT with payloads still queued: nothing
                    # was stranded silently, the operator retries
                    self._reply_json(500, {"error": str(err)})
                except Exception as err:  # noqa: BLE001
                    self._reply_json(500, {"error": f"{type(err).__name__}: {err}"})
                return
            if parsed.path == "/admin/unquarantine":
                try:
                    body = self._read_json_body()
                    tenant, client = body.get("tenant"), body.get("client")
                    if not tenant or not client:
                        self._reply_json(
                            400,
                            {"error": 'body must be {"tenant": ..., "client": ...}'},
                        )
                        return
                    self._reply_json(200, server.admin_unquarantine(str(tenant), str(client)))
                except UnknownTenantError as err:
                    self._reply_json(404, {"error": str(err)})
                except (ValueError, TypeError) as err:
                    self._reply_json(400, {"error": str(err)})
                except Exception as err:  # noqa: BLE001
                    self._reply_json(500, {"error": f"{type(err).__name__}: {err}"})
                return
            if parsed.path != "/ingest":
                self._reply_json(404, {"error": f"no route {parsed.path!r}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                # refuse before buffering: the bounded-payload contract is a
                # memory-safety property here — ThreadingHTTPServer buffers
                # one body per thread, so oversized POSTs would OOM the node.
                # Drain a bounded amount in chunks (never holding the body)
                # so a well-behaved client can still read the 413; anything
                # larger gets the connection cut instead.
                if length < 0 or length > MAX_WIRE_BYTES:
                    remaining = min(max(length, 0), 8 * MAX_WIRE_BYTES)
                    while remaining > 0:
                        chunk = self.rfile.read(min(65536, remaining))
                        if not chunk:
                            break
                        remaining -= len(chunk)
                    self.close_connection = True
                    self._reply_json(
                        413,
                        {
                            "error": f"Content-Length {length} exceeds the"
                            f" {MAX_WIRE_BYTES}-byte wire payload cap"
                        },
                    )
                    return
                data = self.rfile.read(length)
                accepted = server.aggregator.ingest(data, block=False)
                # shed (False) still answers 200: the payload was a
                # duplicate watermark — a retry would only re-shed it
                self._reply_json(200, {"accepted": bool(accepted), "shed": not accepted})
            except UnknownTenantError as err:
                self._reply_json(404, {"error": str(err)})
            except QuarantinedClientError as err:
                # 403, not 5xx: retrying cannot help a quarantined client
                self._reply_json(403, {"error": str(err)})
            except DrainingError as err:
                # 503 WITH a Retry-After derived from the drain timeout:
                # by that point the drain has completed (the ring routes
                # elsewhere) or rolled back — either way the client's next
                # RE-RESOLVE-and-ship is useful, where a hot retry against
                # the draining node only collects more 503s (the hint the
                # backpressure and circuit-open paths already give)
                retry_after = err.retry_after_s or 1.0
                self._reply_json(
                    503,
                    {"error": str(err)},
                    headers={"Retry-After": str(max(1, int(retry_after + 0.999)))},
                )
            except FencedGenerationError as err:
                # 409, not 5xx and not Retry-After: a zombie pre-failover
                # root's ship can NEVER succeed — a newer generation was
                # promoted for its identity; retrying is the one wrong move
                self._reply_json(409, {"error": str(err)})
            except (WireFormatError, SchemaMismatchError, ValueError) as err:
                self._reply_json(400, {"error": str(err)})
            except CircuitOpenError as err:
                self._reply_json(
                    503,
                    {"error": str(err)},
                    headers={"Retry-After": str(max(1, int(err.retry_after_s + 0.999)))},
                )
            except BackpressureError as err:
                retry_after = err.retry_after_s or 1.0
                self._reply_json(
                    503,
                    {"error": str(err)},
                    headers={"Retry-After": str(max(1, int(retry_after + 0.999)))},
                )
            except Exception as err:  # noqa: BLE001
                self._reply_json(500, {"error": f"{type(err).__name__}: {err}"})

    return Handler
