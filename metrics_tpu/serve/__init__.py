"""``metrics_tpu.serve`` — the multi-tenant metrics-aggregation runtime.

The reference "is a library, not a runtime: there is no scheduler, server,
or CLI" (PAPER.md §1). This package is the runtime layer built on the
primitives the library already proved:

* :mod:`~metrics_tpu.serve.wire` — a versioned, forward-compatible wire
  format for bounded metric-state payloads (tenant id, client id,
  ``(epoch, step)`` watermark, schema fingerprint, packed states for every
  reduction kind including ``dist_reduce_fx="sketch"``).
* :mod:`~metrics_tpu.serve.aggregator` — :class:`Aggregator`: per-tenant
  registries, a bounded ingest queue, keep-latest dedup on per-client
  :class:`~metrics_tpu.ft.BatchJournal` watermarks (exactly-once under
  duplicates, reordering and restarts), one jitted batched fold per
  flush, and preemption-safe persistence through
  :class:`~metrics_tpu.ft.CheckpointManager`.
* :mod:`~metrics_tpu.serve.tree` — hierarchical aggregation: a node is
  itself a client of its parent, and the tree fold equals a flat fold of
  every client bitwise (the sketches' fold-order invariance, pinned in
  ``tests/serve/test_tree.py``).
* :mod:`~metrics_tpu.serve.endpoints` — a stdlib ``http.server`` surface:
  ``/metrics`` Prometheus scrape (off :func:`metrics_tpu.obs.to_prometheus`
  plus per-tenant value gauges; the fleet-federated view on roots holding
  remote node snapshots), JSON ``/query`` with the streaming metrics'
  rigorous ``error_bound()`` envelopes, ``/trace`` Chrome-trace export of
  host spans + per-hop payload lifecycles, ``/ingest`` and ``/healthz``.
* :mod:`~metrics_tpu.serve.loadgen` — the 1k-client / 3-level-tree load
  generator behind the ``serve_*`` bench rows (``fault_rate=`` runs it
  under a seeded chaos schedule for the degraded-throughput row).
* :mod:`~metrics_tpu.serve.resilience` — self-healing: per-client circuit
  breakers and the poisoned-state quarantine firewall
  (``Aggregator(resilience=...)``), plus the :class:`Supervisor` that
  detects dead/hung nodes and workers via traffic-implied heartbeats and
  rebuilds them from checkpoints with a resumed ship sequence.
* :mod:`~metrics_tpu.serve.elastic` — live membership: a seeded
  consistent-hash :class:`Router` clients consult per ship, the
  :class:`ElasticFleet` join/drain/split/merge protocols whose
  handoff + tombstone rebalance keeps the root bitwise-equal to the flat
  oracle through topology churn, and the queue-pressure
  :class:`Autoscaler` reading the federated fleet signals.
* :mod:`~metrics_tpu.serve.history` — the time-travel tier
  (``Aggregator(history=...)``): per-tenant retention rings of interval
  snapshots cut from the deduped accepted state, exact 1m→1h→1d rollup
  compaction by monoid merge, the ``/query?start=&end=`` range surface
  (``delta`` vs ``cumulative`` with per-interval error envelopes),
  root-evaluated alert rules (:class:`AlertRule` / :class:`DriftRule`)
  and generation-fenced historical reads across failover — the root as
  its own metrics database (``docs/serving.md`` §10).
* :mod:`~metrics_tpu.serve.region` — multi-region serving: a
  :class:`RegionalMesh` of regional roots cross-merging their cumulative
  aggregates as ordinary wire clients (``region:<name>`` identities,
  exactly-once by watermark dedup), partition-tolerant degraded reads
  (local-complete / global-stale with per-region freshness and an
  optional ``max_staleness_s`` 503 policy), and generation-fenced
  failover to warm standbys (:class:`FencedGenerationError` refuses
  zombie pre-failover roots; promotion performs zero backend compiles
  through the :mod:`metrics_tpu.engine` store).

See ``docs/serving.md`` for the architecture, the exactly-once semantics
and the self-healing guarantees.
"""
from metrics_tpu.serve.aggregator import (
    Aggregator,
    BackpressureError,
    DrainingError,
    FencedGenerationError,
    ServeError,
    UnknownTenantError,
)
from metrics_tpu.serve.elastic import (
    Autoscaler,
    ElasticFleet,
    HashRing,
    RebalancePreconditionError,
    Router,
)
from metrics_tpu.serve.endpoints import MetricsServer
from metrics_tpu.serve.history import (
    AlertRule,
    DeltaUndefinedError,
    DriftRule,
    GenerationFencedRangeError,
    HistoryConfig,
    HistoryRetentionError,
    MetricHistory,
)
from metrics_tpu.serve.region import (
    Region,
    RegionDownError,
    RegionalMesh,
    StaleGlobalViewError,
)
from metrics_tpu.serve.resilience import (
    CircuitOpenError,
    ClientFirewall,
    NodeDownError,
    QuarantinedClientError,
    ResilienceConfig,
    Supervisor,
)
from metrics_tpu.serve.tree import AggregationTree, AggregatorNode
from metrics_tpu.serve.wire import (
    MAX_WIRE_BYTES,
    WIRE_MAJOR,
    WIRE_MINOR,
    MetricPayload,
    SchemaMismatchError,
    WireFormatError,
    apply_payload,
    decode_state,
    encode_state,
    peek_header,
    schema_fingerprint,
)

__all__ = [
    "AggregationTree",
    "Aggregator",
    "AggregatorNode",
    "AlertRule",
    "Autoscaler",
    "BackpressureError",
    "CircuitOpenError",
    "ClientFirewall",
    "DeltaUndefinedError",
    "DrainingError",
    "DriftRule",
    "ElasticFleet",
    "FencedGenerationError",
    "GenerationFencedRangeError",
    "HashRing",
    "HistoryConfig",
    "HistoryRetentionError",
    "MAX_WIRE_BYTES",
    "MetricHistory",
    "MetricPayload",
    "MetricsServer",
    "NodeDownError",
    "QuarantinedClientError",
    "RebalancePreconditionError",
    "Region",
    "RegionDownError",
    "RegionalMesh",
    "ResilienceConfig",
    "Router",
    "SchemaMismatchError",
    "ServeError",
    "StaleGlobalViewError",
    "Supervisor",
    "UnknownTenantError",
    "WIRE_MAJOR",
    "WIRE_MINOR",
    "WireFormatError",
    "apply_payload",
    "decode_state",
    "encode_state",
    "peek_header",
    "schema_fingerprint",
]
