"""Self-healing for the serving fleet: isolate bad clients, revive dead nodes.

The aggregation tier's failure modes split by blast radius, and this module
gives each one a containment mechanism smaller than "the fleet degrades":

* **one flaky client** (corrupt bytes, schema churn, hostile payloads) —
  a per-client **circuit breaker** on ingest: after
  :attr:`ResilienceConfig.error_threshold` consecutive validation failures
  the circuit *opens* and further payloads are refused immediately
  (:class:`CircuitOpenError`, HTTP 503 + ``Retry-After``) instead of paying
  decode + validation per garbage payload. After a cooldown drawn from the
  **seeded decorrelated-jitter** schedule of
  :attr:`ResilienceConfig.probe_policy` (the
  :func:`metrics_tpu.ft.retry.backoff_schedule` chain — a thousand refused
  clients do not thunder back in lockstep), the circuit goes *half-open*:
  exactly one probe payload is admitted; success closes the circuit,
  failure re-opens it with the next backoff delay. Every open transition
  counts ``serve.circuit_open{tenant=}``.
* **one poisoned client** (NaN/Inf-bearing state that would fold into the
  tenant view and stick — ``NaN + x = NaN`` survives every later merge of
  OTHER clients) — the **poisoned-state firewall**: a cheap finite-leaf
  check (:func:`check_poisoned`) runs before any snapshot reaches a slot,
  and an offending client is **quarantined** — its snapshot dropped, its
  future ingests refused (:class:`QuarantinedClientError`), one one-shot
  warning, ``serve.quarantined{tenant=}`` counted — while the tenant keeps
  folding every healthy client. The wire layer's per-leaf crc32
  (:mod:`metrics_tpu.serve.wire`, minor 1) is the in-flight half of the
  same firewall; this is the semantic half a *correctly transmitted* bad
  state needs.
* **a dead or hung node / worker** — :class:`Supervisor`: liveness over an
  :class:`~metrics_tpu.serve.tree.AggregationTree` via the heartbeats the
  traffic already implies (a parent tracks the **age of each child's last
  accepted ship**; children probe parent reachability), plus direct
  flush-worker liveness and last-flush age. :meth:`Supervisor.check`
  classifies into one-shot-warned conditions counted under
  ``health.checks{monitor=}`` / ``health.alerts{monitor=,kind=}`` (the
  :class:`~metrics_tpu.obs.health.HealthMonitor` pattern);
  :meth:`Supervisor.heal` restarts a dead flush worker in place and
  rebuilds a dead node — restoring the root from its
  :class:`~metrics_tpu.ft.CheckpointManager` checkpoint, re-registering
  tenants, and resetting the node's ship sequence so
  :meth:`~metrics_tpu.serve.tree.AggregatorNode._resume_seq` re-runs and
  the healed subtree's ships are not dropped as stale by the parent.

Everything here is **opt-in and off the hot path when off**: an
:class:`~metrics_tpu.serve.Aggregator` without ``resilience=`` does not
construct a firewall and pays nothing; the chaos harness
(:mod:`metrics_tpu.ft.faults` + ``tests/integrations/chaos_smoke.py``)
pins that with the firewall *on* and a seeded fault schedule, the root
``/query`` stays bitwise-equal to a flat oracle merge of exactly the
accepted snapshots. See ``docs/serving.md`` §"Self-healing".
"""
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from metrics_tpu.ft.retry import RetryPolicy, backoff_schedule
from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import observe as _obs_observe
from metrics_tpu.obs.registry import set_gauge as _obs_gauge
from metrics_tpu.serve.aggregator import ServeError

__all__ = [
    "CircuitOpenError",
    "ClientFirewall",
    "NodeDownError",
    "QuarantinedClientError",
    "ResilienceConfig",
    "Supervisor",
    "check_poisoned",
]


class CircuitOpenError(ServeError):
    """Client's ingest circuit is open; retry after :attr:`retry_after_s`."""

    def __init__(self, message: str, retry_after_s: float) -> None:
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class QuarantinedClientError(ServeError):
    """Client is quarantined for shipping poisoned state; operator action
    (``ClientFirewall.unquarantine``) required — time does not heal a bug."""


class NodeDownError(ServeError):
    """The aggregator behind this tree node is dead (killed, not stopped)."""


@dataclass(frozen=True)
class ResilienceConfig:
    """Policy for :class:`ClientFirewall` (pass to ``Aggregator(resilience=)``).

    Args:
        error_threshold: consecutive validation failures (wire corruption,
            schema mismatch, lying body) that open a client's circuit.
        probe_policy: the cooldown schedule between open and half-open —
            consumed through :func:`metrics_tpu.ft.retry.backoff_schedule`,
            so ``jitter="decorrelated"`` + a seed gives every client a
            distinct, reproducible probe schedule (no thundering probe
            herd, pinnable in tests).
        poison_strikes: poisoned snapshots (NaN/Inf leaves) before the
            client is quarantined. Default 1: a single NaN is never a
            transient — it is a client-side bug, and the firewall exists
            so that bug cannot stale the tenant.
        shed_watermark: ingest-queue fill fraction above which
            duplicate-watermark payloads are shed at the door (they would
            be dedup-dropped at fold anyway; under pressure the queue
            slots are the scarce resource). ``1.0`` disables shedding.
        max_tracked_clients: bound on the breaker/quarantine records one
            firewall keeps. Strikes for identities taken off an
            unvalidated wire header must not be a memory-exhaustion
            vector (a sender spraying unique spoofed client ids would
            otherwise grow the table one record per id); past the cap,
            NEW identities' strikes are counted under
            ``serve.firewall_untracked`` but not tracked — already-
            tracked offenders (the repeat clients breakers exist for)
            keep their records.
    """

    error_threshold: int = 3
    probe_policy: RetryPolicy = field(
        default_factory=lambda: RetryPolicy(
            backoff_s=0.5, max_backoff_s=30.0, jitter="decorrelated", jitter_seed=0
        )
    )
    poison_strikes: int = 1
    shed_watermark: float = 0.75
    max_tracked_clients: int = 10_000

    def __post_init__(self) -> None:
        if self.error_threshold < 1:
            raise ValueError(f"error_threshold must be >= 1, got {self.error_threshold}")
        if self.poison_strikes < 1:
            raise ValueError(f"poison_strikes must be >= 1, got {self.poison_strikes}")
        if not 0.0 < self.shed_watermark <= 1.0:
            raise ValueError(
                f"shed_watermark must be in (0, 1] (1.0 disables shedding), got {self.shed_watermark}"
            )
        if self.max_tracked_clients < 1:
            raise ValueError(
                f"max_tracked_clients must be >= 1, got {self.max_tracked_clients}"
            )


def check_poisoned(
    spec: List[Tuple[Tuple[str, ...], str]], leaves: List[np.ndarray]
) -> Optional[str]:
    """Cheap pre-fold poison check; returns a detail string or None.

    ``sum`` leaves must be fully finite (an Inf or NaN addend survives
    every later merge); ``min``/``max`` leaves may legitimately be ±Inf
    (their no-data identity) but never NaN (NaN wins/loses comparisons
    unpredictably and never washes out). Integer and sketch-count leaves
    cannot encode either. One vectorized pass over a ≤64KB payload —
    orders cheaper than the fold it protects.
    """
    for (path, red), leaf in zip(spec, leaves):
        if not np.issubdtype(leaf.dtype, np.floating):
            continue
        if red == "sum":
            if not bool(np.all(np.isfinite(leaf))):
                return f"sum-reduced leaf {'/'.join(path)} carries non-finite values"
        elif bool(np.any(np.isnan(leaf))):
            return f"{red}-reduced leaf {'/'.join(path)} carries NaN values"
    return None


class _Circuit:
    """Per-(tenant, client) breaker record. States: closed → open →
    half-open → closed (probe ok) or back to open (probe failed)."""

    __slots__ = ("errors", "state", "open_until", "delays", "poison", "quarantined")

    def __init__(self) -> None:
        self.errors = 0
        self.state = "closed"
        self.open_until = 0.0
        self.delays: Optional[Iterator[float]] = None
        self.poison = 0
        self.quarantined = False


class ClientFirewall:
    """Per-client circuit breakers + quarantine for one aggregator node.

    Constructed by :class:`~metrics_tpu.serve.Aggregator` when
    ``resilience=`` is given; all methods are thread-safe (ingest threads
    and the background flush worker both consult it).

    Args:
        config: the :class:`ResilienceConfig` policy.
        node: owning aggregator's name (warning/labels context).
        clock: monotonic time source (injectable for tests).
    """

    def __init__(
        self,
        config: ResilienceConfig,
        *,
        node: str = "",
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.config = config
        self._node = str(node)
        self._clock = clock
        self._lock = threading.Lock()
        self._circuits: Dict[Tuple[str, str], _Circuit] = {}
        self._warned: set = set()

    # -- admission -------------------------------------------------------

    def admit(self, tenant: str, client: str) -> None:
        """Gate one ingest attempt; raises :class:`QuarantinedClientError`
        or :class:`CircuitOpenError` when the client may not pass. An open
        circuit whose cooldown has elapsed admits exactly ONE half-open
        probe; concurrent attempts during the probe stay refused."""
        key = (str(tenant), str(client))
        with self._lock:
            circuit = self._circuits.get(key)
            if circuit is None:
                return
            if circuit.quarantined:
                if _obs_enabled():
                    _obs_inc("serve.quarantine_drops", tenant=key[0])
                raise QuarantinedClientError(
                    f"client {key[1]!r} of tenant {key[0]!r} is quarantined on"
                    f" aggregator {self._node!r} for shipping poisoned state;"
                    " fix the client and unquarantine() it — retrying will not help."
                )
            if circuit.state == "open":
                now = self._clock()
                if now >= circuit.open_until:
                    circuit.state = "half_open"  # this caller is the probe
                    return
                self._refuse_open(key, circuit.open_until - now)
            elif circuit.state == "half_open":
                # a probe is already in flight; its outcome decides
                self._refuse_open(key, self.config.probe_policy.backoff_s)

    def _refuse_open(self, key: Tuple[str, str], retry_after: float) -> None:
        if _obs_enabled():
            _obs_inc("serve.circuit_drops", tenant=key[0])
        raise CircuitOpenError(
            f"ingest circuit for client {key[1]!r} of tenant {key[0]!r} is open on"
            f" aggregator {self._node!r} after repeated invalid payloads;"
            f" retry in {retry_after:.2f}s",
            retry_after_s=retry_after,
        )

    # -- outcomes --------------------------------------------------------

    def record_ok(self, tenant: str, client: str) -> None:
        """A payload validated clean (accepted or dedup-dropped): reset the
        error streak; a half-open probe success closes the circuit."""
        key = (str(tenant), str(client))
        with self._lock:
            circuit = self._circuits.get(key)
            if circuit is None or circuit.quarantined:
                return
            circuit.errors = 0
            if circuit.state != "closed":
                circuit.state = "closed"
                circuit.delays = None  # a fresh incident gets a fresh schedule
                self._gauge_open_locked()

    def abandon_probe(self, tenant: str, client: str) -> None:
        """A half-open probe whose outcome will never be known (the
        payload was shed unjudged, hit queue backpressure, or died on an
        unrelated error). The circuit returns to ``open`` with its
        original expiry — already in the past — so the NEXT attempt
        becomes the probe; without this the circuit would sit in
        ``half_open`` forever, refusing a client nobody ever judged."""
        with self._lock:
            circuit = self._circuits.get((str(tenant), str(client)))
            if circuit is not None and circuit.state == "half_open":
                circuit.state = "open"

    def _tracked(self, key: Tuple[str, str]) -> Optional[_Circuit]:
        """Existing record, or a new one if under the tracking cap (must
        be called with the lock held). Past the cap, None: the strike is
        counted but a spoofed-identity flood cannot grow the table."""
        circuit = self._circuits.get(key)
        if circuit is None:
            if len(self._circuits) >= self.config.max_tracked_clients:
                if _obs_enabled():
                    _obs_inc("serve.firewall_untracked", tenant=key[0])
                return None
            circuit = self._circuits[key] = _Circuit()
        return circuit

    def record_error(self, tenant: str, client: str) -> None:
        """A validation failure attributed to this client. Opens the
        circuit at ``error_threshold`` consecutive failures (or instantly
        re-opens a failed half-open probe) with the next seeded-jitter
        cooldown."""
        key = (str(tenant), str(client))
        with self._lock:
            circuit = self._tracked(key)
            if circuit is None:
                return
            if circuit.quarantined:
                return
            circuit.errors += 1
            failed_probe = circuit.state == "half_open"
            if failed_probe or (
                circuit.state == "closed" and circuit.errors >= self.config.error_threshold
            ):
                delay = self._open_locked(key, circuit)
                errors = circuit.errors
                first = ("circuit", key) not in self._warned
                self._warned.add(("circuit", key))
            else:
                return
        if first:
            import warnings

            warnings.warn(
                f"aggregator {self._node!r} opened the ingest circuit for client"
                f" {key[1]!r} of tenant {key[0]!r} after {errors} consecutive"
                f" invalid payload(s); refusing for {delay:.2f}s, then admitting"
                " one half-open probe. Re-opens of this circuit are counted under"
                " serve.circuit_open without warning again.",
                stacklevel=3,
            )

    def _open_locked(self, key: Tuple[str, str], circuit: _Circuit) -> float:
        """Transition ``circuit`` to open with the next seeded-jitter
        cooldown (lock held); returns the cooldown drawn."""
        if circuit.delays is None:
            # the op label folds the client identity into the seed, so
            # every client's probe schedule is distinct AND reproducible
            circuit.delays = backoff_schedule(
                self.config.probe_policy, op=f"{self._node}:{key[0]}:{key[1]}"
            )
        delay = next(circuit.delays)
        circuit.state = "open"
        circuit.open_until = self._clock() + delay
        if _obs_enabled():
            _obs_inc("serve.circuit_open", tenant=key[0])
            self._gauge_open_locked()
        return delay

    def record_poison(self, tenant: str, client: str, detail: str) -> bool:
        """A structurally-valid snapshot carried poisoned (NaN/Inf) state.
        Returns True when this strike quarantined the client."""
        key = (str(tenant), str(client))
        with self._lock:
            circuit = self._tracked(key)
            if circuit is None:
                return False
            circuit.poison += 1
            if _obs_enabled():
                _obs_inc("serve.poisoned", tenant=key[0])
            if circuit.quarantined or circuit.poison < self.config.poison_strikes:
                if not circuit.quarantined and circuit.state == "half_open":
                    # the probe WAS judged and it failed (poisoned, just below
                    # the quarantine threshold): re-open like any failed probe,
                    # else the circuit would sit half_open refusing forever
                    self._open_locked(key, circuit)
                return circuit.quarantined
            circuit.quarantined = True
            first = ("quarantine", key) not in self._warned
            self._warned.add(("quarantine", key))
            if _obs_enabled():
                _obs_inc("serve.quarantined", tenant=key[0])
                self._gauge_open_locked()
        if first:
            import warnings

            warnings.warn(
                f"aggregator {self._node!r} QUARANTINED client {key[1]!r} of tenant"
                f" {key[0]!r}: {detail}. The snapshot was dropped (the tenant keeps"
                " folding its healthy clients), further ingests from this client are"
                " refused, and serve.quarantined counts the event. Quarantine does"
                " not expire — fix the client and call unquarantine().",
                stacklevel=3,
            )
        return True

    # -- operator surface ------------------------------------------------

    def is_quarantined(self, tenant: str, client: str) -> bool:
        circuit = self._circuits.get((str(tenant), str(client)))
        return circuit is not None and circuit.quarantined

    def unquarantine(self, tenant: str, client: str) -> bool:
        """Operator override: lift a quarantine (returns True if one was
        lifted). The error/poison counters restart from zero."""
        key = (str(tenant), str(client))
        with self._lock:
            circuit = self._circuits.get(key)
            if circuit is None or not circuit.quarantined:
                return False
            self._circuits[key] = _Circuit()
            self._warned.discard(("quarantine", key))
            self._gauge_open_locked()
        return True

    def status(self) -> Dict[str, List[str]]:
        """Snapshot for ``/healthz``: open circuits and quarantined clients
        as ``"tenant/client"`` strings."""
        with self._lock:
            return {
                "open_circuits": sorted(
                    f"{t}/{c}"
                    for (t, c), circuit in self._circuits.items()
                    if circuit.state != "closed" and not circuit.quarantined
                ),
                "quarantined": sorted(
                    f"{t}/{c}" for (t, c), circuit in self._circuits.items() if circuit.quarantined
                ),
            }

    def _gauge_open_locked(self) -> None:
        # labeled per node: several aggregators in one process (a tree)
        # must not clobber each other's current-state gauges — health
        # conditions aggregate across the series
        if _obs_enabled():
            _obs_gauge(
                "serve.circuits_open",
                float(sum(1 for c in self._circuits.values() if c.state != "closed" and not c.quarantined)),
                node=self._node,
            )
            _obs_gauge(
                "serve.clients_quarantined",
                float(sum(1 for c in self._circuits.values() if c.quarantined)),
                node=self._node,
            )


class Supervisor:
    """Liveness + supervision over an :class:`~metrics_tpu.serve.tree.AggregationTree`.

    Heartbeats are derived from the traffic itself — no extra RPCs: every
    accepted payload stamps its client slot, so a parent's view of a child
    node is "age of the last accepted ``node:<child>`` ship", and a child's
    view of its parent is :meth:`~metrics_tpu.serve.tree.AggregatorNode.parent_reachable`.
    Call :meth:`check` on the operator's cadence and :meth:`heal` when it
    reports findings (or unconditionally — healing a healthy tree is a
    no-op).

    Conditions:

    * ``dead_node`` — the node was hard-killed (its in-memory aggregator is
      gone; in production: the process died).
    * ``dead_worker`` — the node's background flush worker thread died
      (the silent-freeze failure: the queue fills, ``/metrics`` goes stale,
      nothing raises).
    * ``hung_flush`` — the worker is alive but no flush has completed
      within ``flush_hang_s`` (a wedged fold / device hang).
    * ``stale_child`` — a child node's last accepted ship is older than
      ``heartbeat_timeout_s`` (dead child, or a network partition — the
      signal is the same and so is the repair: the child's next cumulative
      ship).
    * ``parent_unreachable`` — the child-side probe of the uplink failed.

    :meth:`heal` repairs what it can locally: a dead worker is restarted in
    place (state is intact — the thread died, not the process); a dead node
    is rebuilt through :meth:`AggregationTree.revive` — fresh aggregator,
    tenants re-registered, the root restored from its latest checkpoint,
    and the node's ship sequence reset so ``_resume_seq`` re-derives it
    above the parent's recorded watermark (a healed subtree that restarted
    its sequence at 0 would have every ship dropped as stale — a silently
    frozen subtree, the exact failure supervision exists to end).
    ``stale_child``/``parent_unreachable`` have no local repair: they heal
    when the named peer is healed (possibly by another Supervisor).
    """

    _KINDS = ("dead_node", "dead_worker", "hung_flush", "stale_child", "parent_unreachable")

    def __init__(
        self,
        tree: Any,
        *,
        heartbeat_timeout_s: float = 5.0,
        flush_hang_s: Optional[float] = None,
        name: str = "supervisor",
        warn: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if heartbeat_timeout_s <= 0:
            raise ValueError(f"heartbeat_timeout_s must be positive, got {heartbeat_timeout_s}")
        if flush_hang_s is not None and flush_hang_s <= 0:
            raise ValueError(f"flush_hang_s must be positive (or None), got {flush_hang_s}")
        self.tree = tree
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.flush_hang_s = flush_hang_s
        self.name = str(name)
        self.warn = bool(warn)
        self._clock = clock
        self._warned_kinds: set = set()

    # ------------------------------------------------------------------

    def check(self) -> Dict[str, Any]:
        """Classify the tree's current state; returns
        ``{"healthy": bool, "findings": [{"kind", "node", "detail"}, ...]}``
        and counts ``health.checks{monitor=}`` /
        ``health.alerts{monitor=,kind=}`` (one-shot warn per kind)."""
        findings: List[Dict[str, str]] = []
        for node in self.tree.nodes:
            if node.is_dead:
                findings.append(
                    {
                        "kind": "dead_node",
                        "node": node.name,
                        "detail": f"node {node.name!r} is down (in-memory state lost); heal() rebuilds it",
                    }
                )
                continue
            agg = node.aggregator
            alive = agg.worker_alive()
            if alive is False:
                findings.append(
                    {
                        "kind": "dead_worker",
                        "node": node.name,
                        "detail": (
                            f"background flush worker of {node.name!r} died — the queue"
                            " fills and nothing folds; heal() restarts it in place"
                        ),
                    }
                )
            elif alive and self.flush_hang_s is not None:
                age = agg.last_flush_age_s()
                if age is not None and age > self.flush_hang_s:
                    findings.append(
                        {
                            "kind": "hung_flush",
                            "node": node.name,
                            "detail": (
                                f"{node.name!r}: worker alive but last completed flush was"
                                f" {age:.1f}s ago (> {self.flush_hang_s:.1f}s) — a wedged fold?"
                            ),
                        }
                    )
            for child_id, age in agg.client_ages().items():
                if child_id.startswith("node:") and age > self.heartbeat_timeout_s:
                    findings.append(
                        {
                            "kind": "stale_child",
                            "node": node.name,
                            "detail": (
                                f"{node.name!r} last accepted a ship from {child_id!r}"
                                f" {age:.1f}s ago (> {self.heartbeat_timeout_s:.1f}s):"
                                " the child is dead or partitioned; its next cumulative"
                                " ship repairs the view either way"
                            ),
                        }
                    )
            if node.parent is not None and not node.parent_reachable():
                findings.append(
                    {
                        "kind": "parent_unreachable",
                        "node": node.name,
                        "detail": f"{node.name!r} cannot reach its parent; ships are being dropped",
                    }
                )
        if _obs_enabled():
            _obs_inc("health.checks", monitor=self.name)
            for finding in findings:
                _obs_inc("health.alerts", monitor=self.name, kind=finding["kind"])
        if self.warn:
            for finding in findings:
                if finding["kind"] in self._warned_kinds:
                    continue
                self._warned_kinds.add(finding["kind"])
                import warnings

                warnings.warn(
                    f"Supervisor {self.name!r} [{finding['kind']}]: {finding['detail']}."
                    " Further findings of this kind are counted under health.alerts"
                    f"{{monitor={self.name}}} without warning again.",
                    stacklevel=2,
                )
        return {"healthy": not findings, "findings": findings}

    def heal(self) -> List[Dict[str, Any]]:
        """Repair every locally-repairable finding; returns the actions
        taken (``restart_worker`` / ``rebuild_node`` entries). Idempotent:
        a healthy tree yields no actions."""
        actions: List[Dict[str, Any]] = []
        for node in self.tree.nodes:
            if node.is_dead:
                t0 = time.perf_counter()
                manifest = self.tree.revive(node)
                if _obs_enabled():
                    _obs_inc("serve.heals", kind="rebuild_node")
                    # per-action repair latency: how long the fleet ran with
                    # this node dark — the churn headline /metrics renders
                    # next to serve.rebalance_ms (federated like any histogram)
                    _obs_observe(
                        "serve.heal_ms", (time.perf_counter() - t0) * 1000.0, kind="rebuild_node"
                    )
                actions.append(
                    {
                        "action": "rebuild_node",
                        "node": node.name,
                        "restored": manifest is not None,
                        # AOT-armed trees restore executables WITH state:
                        # how many fold programs the revive warmed before
                        # the node re-entered traffic (0 = no engine)
                        "warmed_programs": getattr(node, "last_warmup_programs", 0),
                    }
                )
            elif node.aggregator.worker_alive() is False:
                t0 = time.perf_counter()
                node.aggregator.start()
                if _obs_enabled():
                    _obs_inc("serve.heals", kind="restart_worker")
                    _obs_observe(
                        "serve.heal_ms", (time.perf_counter() - t0) * 1000.0, kind="restart_worker"
                    )
                actions.append({"action": "restart_worker", "node": node.name})
        return actions

    def reset_warnings(self) -> None:
        """Re-arm the one-shot warning per condition kind."""
        self._warned_kinds.clear()

    def __repr__(self) -> str:
        return (
            f"Supervisor(name={self.name!r}, heartbeat_timeout_s={self.heartbeat_timeout_s},"
            f" flush_hang_s={self.flush_hang_s})"
        )
