"""Time-travel metrics database at the serving root.

The aggregation tier answers "what is the cumulative value *now*"; a
fleet operator's actual question is "p99 AUROC over the last hour, per
tenant, and when did it regress?". Because every servable state merges
as an exact monoid (``sum`` / ``min`` / ``max`` / sketch — see
:mod:`metrics_tpu.serve.aggregator`), the root can retain **interval
snapshots** of its already-deduped merged state and answer ANY time
range by pure monoid algebra — no approximation beyond each sketch's
own pinned error bounds. :class:`MetricHistory` is that database:

* **Retention rings** — per tenant, a ladder of bounded levels
  (:class:`HistoryConfig.levels`): the finest ring holds one
  *cumulative* snapshot per cut cadence, and eviction from level *i*
  promotes into level *i+1* by keep-newest-per-coarse-bucket (the
  ``WindowedMetric`` ring discipline, with the
  ``MAX_RETIRED_TOMBSTONES`` bounding stance: every drop off the
  coarsest level is COUNTED under ``history.intervals_evicted``, never
  silent). Because snapshots are cumulative, keep-newest-per-bucket IS
  the exact monoid rollup — the 1m→1h→1d compaction is bitwise-equal to
  merging the raw fine intervals (pinned by
  ``tests/serve/test_history.py``).
* **Interval-delta algebra** — the delta of a cumulative snapshot pair
  is computable exactly for ``sum`` leaves (subtract) and for sketch
  states (count leaves subtract; the monotone ``minv``/``maxv``
  extremes carry the newer snapshot's value, which is exact under
  merge). Plain ``max``/``min`` metric states are a non-invertible
  monoid — a delta query over them REFUSES with
  :class:`DeltaUndefinedError` (loud, typed) rather than fabricating a
  number. The algebra satisfies ``delta(a,b) ⊕ delta(b,c) ==
  delta(a,c)`` bitwise for integer-valued leaves (the same class the
  fold-order invariance pins).
* **Range queries** — :meth:`MetricHistory.range_query` resolves
  ``start``/``end``(/``step``) against the retained rings and answers
  per-interval values WITH the streaming metrics' rigorous
  ``error_bound()``/``bounds()`` envelopes, in ``delta`` or
  ``cumulative`` mode (the ``/query`` HTTP surface's
  ``start``/``end``/``step``/``mode`` parameters). A range that asks
  for time the rings have already evicted raises
  :class:`HistoryRetentionError` — bounded history answers exactly or
  not at all.
* **Root-evaluated alert rules** — :class:`AlertRule` (threshold) and
  :class:`DriftRule` (:class:`~metrics_tpu.streaming.DriftMonitor` over
  the interval delta) run at every cut, edge-triggered through the
  one-shot-warn + obs counter machinery
  (``history.alerts{rule=,tenant=}``), surfaced on ``/healthz/ready``
  and ``/metrics``.
* **Generation fencing of historical reads** — every interval records
  the generation it was cut under (the multi-region ``(generation,
  seq)`` watermark of PR 14). A promoted root refuses a DELTA spanning
  a generation boundary with :class:`GenerationFencedRangeError`
  (subtracting across a failover would difference two histories);
  cumulative reads and within-generation deltas stay exact, and a
  healed peer's cumulative re-ship repairs the global range view
  bitwise from the next cut on.

Durability rides the aggregator's existing checkpoint: the rings
serialize into :meth:`Aggregator.save`'s registry state (positional
``h000000`` slots + manifest metadata) and :meth:`Aggregator.restore`
rebuilds them bitwise — a SIGKILLed root resumes its retention mid-ring
(``tests/integrations/history_smoke.py``).

Disabled mode is free: an aggregator constructed without ``history=``
performs ZERO new work on the ingest/fold path (one ``is None`` check
per flush; the jitted fold programs are untouched, so the HLO
byte-identity pin holds).
"""
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import observe as _obs_observe
from metrics_tpu.obs.registry import set_gauge as _obs_gauge
from metrics_tpu.serve.aggregator import ServeError, _jsonable, _tree_set
from metrics_tpu.streaming.sketches import delta_envelope_leaf

__all__ = [
    "AlertRule",
    "DeltaUndefinedError",
    "DriftRule",
    "GenerationFencedRangeError",
    "HistoryConfig",
    "HistoryRetentionError",
    "IntervalSnapshot",
    "MetricHistory",
    "delta_leaves",
    "merge_delta_leaves",
]


class HistoryError(ServeError):
    """Base class for history-tier errors."""


class DeltaUndefinedError(HistoryError):
    """A delta (interval) query touched a state whose reduction is a
    non-invertible monoid: plain ``max``/``min`` metric states know only
    the running extreme, so the extreme *within* an interval is not
    recoverable from two cumulative snapshots. Refused loudly — a
    fabricated number here would be silently wrong, the one failure mode
    the exact-monoid contract exists to prevent. Sketch-internal
    ``minv``/``maxv`` leaves are NOT affected (they are cumulative
    envelope bounds, carried exactly); query ``mode=cumulative`` or
    re-model the metric as a sketch to get interval behavior."""


class HistoryRetentionError(HistoryError):
    """The requested range reaches before the earliest retained interval
    AND older intervals have already been evicted (or no interval has
    been cut at all): bounded history answers exactly or not at all.
    The eviction horizon is visible under ``history.intervals_evicted``
    and in every range answer's ``evicted`` count."""


class GenerationFencedRangeError(HistoryError):
    """A DELTA range query spans a generation boundary: the intervals on
    either side were cut under different promoted roots (a multi-region
    failover), and differencing across the boundary would subtract two
    histories from each other. Counted under
    ``history.fenced_range_queries`` and answered 409 on the HTTP
    surface. Cumulative reads of either side stay exact — split the
    range at the boundary, or query ``mode=cumulative``."""


class HistoryConfig:
    """Retention + alerting policy for a :class:`MetricHistory`.

    Args:
        cut_every_s: cadence at which :meth:`MetricHistory.maybe_cut`
            (called from every :meth:`Aggregator.flush`) cuts a new
            interval snapshot from each tenant's merged state.
        levels: the compaction ladder, finest first, as ``(span_s,
            capacity)`` pairs: level 0 retains ``capacity`` raw cuts;
            eviction from level *i* promotes the evicted snapshot into
            level *i+1*'s ``floor(t / span_s)`` bucket keeping the
            newest cumulative per bucket (the exact monoid rollup);
            eviction off the LAST level is counted
            (``history.intervals_evicted``) and advances the retention
            horizon. The default is a 1m→1h→1d ladder: 120 minutes of
            minutes, 72 hours of hours, 30 days of days.
        rules: :class:`AlertRule` / :class:`DriftRule` instances
            evaluated at every cut (see :meth:`MetricHistory.cut`).
    """

    def __init__(
        self,
        cut_every_s: float = 60.0,
        levels: Sequence[Tuple[float, int]] = ((60.0, 120), (3600.0, 72), (86400.0, 30)),
        rules: Sequence[Any] = (),
    ) -> None:
        self.cut_every_s = float(cut_every_s)
        if self.cut_every_s <= 0:
            raise ValueError(f"cut_every_s must be > 0, got {cut_every_s}")
        self.levels: Tuple[Tuple[float, int], ...] = tuple(
            (float(span), int(cap)) for span, cap in levels
        )
        if not self.levels:
            raise ValueError("levels must name at least one (span_s, capacity) ring")
        for span, cap in self.levels:
            if span <= 0 or cap < 1:
                raise ValueError(
                    f"every history level needs span_s > 0 and capacity >= 1, got {(span, cap)}"
                )
        spans = [span for span, _ in self.levels]
        if spans != sorted(spans) or len(set(spans)) != len(spans):
            raise ValueError(
                f"history level spans must be strictly ascending (finest first), got {spans}"
            )
        self.rules: Tuple[Any, ...] = tuple(rules)
        names = [(r.tenant, r.name) for r in self.rules]
        if len(names) != len(set(names)):
            raise ValueError(f"alert rule names must be unique per tenant, got {names}")


class IntervalSnapshot:
    """One retained interval: the tenant's CUMULATIVE merged state at cut
    time, spec-ordered exactly like ``_Tenant.merged_leaves``. ``index``
    is the tenant-monotonic cut counter (survives restore), ``t`` the
    wall-clock cut time, ``generation`` the multi-region generation the
    root held when cutting — the fence historical delta reads honor."""

    __slots__ = ("index", "t", "generation", "clients", "folded", "leaves", "consensus")

    def __init__(
        self,
        index: int,
        t: float,
        generation: int,
        clients: int,
        folded: int,
        leaves: List[np.ndarray],
        consensus: List[np.ndarray],
    ) -> None:
        self.index = int(index)
        self.t = float(t)
        self.generation = int(generation)
        self.clients = int(clients)
        self.folded = int(folded)
        self.leaves = leaves
        self.consensus = consensus

    def meta(self) -> Dict[str, Any]:
        return {
            "index": self.index,
            "t": self.t,
            "generation": self.generation,
            "clients": self.clients,
        }


# ----------------------------------------------------------------------
# Interval-delta algebra (module-level, property-tested directly)
# ----------------------------------------------------------------------


_SKETCH_LEAF_PREFIX = "__sketch_leaf_"


def _is_sketch_extreme(path: Tuple[str, ...], red: str) -> bool:
    """A sketch-internal min/max leaf that is a MONOTONE cumulative
    envelope bound (a quantile sketch's ``minv``/``maxv``), not a
    windowed extreme — carried, never subtracted, and exact under delta
    merge (``min(newer_b, newer_c) == newer_c`` because cumulative
    extremes only tighten).

    Not every sketch min/max leaf qualifies: HLL max-registers are the
    canonical counterexample (their carry would silently answer "uniques
    ever" to a "uniques this interval" query), so the judgment is
    delegated to the sketch registry's per-class
    ``_delta_envelope_leaves`` declarations via
    :func:`metrics_tpu.streaming.sketches.delta_envelope_leaf` — an
    undeclared min/max leaf falls through to the refusing arm."""
    return (
        red in ("min", "max")
        and path[-1].startswith(_SKETCH_LEAF_PREFIX)
        and delta_envelope_leaf(path[-1][len(_SKETCH_LEAF_PREFIX):])
    )


def delta_leaves(
    spec: Sequence[Tuple[Tuple[str, ...], str]],
    newer: Sequence[np.ndarray],
    older: Sequence[np.ndarray],
) -> List[np.ndarray]:
    """The exact interval delta of two CUMULATIVE spec-ordered leaf
    lists (``newer`` at the interval end, ``older`` at its start).

    ``sum`` leaves subtract (bitwise-exact for the integer leaves the
    fold-order invariance pins — sketch counts, ``__update_count``,
    integer sums); sketch ``minv``/``maxv`` extremes carry the newer
    snapshot's value (see :func:`_is_sketch_extreme`); a plain
    ``max``/``min`` state raises :class:`DeltaUndefinedError`.
    """
    out: List[np.ndarray] = []
    for (path, red), new, old in zip(spec, newer, older):
        if red == "sum":
            out.append(np.subtract(new, old))
        elif _is_sketch_extreme(path, red):
            out.append(np.array(new, copy=True))
        else:
            raise DeltaUndefinedError(
                f"state leaf {'/'.join(path)} has reduction {red!r}: a"
                " max/min monoid is not invertible, so the interval delta of"
                " two cumulative snapshots is undefined for it (for an HLL"
                " register array the carry would answer 'uniques ever', not"
                " 'uniques this interval'). Query mode=cumulative, or use a"
                " windowed metric instance (metrics_tpu.streaming windows)"
                " for per-interval values."
            )
    return out


def merge_delta_leaves(
    spec: Sequence[Tuple[Tuple[str, ...], str]],
    earlier: Sequence[np.ndarray],
    later: Sequence[np.ndarray],
) -> List[np.ndarray]:
    """Merge two ADJACENT interval deltas (``earlier`` then ``later``)
    into the delta of the concatenated interval: ``merge(delta(a,b),
    delta(b,c)) == delta(a,c)`` bitwise for integer leaves — the
    property test's subject. ``sum`` leaves add; sketch extremes keep
    the LATER interval's carried value (= the newer cumulative bound,
    exactly what ``delta(a,c)`` carries)."""
    out: List[np.ndarray] = []
    for (path, red), a, b in zip(spec, earlier, later):
        if red == "sum":
            out.append(np.add(a, b))
        elif _is_sketch_extreme(path, red):
            out.append(np.array(b, copy=True))
        else:
            raise DeltaUndefinedError(
                f"state leaf {'/'.join(path)} has reduction {red!r}: interval"
                " deltas are undefined for plain max/min states"
            )
    return out


# ----------------------------------------------------------------------
# Alert rules
# ----------------------------------------------------------------------


class AlertRule:
    """Threshold rule evaluated at the root on every interval cut.

    Fires when the named metric's computed value crosses ``above`` /
    ``below`` (inclusive of neither). ``on="delta"`` (default) evaluates
    the metric over the just-cut interval's delta — the "did it regress
    THIS minute" question; ``on="cumulative"`` evaluates the running
    value. Firing is EDGE-TRIGGERED: the transition into violation
    counts ``history.alerts{rule=,tenant=}`` once and emits one
    ``rank_zero_warn``; a rule that stays in violation across many cuts
    fires exactly once until it recovers and re-arms (the
    ``HealthMonitor`` one-shot-warn discipline).

    Args:
        name: rule identity (the ``rule=`` obs label; unique per tenant).
        tenant: tenant the rule watches.
        metric: member name inside the tenant's collection.
        above / below: fire when value > above, or value < below (at
            least one required).
        on: ``"delta"`` or ``"cumulative"``.
    """

    def __init__(
        self,
        name: str,
        tenant: str,
        metric: str,
        *,
        above: Optional[float] = None,
        below: Optional[float] = None,
        on: str = "delta",
    ) -> None:
        if above is None and below is None:
            raise ValueError(f"alert rule {name!r} needs at least one of above=/below=")
        if on not in ("delta", "cumulative"):
            raise ValueError(f"alert rule {name!r}: on must be 'delta' or 'cumulative', got {on!r}")
        self.name = str(name)
        self.tenant = str(tenant)
        self.metric = str(metric)
        self.above = None if above is None else float(above)
        self.below = None if below is None else float(below)
        self.on = on

    def check(self, value: Any, metric: Any) -> Optional[str]:
        """Violation detail string, or None when healthy."""
        arr = np.asarray(value)
        if arr.ndim != 0 or not np.issubdtype(arr.dtype, np.number):
            return None  # structured values have no scalar threshold
        v = float(arr)
        if self.above is not None and v > self.above:
            return f"{self.metric}={v:g} above threshold {self.above:g} ({self.on})"
        if self.below is not None and v < self.below:
            return f"{self.metric}={v:g} below threshold {self.below:g} ({self.on})"
        return None


class DriftRule:
    """Distribution-drift rule: a
    :class:`~metrics_tpu.streaming.DriftMonitor` (PSI / KL / JS against
    a frozen reference sketch) evaluated over each cut interval's state.
    Same edge-triggered firing discipline as :class:`AlertRule`.

    Args:
        name / tenant / metric: as :class:`AlertRule` — ``metric`` must
            be a sketch-backed member (the monitor extracts its sketch).
        reference: the frozen reference sketch (or sketch-backed metric).
        psi_threshold / kl_threshold / js_threshold: forwarded to
            :class:`~metrics_tpu.streaming.DriftMonitor` (at least one).
        on: ``"delta"`` (drift of the interval's own traffic) or
            ``"cumulative"``.
    """

    def __init__(
        self,
        name: str,
        tenant: str,
        metric: str,
        reference: Any,
        *,
        psi_threshold: Optional[float] = 0.2,
        kl_threshold: Optional[float] = None,
        js_threshold: Optional[float] = None,
        on: str = "delta",
    ) -> None:
        from metrics_tpu.streaming.drift import DriftMonitor

        if on not in ("delta", "cumulative"):
            raise ValueError(f"drift rule {name!r}: on must be 'delta' or 'cumulative', got {on!r}")
        self.name = str(name)
        self.tenant = str(tenant)
        self.metric = str(metric)
        self.on = on
        # warn=False: the history layer owns the one-shot warning (edge-
        # triggered per rule), the monitor just computes the divergences
        self._monitor = DriftMonitor(
            reference,
            psi_threshold=psi_threshold,
            kl_threshold=kl_threshold,
            js_threshold=js_threshold,
            name=self.name,
            warn=False,
        )

    def check(self, value: Any, metric: Any) -> Optional[str]:
        if metric is None:
            return None
        report = self._monitor.check(metric)
        if not report.get("alert"):
            return None
        detail = ", ".join(
            f"{k}={report[k]:.4f}" for k in ("psi", "kl", "js") if report.get(k) is not None
        )
        return f"{self.metric} drifted vs reference ({detail}, {self.on})"


# ----------------------------------------------------------------------
# Per-tenant retention rings
# ----------------------------------------------------------------------


class _TenantHistory:
    """One tenant's retention ladder. Level 0 is an append-ordered list
    of raw cuts; each coarser level keys buckets ``floor(t / span)`` to
    the newest cumulative snapshot promoted into them (dict insertion
    order == promotion order == chronological, so eviction pops the
    oldest bucket). All mutation happens under ``MetricHistory._lock``.
    """

    __slots__ = ("tenant_id", "levels", "rings", "next_index", "evicted", "last_evicted_t")

    def __init__(self, tenant_id: str, levels: Tuple[Tuple[float, int], ...]) -> None:
        self.tenant_id = tenant_id
        self.levels = levels
        # rings[0]: List[IntervalSnapshot]; rings[i>0]: Dict[int, IntervalSnapshot]
        self.rings: List[Any] = [[]] + [dict() for _ in levels[1:]]
        self.next_index = 0
        self.evicted = 0
        self.last_evicted_t: Optional[float] = None

    def append(self, snap: IntervalSnapshot) -> Tuple[int, int]:
        """Insert a fresh cut; returns (rollups performed, evictions)."""
        self.rings[0].append(snap)
        rollups = evictions = 0
        level = 0
        overflow: List[IntervalSnapshot] = []
        while level < len(self.levels):
            cap = self.levels[level][1]
            ring = self.rings[level]
            for promoted in overflow:
                rollups += 1
                self._insert(level, promoted)
            overflow = []
            if level == 0:
                while len(ring) > cap:
                    overflow.append(ring.pop(0))
            else:
                while len(ring) > cap:
                    oldest = next(iter(ring))
                    overflow.append(ring.pop(oldest))
            level += 1
        for dropped in overflow:  # off the coarsest level: counted, never silent
            evictions += 1
            self.evicted += 1
            t = dropped.t
            if self.last_evicted_t is None or t > self.last_evicted_t:
                self.last_evicted_t = t
        return rollups, evictions

    def _insert(self, level: int, snap: IntervalSnapshot) -> None:
        """Keep-newest-cumulative-per-bucket: the exact monoid rollup
        (a cumulative snapshot already IS the merge of everything before
        it, so the newest per bucket equals merging the bucket's raw
        intervals bitwise)."""
        span = self.levels[level][0]
        bucket = int(snap.t // span)
        held = self.rings[level].get(bucket)
        if held is None or (snap.t, snap.index) >= (held.t, held.index):
            self.rings[level][bucket] = snap

    def restore_insert(self, level: int, snap: IntervalSnapshot) -> None:
        """Checkpoint replay: place a snapshot directly into its recorded
        level, bypassing promotion (the ladder shape is restored as
        saved, not re-derived)."""
        if level == 0:
            self.rings[0].append(snap)
        else:
            self._insert(level, snap)

    def retained(self) -> List[Tuple[int, IntervalSnapshot]]:
        """Every retained ``(level, snapshot)``, oldest first. Promotion
        MOVES a snapshot between levels (never copies), so the list is
        duplicate-free by construction."""
        out: List[Tuple[int, IntervalSnapshot]] = []
        for level, ring in enumerate(self.rings):
            snaps = ring if level == 0 else ring.values()
            out.extend((level, s) for s in snaps)
        out.sort(key=lambda pair: (pair[1].t, pair[1].index))
        return out

    def newest(self) -> Optional[IntervalSnapshot]:
        pairs = self.retained()
        return pairs[-1][1] if pairs else None

    def snapshot_at(self, t: float) -> Optional[IntervalSnapshot]:
        """The newest retained snapshot cut at or before ``t`` (the
        cumulative state AS OF ``t``), or None when history starts
        after ``t``."""
        best: Optional[IntervalSnapshot] = None
        for _, snap in self.retained():
            if snap.t <= t and (best is None or (snap.t, snap.index) > (best.t, best.index)):
                best = snap
        return best


# ----------------------------------------------------------------------
# The database
# ----------------------------------------------------------------------


class MetricHistory:
    """Per-tenant time-travel store living inside one
    :class:`~metrics_tpu.serve.Aggregator` (construct the aggregator
    with ``history=HistoryConfig(...)`` — or ``history=True`` for the
    defaults — and every flush cadence-cuts automatically; see the
    module docstring for the full design).

    Example::

        agg = Aggregator("root", history=HistoryConfig(
            cut_every_s=60.0,
            rules=[AlertRule("seen-stall", "search", "seen", below=1.0)],
        ))
        agg.register_tenant("search", factory)
        ...
        agg.history_query("search", start=t0, end=t1, step=60.0)
    """

    def __init__(self, config: HistoryConfig, node: str = "?", generation: int = 0) -> None:
        self.config = config
        self.node = str(node)
        # the multi-region generation new cuts are stamped with; the
        # Region wiring advances it on set_generation()/promotion
        self.generation = int(generation)
        self._tenants: Dict[str, _TenantHistory] = {}
        self._last_cut_s: Optional[float] = None
        # (tenant, rule name) -> detail while firing; edge-trigger state
        self._active: Dict[Tuple[str, str], str] = {}
        self._warned_rules: set = set()
        # post-cut observers (the experiment DecisionEngine attaches
        # here): called once per cut() AFTER every tenant's interval has
        # been retained, with (history, aggregator). Hook errors degrade
        # to a one-shot warning — a decision bug must never block cuts.
        self._cut_hooks: List[Callable[["MetricHistory", Any], None]] = []
        self._warned_hooks: set = set()
        import threading

        self._lock = threading.Lock()

    # -- cutting ---------------------------------------------------------

    def maybe_cut(self, aggregator: Any) -> int:
        """Cadence gate for the flush hook: cut when ``cut_every_s`` has
        elapsed since the last cut (first flush arms the clock without
        cutting — an empty just-started node has nothing to retain).
        Returns intervals cut (0 when the cadence has not elapsed)."""
        now = time.time()
        if self._last_cut_s is None:
            self._last_cut_s = now
            return 0
        if now - self._last_cut_s < self.config.cut_every_s:
            return 0
        return self.cut(aggregator, now=now)

    def cut(self, aggregator: Any, now: Optional[float] = None) -> int:
        """Cut one interval snapshot per tenant from the aggregator's
        merged (already-deduped, already-folded) state; evaluate alert
        rules on the fresh interval. Safe inside the flush lock — errors
        in one tenant's cut or rules never abort the others (the flush
        loop's one-bad-tenant stance)."""
        t0 = time.perf_counter()
        now = time.time() if now is None else float(now)
        self._last_cut_s = now
        armed = _obs_enabled()
        cuts = 0
        for tenant_id in aggregator.tenants():
            tenant = aggregator._tenant(tenant_id)
            with tenant.view_lock:
                if tenant.merged_leaves is None:
                    continue  # nothing folded yet: no interval to retain
                leaves = [np.array(leaf, copy=True) for leaf in tenant.merged_leaves]
            # consensus leaves are byte-identical across clients by the fold
            # contract; capture from any live slot, template when empty
            with tenant.lock:
                slot = next(iter(tenant.clients.values()), None)
                consensus = [
                    np.array(leaf, copy=True)
                    for leaf in (slot.consensus if slot is not None else tenant.template_consensus)
                ]
                clients = len(tenant.clients)
            folded = tenant.folded_payloads
            with self._lock:
                th = self._tenants.get(tenant_id)
                if th is None:
                    th = self._tenants[tenant_id] = _TenantHistory(tenant_id, self.config.levels)
                prev = th.newest()
                snap = IntervalSnapshot(
                    th.next_index, now, self.generation, clients, folded, leaves, consensus,
                )
                th.next_index += 1
                rollups, evictions = th.append(snap)
                retained = len(th.retained())
            cuts += 1
            if armed:
                _obs_inc("history.cuts", tenant=tenant_id)
                _obs_gauge("history.intervals", float(retained), tenant=tenant_id)
                if rollups:
                    _obs_inc("history.rollups", float(rollups), tenant=tenant_id)
                if evictions:
                    _obs_inc("history.intervals_evicted", float(evictions), tenant=tenant_id)
            self._evaluate_rules(tenant, prev, snap)
        if cuts:
            for hook in tuple(self._cut_hooks):
                try:
                    hook(self, aggregator)
                except Exception as err:  # noqa: BLE001 — observers must not kill cuts
                    key = getattr(hook, "__qualname__", repr(hook))
                    if key not in self._warned_hooks:
                        self._warned_hooks.add(key)
                        warnings.warn(
                            f"history cut hook {key} failed:"
                            f" {type(err).__name__}: {err}", stacklevel=2,
                        )
        if armed and cuts:
            _obs_observe("history.cut_ms", (time.perf_counter() - t0) * 1000.0)
        return cuts

    def add_cut_hook(self, hook: Callable[["MetricHistory", Any], None]) -> None:
        """Attach a post-cut observer ``hook(history, aggregator)`` —
        invoked once per :meth:`cut` after all tenants' intervals land
        (the :class:`~metrics_tpu.experiment.DecisionEngine` seam)."""
        if not callable(hook):
            raise ValueError("cut hook must be callable")
        self._cut_hooks.append(hook)

    # -- alert evaluation ------------------------------------------------

    def _evaluate_rules(self, tenant: Any, prev: Optional[IntervalSnapshot],
                        snap: IntervalSnapshot) -> None:
        rules = [r for r in self.config.rules if r.tenant == tenant.tenant_id]
        if not rules:
            return
        for rule in rules:
            try:
                detail = self._check_rule(tenant, rule, prev, snap)
            except DeltaUndefinedError as err:
                # a delta rule over a non-invertible state is a CONFIG
                # error: warn once per rule, never abort the flush
                key = (rule.tenant, rule.name)
                if key not in self._warned_rules:
                    self._warned_rules.add(key)
                    warnings.warn(
                        f"history alert rule {rule.name!r} (tenant {rule.tenant!r})"
                        f" cannot evaluate: {err}", stacklevel=2,
                    )
                continue
            except Exception as err:  # noqa: BLE001 — rule errors must not kill flushes
                key = (rule.tenant, rule.name)
                if key not in self._warned_rules:
                    self._warned_rules.add(key)
                    warnings.warn(
                        f"history alert rule {rule.name!r} (tenant {rule.tenant!r})"
                        f" failed: {type(err).__name__}: {err}", stacklevel=2,
                    )
                continue
            self._transition(rule, detail)

    def _check_rule(self, tenant: Any, rule: Any, prev: Optional[IntervalSnapshot],
                    snap: IntervalSnapshot) -> Optional[str]:
        if rule.on == "delta":
            if prev is None or prev.generation != snap.generation:
                return None  # no fenceable baseline: the interval is undefined
            leaves = delta_leaves(tenant.spec, snap.leaves, prev.leaves)
        else:
            leaves = snap.leaves
        def probe(view: Any) -> Optional[str]:
            computed = view.compute()
            if rule.metric not in computed:
                return None
            return rule.check(computed[rule.metric], dict(view.items()).get(rule.metric))
        return self._with_loaded(tenant, leaves, snap.consensus, probe)

    def _transition(self, rule: Any, detail: Optional[str]) -> None:
        """Edge-triggered firing through the obs + one-shot-warn
        machinery: healthy→firing counts once and warns once per rule;
        firing→healthy re-arms (and clears the active gauge)."""
        key = (rule.tenant, rule.name)
        was_active = key in self._active
        if detail is not None:
            self._active[key] = detail
            if not was_active:
                if _obs_enabled():
                    _obs_inc("history.alerts", rule=rule.name, tenant=rule.tenant)
                    _obs_gauge("history.alert_active", 1.0, rule=rule.name, tenant=rule.tenant)
                if key not in self._warned_rules:
                    self._warned_rules.add(key)
                    from metrics_tpu.utilities.prints import rank_zero_warn

                    rank_zero_warn(
                        f"history alert {rule.name!r} FIRING for tenant"
                        f" {rule.tenant!r} on node {self.node!r}: {detail}"
                        " (counted under history.alerts; edge-triggered — warns"
                        " once until the rule recovers)"
                    )
        elif was_active:
            del self._active[key]
            if _obs_enabled():
                _obs_gauge("history.alert_active", 0.0, rule=rule.name, tenant=rule.tenant)

    def active_alerts(self) -> List[Dict[str, str]]:
        """Currently-firing rules (the ``/healthz/ready`` reasons feed)."""
        with self._lock:
            return [
                {"rule": name, "tenant": tenant, "detail": detail}
                for (tenant, name), detail in sorted(self._active.items())
            ]

    def reset_warnings(self) -> None:
        """Re-arm every rule's one-shot warning (test hook, mirroring
        :meth:`~metrics_tpu.obs.HealthMonitor.reset_warnings`)."""
        self._warned_rules.clear()

    # -- range queries ---------------------------------------------------

    def range_query(
        self,
        aggregator: Any,
        tenant_id: str,
        start: float,
        end: float,
        *,
        step: Optional[float] = None,
        mode: str = "delta",
    ) -> Dict[str, Any]:
        """Answer ``[start, end]`` from the retained rings.

        ``mode="cumulative"`` returns one point per tick: the merged
        state AS OF that time (the newest retained snapshot at or before
        it). ``mode="delta"`` returns one interval per consecutive tick
        pair: the exact difference of the two resolved cumulative
        snapshots (:func:`delta_leaves`). Every entry carries the
        computed values WITH ``bounds``/``error_bound`` envelopes where
        the metric documents them. Without ``step`` the whole range is
        one interval (or two points).

        Raises :class:`HistoryRetentionError` when the range reaches
        past the eviction horizon, :class:`DeltaUndefinedError` for a
        delta over plain max/min states, and
        :class:`GenerationFencedRangeError` for a delta spanning a
        generation boundary.
        """
        t0 = time.perf_counter()
        start, end = float(start), float(end)
        if end < start:
            raise ValueError(f"range end {end} precedes start {start}")
        if mode not in ("delta", "cumulative"):
            raise ValueError(f"mode must be 'delta' or 'cumulative', got {mode!r}")
        if step is not None and float(step) <= 0:
            raise ValueError(f"step must be > 0, got {step}")
        tenant = aggregator._tenant(tenant_id)
        with self._lock:
            th = self._tenants.get(tenant_id)
            if th is None:
                raise HistoryRetentionError(
                    f"tenant {tenant_id!r} has no retained history on node"
                    f" {self.node!r}: no interval has been cut yet (history cuts"
                    f" every {self.config.cut_every_s}s of flushed traffic)"
                )
            pairs = th.retained()
            evicted, last_evicted_t = th.evicted, th.last_evicted_t
        if _obs_enabled():
            _obs_inc("history.range_queries", tenant=tenant_id, mode=mode)

        def resolve(t: float) -> Optional[IntervalSnapshot]:
            best: Optional[IntervalSnapshot] = None
            for _, snap in pairs:
                if snap.t <= t and (best is None or (snap.t, snap.index) > (best.t, best.index)):
                    best = snap
            if best is None and evicted:
                raise HistoryRetentionError(
                    f"tenant {tenant_id!r}: time {t} precedes the earliest retained"
                    f" interval and {evicted} older interval(s) were already evicted"
                    f" (horizon ~{last_evicted_t}); bounded history answers exactly"
                    " or not at all — widen the retention ladder"
                    " (HistoryConfig.levels) to keep more"
                )
            return best

        ticks = [start]
        if step is not None:
            tick = start + float(step)
            while tick < end - 1e-9:
                ticks.append(tick)
                tick += float(step)
        ticks.append(end)

        out: Dict[str, Any] = {
            "tenant": tenant.tenant_id,
            "mode": mode,
            "start": start,
            "end": end,
            "step": step,
            "generation": self.generation,
            "retained": len(pairs),
            "evicted": evicted,
        }
        if mode == "cumulative":
            points: List[Dict[str, Any]] = []
            for tick in ticks:
                snap = resolve(tick)
                if snap is None:
                    points.append({"t": tick, "snapshot": None, "values": None})
                    continue
                values = self._with_loaded(tenant, snap.leaves, snap.consensus, _values_of)
                points.append({"t": tick, "snapshot": snap.meta(), "values": values})
            out["points"] = points
        else:
            intervals: List[Dict[str, Any]] = []
            for a, b in zip(ticks[:-1], ticks[1:]):
                base, head = resolve(a), resolve(b)
                entry: Dict[str, Any] = {"start": a, "end": b}
                if head is None:
                    # history starts after this tick pair and nothing was
                    # ever evicted: the interval is exactly empty
                    entry.update(snapshot=None, baseline=None, values=None)
                    intervals.append(entry)
                    continue
                if base is not None and base.generation != head.generation:
                    if _obs_enabled():
                        _obs_inc("history.fenced_range_queries", tenant=tenant_id)
                    raise GenerationFencedRangeError(
                        f"tenant {tenant_id!r}: delta [{a}, {b}] spans a generation"
                        f" boundary ({base.generation} -> {head.generation}) — the"
                        " two sides were cut under different promoted roots and"
                        " differencing across a failover would subtract two"
                        " histories. Split the range at the boundary or query"
                        " mode=cumulative."
                    )
                older = base.leaves if base is not None else tenant.template_leaves
                leaves = delta_leaves(tenant.spec, head.leaves, older)
                values = self._with_loaded(tenant, leaves, head.consensus, _values_of)
                for name, extra in self._topk_churn(tenant, base, head).items():
                    if name in values:
                        values[name].update(extra)
                entry.update(
                    snapshot=head.meta(),
                    baseline=None if base is None else base.meta(),
                    values=values,
                )
                intervals.append(entry)
            out["intervals"] = intervals
        if _obs_enabled():
            _obs_observe("history.range_query_ms", (time.perf_counter() - t0) * 1000.0)
        return out

    def _topk_churn(self, tenant: Any, base: Optional[IntervalSnapshot],
                    head: Optional[IntervalSnapshot]) -> Dict[str, Dict[str, Any]]:
        """Per-member top-k churn enrichment for one delta interval:
        which ids ``entered``/``exited``/``stayed`` in the CERTIFIED
        top-k between the interval's baseline and head cumulative
        snapshots (:meth:`~metrics_tpu.streaming.StreamingTopK.churn`'s
        semantics over retained rings). An ambiguous envelope overlap
        refuses THAT member (``churn_undefined``), never the whole range
        answer; a missing baseline churns against the empty set (history
        starts inside the asked-for interval and nothing was evicted)."""
        from metrics_tpu.streaming.metrics import ChurnUndefinedError, StreamingTopK

        names = [n for n, m in dict(tenant.view.items()).items()
                 if isinstance(m, StreamingTopK)]
        if not names or head is None:
            return {}

        def grab(view: Any) -> Dict[str, Any]:
            out: Dict[str, Any] = {}
            for name in names:
                member = dict(view.items())[name]
                try:
                    out[name] = {int(i) for i in member.certified_topk()}
                except ChurnUndefinedError as err:
                    out[name] = err
            return out

        old = ({n: set() for n in names} if base is None
               else self._with_loaded(tenant, base.leaves, base.consensus, grab))
        new = self._with_loaded(tenant, head.leaves, head.consensus, grab)
        enriched: Dict[str, Dict[str, Any]] = {}
        for name in names:
            o, w = old[name], new[name]
            if isinstance(o, Exception) or isinstance(w, Exception):
                err = o if isinstance(o, Exception) else w
                enriched[name] = {"churn_undefined": str(err)}
            else:
                enriched[name] = {"churn": {
                    "entered": sorted(w - o),
                    "exited": sorted(o - w),
                    "stayed": sorted(w & o),
                }}
        return enriched

    def _with_loaded(self, tenant: Any, leaves: Sequence[np.ndarray],
                     consensus: Sequence[np.ndarray], fn: Callable[[Any], Any]) -> Any:
        """Run ``fn(view)`` with the tenant's view state TEMPORARILY
        replaced by the given spec-ordered leaves, under ``view_lock``
        with capture-and-restore — the live merged state (and any
        concurrent scrape) is bitwise undisturbed."""
        from metrics_tpu.utilities.checkpoint import (
            load_metric_state_tree,
            metric_state_to_tree,
        )

        tree: Dict[str, Any] = {}
        for (path, _), leaf in zip(tenant.spec, leaves):
            _tree_set(tree, path, leaf)
        for path, leaf in zip(tenant.consensus_paths, consensus):
            _tree_set(tree, path, leaf)
        with tenant.view_lock:
            saved = metric_state_to_tree(tenant.view)
            try:
                load_metric_state_tree(tenant.view, tree)
                with warnings.catch_warnings():
                    # an EMPTY interval (no traffic between two cuts) is a
                    # legitimate history answer, not the compute-before-
                    # update misuse the base-class warning polices
                    warnings.filterwarnings(
                        "ignore", message=".*compute.*method of metric.*"
                    )
                    return fn(tenant.view)
            finally:
                load_metric_state_tree(tenant.view, saved)

    # -- introspection ---------------------------------------------------

    def tenant_intervals(self, tenant_id: str) -> List[Dict[str, Any]]:
        """Retained interval descriptors (oldest first) for one tenant —
        the admin/debug view of the ring ladder."""
        with self._lock:
            th = self._tenants.get(str(tenant_id))
            if th is None:
                return []
            return [dict(snap.meta(), level=level) for level, snap in th.retained()]

    def evicted_count(self, tenant_id: str) -> int:
        with self._lock:
            th = self._tenants.get(str(tenant_id))
            return 0 if th is None else th.evicted

    # -- durability (rides Aggregator.save/restore) ----------------------

    def state_for_checkpoint(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """(pytree, manifest meta) of every retained ring — positional
        ``h000000`` slots exactly like the registry's tenant slots
        (hostile tenant ids never become filesystem paths)."""
        tree: Dict[str, Any] = {}
        meta: Dict[str, Any] = {"tenants": {}, "intervals": {}, "state": {}}
        with self._lock:
            for h_idx, tenant_id in enumerate(sorted(self._tenants)):
                th = self._tenants[tenant_id]
                hslot = f"h{h_idx:06d}"
                meta["tenants"][hslot] = tenant_id
                meta["state"][hslot] = {
                    "next_index": th.next_index,
                    "evicted": th.evicted,
                    "last_evicted_t": th.last_evicted_t,
                }
                descriptors: List[List[Any]] = []
                slots: Dict[str, Any] = {}
                for j, (level, snap) in enumerate(th.retained()):
                    descriptors.append(
                        [snap.index, snap.t, snap.generation, level, snap.clients, snap.folded]
                    )
                    slots[f"i{j:06d}"] = {
                        "leaves": {f"l{i:06d}": leaf for i, leaf in enumerate(snap.leaves)},
                        "consensus": {
                            f"l{i:06d}": leaf for i, leaf in enumerate(snap.consensus)
                        },
                    }
                meta["intervals"][hslot] = descriptors
                if slots:
                    tree[hslot] = slots
            meta["generation"] = self.generation
            meta["last_cut_s"] = self._last_cut_s
        return tree, meta

    def load_checkpoint_state(self, tree: Dict[str, Any], meta: Dict[str, Any],
                              aggregator: Any) -> None:
        """Rebuild the rings bitwise from a checkpoint written by
        :meth:`state_for_checkpoint` (called from
        :meth:`Aggregator.restore` after tenants re-registered). Rings
        are replaced wholesale; tenants the checkpoint does not name
        keep whatever they have (a fresh node: nothing)."""
        with self._lock:
            for hslot, tenant_id in (meta.get("tenants") or {}).items():
                if tenant_id not in aggregator._tenants:
                    continue  # aggregator.restore already validated registration
                tenant = aggregator._tenants[tenant_id]
                th = _TenantHistory(tenant_id, self.config.levels)
                state = (meta.get("state") or {}).get(hslot) or {}
                th.next_index = int(state.get("next_index", 0))
                th.evicted = int(state.get("evicted", 0))
                last_t = state.get("last_evicted_t")
                th.last_evicted_t = None if last_t is None else float(last_t)
                slots = tree.get(hslot, {})
                for j, desc in enumerate(meta.get("intervals", {}).get(hslot) or []):
                    index, t, generation, level, clients, folded = desc
                    data = slots[f"i{j:06d}"]
                    leaves = [
                        np.asarray(data["leaves"][f"l{i:06d}"]).astype(tpl.dtype).reshape(tpl.shape)
                        for i, tpl in enumerate(tenant.template_leaves)
                    ]
                    consensus = [
                        np.asarray(data["consensus"][f"l{i:06d}"]).astype(tpl.dtype).reshape(tpl.shape)
                        for i, tpl in enumerate(tenant.template_consensus)
                    ]
                    th.restore_insert(
                        min(int(level), len(self.config.levels) - 1),
                        IntervalSnapshot(
                            int(index), float(t), int(generation), int(clients),
                            int(folded), leaves, consensus,
                        ),
                    )
                self._tenants[tenant_id] = th
            gen = meta.get("generation")
            if gen is not None and int(gen) > self.generation:
                self.generation = int(gen)
            if _obs_enabled():
                for tenant_id, th in self._tenants.items():
                    _obs_gauge("history.intervals", float(len(th.retained())), tenant=tenant_id)


def _values_of(view: Any) -> Dict[str, Any]:
    """Computed values + streaming envelopes of a (temporarily loaded)
    collection view — the same shape :meth:`Aggregator.query` answers."""
    values: Dict[str, Any] = {}
    computed = view.compute()
    members = dict(view.items())
    for name, value in computed.items():
        entry: Dict[str, Any] = {"value": _jsonable(value)}
        metric = members.get(name)
        if metric is not None and hasattr(metric, "bounds") and hasattr(metric, "error_bound"):
            lo, hi = metric.bounds()
            entry["bounds"] = [_jsonable(lo), _jsonable(hi)]
            entry["error_bound"] = _jsonable(metric.error_bound())
        values[name] = entry
    return values
