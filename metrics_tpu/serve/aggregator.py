"""Multi-tenant metric-state aggregation runtime.

One :class:`Aggregator` is one long-lived node in the serving tier: it
hosts a **tenant registry** (tenant id → metric collection schema), accepts
bounded-size wire payloads from thousands of clients, and maintains the
live merged state every scrape/query reads. The design rests on three
choices, each inherited from a primitive that already proved it:

* **cumulative snapshots + keep-latest** — a payload carries the client's
  *whole* folded state up to its ``(epoch, step)`` watermark (see
  :mod:`metrics_tpu.serve.wire`), and the aggregator keeps exactly the
  newest snapshot per client. Duplicates and reordered deliveries reduce
  to a watermark comparison against the client's
  :class:`~metrics_tpu.ft.journal.BatchJournal` — a stale or repeated
  payload is *dropped*, not re-merged, so delivery can be at-least-once
  while aggregation stays exactly-once.
* **batched jitted folds** — merging is not done per payload. Accepted
  snapshots mark their tenant dirty; :meth:`Aggregator.flush` stacks every
  client's state leaves along a leading axis and folds them in ONE jitted
  launch per tenant (the ``_FOLD_OPS`` shape ``make_epoch`` uses), with
  client counts padded to power-of-two buckets using the schema's identity
  state so the number of distinct traces stays logarithmic. Integer-valued
  ``sum`` leaves and sketch merges make the fold bitwise fold-order
  invariant — the property the hierarchical tree test pins
  (``tests/serve/test_tree.py``).
* **preemption-safe persistence** — :meth:`save` bundles every tenant's
  client snapshots + watermarks through
  :class:`~metrics_tpu.ft.CheckpointManager` (atomic stage+rename,
  rotation, manifest); :meth:`restore` brings them back bitwise and the
  restored watermarks keep dedup exact across the restart. Clients resend
  their latest snapshot on their next interval, so payloads that arrived
  after the last checkpoint are recovered by the at-least-once delivery,
  never double-counted.

Observability rides the :mod:`metrics_tpu.obs` registry: per-tenant
``serve.ingests`` / ``serve.merges`` / ``serve.dedup_drops`` counters, the
``serve.tenants`` / ``serve.clients`` / ``serve.queue_depth`` gauges and
``serve.ingest_ms`` / ``serve.flush_ms`` latency histograms — all exported
by the ``/metrics`` endpoint (:mod:`metrics_tpu.serve.endpoints`).
"""
import functools
import queue
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ft.journal import BatchJournal
from metrics_tpu.obs import meter as _obs_meter
from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import observe as _obs_observe
from metrics_tpu.obs.registry import record_hop as _obs_record_hop
from metrics_tpu.obs.registry import set_gauge as _obs_gauge
from metrics_tpu.serve.wire import (
    MetricPayload,
    SchemaMismatchError,
    WireFormatError,
    decode_state,
    peek_header,
    schema_diff,
    schema_fingerprint,
    schema_of,
)

__all__ = [
    "Aggregator",
    "BackpressureError",
    "DrainingError",
    "FencedGenerationError",
    "ServeError",
    "UnknownTenantError",
]

# reductions the aggregation fold understands: the merge-combinable family
# (the same set make_epoch's flattened fast path accepts). "mean" needs
# per-client weights and "cat" is unbounded — both are exactly what the
# bounded-state serving contract excludes.
_SERVABLE_REDUCTIONS = ("sum", "min", "max", "sketch")

# per-tenant bound on retired-identity tombstones: under sustained elastic
# churn every re-homed client leaves one behind at its old home, and an
# unbounded table (plus its copy in every checkpoint manifest) would grow
# monotonically with clients-moved x rebalances. Eviction is
# least-recently-retired and COUNTED (serve.tombstones_evicted) — the
# worst case of an evicted tombstone is a sufficiently ancient duplicate
# of a final ship being re-accepted, which the bound makes ~impossible in
# practice and the counter makes visible in any case.
MAX_RETIRED_TOMBSTONES = 10_000


class ServeError(RuntimeError):
    """Base class for serving-tier errors."""


class UnknownTenantError(ServeError):
    """Payload names a tenant this aggregator has not registered."""


class BackpressureError(ServeError):
    """Ingest queue full and the caller asked not to block (or its wait
    timed out). :attr:`retry_after_s` is the node's suggested backoff —
    the ``Retry-After`` the HTTP surface answers with."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DrainingError(ServeError):
    """The node is draining (:meth:`Aggregator.drain`): it no longer admits
    payloads. Unlike backpressure this is not transient for THIS node — the
    client should re-resolve its route (the elastic
    :class:`~metrics_tpu.serve.elastic.Router` already points its next ship
    at the new home). :attr:`retry_after_s` is derived from the drain
    timeout: by then the drain has either completed (the ring points
    elsewhere) or timed out and rolled back — either way the client's NEXT
    resolve-and-ship is useful, where a hot retry against the draining
    node is not (the ``Retry-After`` the HTTP surface answers with)."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


class FencedGenerationError(ServeError):
    """A payload carried ``meta["generation"]`` OLDER than the generation
    fence recorded for its client identity: a zombie pre-failover root (or
    a delayed replica of one) is trying to ship state a promotion already
    superseded. Refused loudly and counted (``serve.fenced_ships``) —
    merging it would resurrect pre-failover state next to the promoted
    root's live stream, a divergence nothing downstream could detect. NOT
    retryable: the zombie must be decommissioned (or re-promoted, which
    mints a NEWER generation)."""


@functools.partial(jax.jit, static_argnames=("reds",))
def _fold_stacked(stacked: Tuple[jax.Array, ...], reds: Tuple[str, ...]) -> Tuple[jax.Array, ...]:
    """ONE launch folding every leaf's leading client axis with its
    declared reduction — the whole flush amortizes into this call."""
    ops = {
        "sum": lambda m: jnp.sum(m, axis=0),
        "min": lambda m: jnp.min(m, axis=0),
        "max": lambda m: jnp.max(m, axis=0),
    }
    return tuple(ops[r](s) for s, r in zip(stacked, reds))


def _tree_get(tree: Dict[str, Any], path: Tuple[str, ...]) -> Any:
    node: Any = tree
    for key in path:
        node = node[key]
    return node


def _tree_set(tree: Dict[str, Any], path: Tuple[str, ...], leaf: Any) -> None:
    node = tree
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = leaf


class _ClientSlot:
    """Latest accepted snapshot of one client: journal watermark + the
    spec-ordered state leaves (numpy, ready to stack). ``last_accept_s``
    (monotonic) is the implicit heartbeat supervision reads — for a tree
    node's ``node:*`` clients, its age IS the child's ship-sequence age.

    ``trace`` is the payload's wire trace context (id, client encode
    timestamp, upstream hop chain) extended with THIS node's accept
    stamp/queue wait; ``trace_fresh`` marks it as not yet folded, so the
    fold records each accepted payload's e2e freshness exactly once."""

    __slots__ = ("journal", "leaves", "consensus", "last_accept_s", "trace", "trace_fresh")

    def __init__(self) -> None:
        self.journal = BatchJournal()
        self.leaves: List[np.ndarray] = []
        self.consensus: List[np.ndarray] = []
        self.last_accept_s = time.monotonic()
        self.trace: Optional[Dict[str, Any]] = None
        self.trace_fresh = False


class _Tenant:
    """Registry entry: schema, leaf layout, client snapshots, merged view.

    ``engine`` (an :class:`~metrics_tpu.engine.ExecutionEngine` resolving
    AOT programs) and ``eager_fold`` select the fold backend: with an
    engine, every fold bucket runs through ONE pre-resolvable executable
    keyed by the tenant's schema fingerprint (the cache-key discipline:
    two tenants differing only in sketch bin count have different
    fingerprints, therefore different programs); with ``eager_fold`` the
    fold is plain numpy (no compile ever — tiny-fleet CPU serving);
    neither keeps the default jitted ``_fold_stacked`` path."""

    def __init__(
        self,
        tenant_id: str,
        collection: Any,
        node: str = "?",
        engine: Any = None,
        eager_fold: bool = False,
    ) -> None:
        from metrics_tpu.collections import MetricCollection
        from metrics_tpu.streaming.sketches import Sketch
        from metrics_tpu.utilities.checkpoint import metric_state_to_tree

        self.tenant_id = tenant_id
        # hosting aggregator's name: the node= label on the per-hop
        # provenance histograms this tenant's fold/accept path records
        self.node = str(node)
        # newest completed fold's latency + the oldest (stalest-encode)
        # live trace context — what AggregatorNode.forward stamps into the
        # upward payload's hop record so provenance follows the critical path
        self.last_fold_ms: Optional[float] = None
        self.oldest_trace: Optional[Dict[str, Any]] = None
        if not isinstance(collection, MetricCollection):
            collection = MetricCollection([collection])
        self.view = collection  # merged state materializes into this
        self.view.reset()
        self.schema = schema_of(self.view)
        self.schema_hash = schema_fingerprint(self.view)

        # leaf layout: folded leaves carry a (path, reduction); consensus
        # leaves (sketch meta blobs, detected-mode __aux json) must be
        # byte-identical across clients and are carried, not folded
        self.spec: List[Tuple[Tuple[str, ...], str]] = []
        self.consensus_paths: List[Tuple[str, ...]] = []
        template_trees: Dict[str, Dict[str, Any]] = {}
        for member, metric in sorted(self.view.items()):
            bad = {
                state: red
                for state, red in metric._reductions.items()
                if red not in _SERVABLE_REDUCTIONS
            }
            if bad:
                raise ServeError(
                    f"tenant {tenant_id!r} member {member!r} has non-servable state"
                    f" reduction(s) {bad}: the aggregation tier folds bounded"
                    f" {_SERVABLE_REDUCTIONS} states only. Unbounded cat/buffer"
                    " accumulations should stream through a mergeable sketch"
                    " (metrics_tpu.streaming) instead."
                )
            tree = metric_state_to_tree(metric)
            template_trees[member] = tree
            for state, red in metric._reductions.items():
                default = metric._defaults[state]
                if isinstance(default, Sketch):
                    for leaf_name, leaf_red in type(default)._leaf_fields:
                        self.spec.append(((member, state, f"__sketch_leaf_{leaf_name}"), leaf_red))
                    self.consensus_paths.append((member, state, "__sketch_meta"))
                else:
                    self.spec.append(((member, state), red))
            self.spec.append(((member, "__update_count"), "sum"))
            if "__aux" in tree:
                self.consensus_paths.append((member, "__aux"))
        self.spec.sort()
        self.consensus_paths.sort()

        self.template_leaves = [
            np.asarray(_tree_get(template_trees, path)) for path, _ in self.spec
        ]
        self.template_consensus = [
            np.asarray(_tree_get(template_trees, path)) for path in self.consensus_paths
        ]
        self.can_pad = all(
            _is_identity(leaf, red) for leaf, (_, red) in zip(self.template_leaves, self.spec)
        )

        self.engine = engine
        self.eager_fold = bool(eager_fold)
        # bucket (padded client count) -> resolved executable; warm_buckets
        # records every bucket this tenant ever folded or pre-lowered — the
        # warmup manifest the checkpoint carries so a revived node replays
        # exactly the programs its predecessor ran
        self.fold_programs: Dict[int, Any] = {}
        self.warm_buckets: set = set()

        self.clients: Dict[str, _ClientSlot] = {}
        # watermark TOMBSTONES of retired clients (state re-homed by an
        # elastic rebalance): dedup keeps working against them, so a late
        # duplicate of a drained node's final ship cannot resurrect state
        # the rebalance already moved; a re-joining identity resumes its
        # watermark chain from here (and _resume_seq derives above it)
        self.retired: Dict[str, BatchJournal] = {}
        self.dirty = False
        self.lock = threading.Lock()
        # serializes view materialization (fold) against view readers
        # (query / scrape compute): the jitted fold itself runs outside
        # both locks, so ingest is never blocked on device compute
        self.view_lock = threading.Lock()
        self.merged_leaves: Optional[List[np.ndarray]] = None

    # -- ingest-side -----------------------------------------------------

    def flatten_payload(self, payload: MetricPayload) -> Tuple[List[np.ndarray], List[np.ndarray]]:
        """Spec-ordered (folded leaves, consensus leaves) of a payload,
        shape/dtype-checked against the template (the schema hash already
        matched, so a mismatch here means a corrupted body)."""
        leaves: List[np.ndarray] = []
        for (path, _), template in zip(self.spec, self.template_leaves):
            try:
                # KeyError: leaf missing; IndexError/TypeError: the body
                # collapsed a dict level into an array (indexing an ndarray
                # with a string) — all of them are a lying body, not a crash
                leaf = np.asarray(_tree_get(payload.states, path))
            except (KeyError, IndexError, TypeError) as err:
                raise ServeError(
                    f"payload for tenant {self.tenant_id!r} is missing state leaf"
                    f" {'/'.join(path)} (schema hash matched — body corrupted?)"
                ) from err
            if leaf.shape != template.shape or leaf.dtype != template.dtype:
                raise ServeError(
                    f"payload leaf {'/'.join(path)} for tenant {self.tenant_id!r} has"
                    f" shape/dtype {leaf.shape}/{leaf.dtype}, registered schema expects"
                    f" {template.shape}/{template.dtype}"
                )
            leaves.append(leaf)
        try:
            consensus = [np.asarray(_tree_get(payload.states, p)) for p in self.consensus_paths]
        except (KeyError, IndexError, TypeError) as err:
            raise ServeError(
                f"payload for tenant {self.tenant_id!r} is missing a consensus leaf"
                " (schema hash matched — body corrupted?)"
            ) from err
        return leaves, consensus

    # -- fold-side -------------------------------------------------------

    def fold_program(self, bucket: int) -> Any:
        """Resolve (or reuse) the stacked-fold executable for a ``bucket``
        of client rows — the per-tenant AOT program ``register_tenant``
        pre-lowers and :meth:`Aggregator.warmup` replays. The key is
        (schema fingerprint, stacked shapes/dtypes, reduction tuple,
        backend, jax version, topology): the schema fingerprint makes a
        bin-count change a different program, never a collision."""
        program = self.fold_programs.get(bucket)
        if program is None:
            from metrics_tpu.engine.keys import ProgramKey

            reds = tuple(red for _, red in self.spec)
            sds = tuple(
                jax.ShapeDtypeStruct((int(bucket),) + t.shape, t.dtype)
                for t in self.template_leaves
            )
            key = ProgramKey.build(
                "serve.fold_stacked", self.schema_hash, (sds,), static_sig=repr(reds)
            )
            program = self.engine.prepare(_fold_stacked, key, sds, reds=reds)
            self.fold_programs[bucket] = program
        # under the lock: _warmup_manifest (a checkpoint save) sorts this
        # set concurrently with worker folds adding to it
        with self.lock:
            self.warm_buckets.add(int(bucket))
        return program

    def prime_program(self, bucket: int) -> None:
        """Resolve the bucket's executable AND run it once on identity
        (template) rows: primes host->device transfer paths and proves the
        (possibly disk-loaded) executable actually executes — a corrupt
        cached program must fail at warmup, not under traffic."""
        program = self.fold_program(bucket)
        stacked = tuple(
            jnp.asarray(np.stack([t] * int(bucket)))
            for t in self.template_leaves
        )
        jax.block_until_ready(program(stacked))

    def fold(self) -> int:
        """Materialize the merged view from every client's latest snapshot
        in one jitted launch; returns the number of snapshots folded."""
        from metrics_tpu.utilities.checkpoint import load_metric_state_tree

        t_fold = time.perf_counter()
        armed = _obs_enabled()
        fresh_traces: List[Dict[str, Any]] = []
        with self.lock:
            order = sorted(self.clients)
            rows = [[self.clients[cid].leaves[i] for cid in order] for i in range(len(self.spec))]
            consensus_rows = [
                [self.clients[cid].consensus[i] for cid in order]
                for i in range(len(self.consensus_paths))
            ]
            self.dirty = False
            if armed:
                traced = [s.trace for s in self.clients.values() if s.trace is not None]
                self.oldest_trace = min(traced, key=lambda t: t["encoded_at"], default=None)
                for slot in self.clients.values():
                    if slot.trace_fresh and slot.trace is not None:
                        fresh_traces.append(slot.trace)
                        slot.trace_fresh = False
        k = len(order)
        if k == 0:
            merged = list(self.template_leaves)
            merged_consensus = list(self.template_consensus)
        else:
            for path, row in zip(self.consensus_paths, consensus_rows):
                first = row[0]
                for other in row[1:]:
                    if first.shape != other.shape or not np.array_equal(first, other):
                        raise ServeError(
                            f"tenant {self.tenant_id!r}: clients disagree on the"
                            f" non-foldable leaf {'/'.join(path)} (e.g. detected input"
                            " mode / sketch meta). All clients of a tenant must run"
                            " the same metric configuration."
                        )
            merged_consensus = [row[0] for row in consensus_rows]
            reds = tuple(red for _, red in self.spec)
            if self.eager_fold:
                # no-compile CPU backend: plain numpy reductions. Matches
                # the jitted fold bitwise for integer/sketch-count leaves
                # (the classes the tree invariant pins); float sums may
                # reassociate differently — document, don't mix backends
                # across nodes of one tree.
                ops = {"sum": np.sum, "min": np.min, "max": np.max}
                # pin the template dtype: np.sum silently widens int32
                # accumulations to the platform int, and a dtype drift here
                # would fail the payload shape/dtype check on re-encode
                merged = [
                    np.asarray(ops[red](np.stack(row), axis=0)).astype(
                        template.dtype, copy=False
                    )
                    for red, row, template in zip(reds, rows, self.template_leaves)
                ]
            else:
                pad = (_next_pow2(k) - k) if self.can_pad else 0
                stacked = tuple(
                    jnp.asarray(np.stack(row + [self.template_leaves[i]] * pad))
                    for i, row in enumerate(rows)
                )
                if self.engine is not None:
                    folded = self.fold_program(k + pad)(stacked)
                else:
                    folded = _fold_stacked(stacked, reds=reds)
                merged = [np.asarray(x) for x in folded]

        tree: Dict[str, Any] = {}
        for (path, _), leaf in zip(self.spec, merged):
            _tree_set(tree, path, leaf)
        for path, leaf in zip(self.consensus_paths, merged_consensus):
            _tree_set(tree, path, leaf)
        with self.view_lock:
            self.merged_leaves = merged
            load_metric_state_tree(self.view, tree)
        if armed:
            # the accepted snapshots just became queryable AT THIS NODE:
            # fold latency is one hop-provenance histogram sample, and each
            # not-yet-folded trace contributes one end-to-end freshness
            # sample (client encode wall time -> queryable here; the root's
            # node= series is the fleet's headline freshness)
            fold_ms = (time.perf_counter() - t_fold) * 1000.0
            self.last_fold_ms = fold_ms
            _obs_observe("serve.hop_fold_ms", fold_ms, node=self.node)
            # metering: the same fold latency split per tenant (the tenant
            # IS the fold unit here), plus the tenant's resident state
            # footprint — k client snapshots of the fixed schema plus the
            # merged view, all template-shaped by construction
            _obs_observe("meter.fold_ms", fold_ms, tenant=self.tenant_id)
            schema_bytes = sum(int(t.nbytes) for t in self.template_leaves) + sum(
                int(t.nbytes) for t in self.template_consensus
            )
            _obs_gauge(
                "meter.state_bytes", float((k + 1) * schema_bytes), tenant=self.tenant_id
            )
            now = time.time()
            for trace in fresh_traces:
                freshness_ms = max(0.0, (now - trace["encoded_at"]) * 1000.0)
                _obs_observe("serve.e2e_freshness_ms", freshness_ms, node=self.node)
                # per-tenant variant (additional series, same family): the
                # freshness SLI differences its bucket counts per tenant
                _obs_observe(
                    "serve.e2e_freshness_ms", freshness_ms,
                    node=self.node, tenant=self.tenant_id,
                )
                _obs_record_hop(trace["id"], self.node, "fold", fold_ms)
        return k

    def tombstone(self, client_id: str, journal: "BatchJournal") -> None:
        """(``self.lock`` held) record a retirement tombstone, bounded by
        ``MAX_RETIRED_TOMBSTONES``: the pop-reinsert keeps the dict in
        least-recently-retired order so eviction drops the oldest, and
        every eviction is counted — never a silent cap."""
        self.retired.pop(client_id, None)
        self.retired[client_id] = journal
        while len(self.retired) > MAX_RETIRED_TOMBSTONES:
            evicted = next(iter(self.retired))
            del self.retired[evicted]
            if _obs_enabled():
                _obs_inc("serve.tombstones_evicted", tenant=self.tenant_id)

    @property
    def folded_payloads(self) -> int:
        # lock: the background worker inserts client slots concurrently and
        # an unlocked .values() iteration can see the dict resize mid-walk
        with self.lock:
            return sum(slot.journal.folded for slot in self.clients.values())


def _is_identity(leaf: np.ndarray, red: str) -> bool:
    """True when ``leaf`` is the neutral element of ``red`` — the padding
    the power-of-two fold buckets rely on. Sketch leaves satisfy this by
    the fresh-sketch-is-identity contract; a tenant whose defaults are not
    neutral folds at exact client counts instead (more retraces, same
    values)."""
    if red == "sum":
        return bool(np.all(leaf == 0))
    if leaf.size == 0:
        return True
    if np.issubdtype(leaf.dtype, np.floating):
        target = np.inf if red == "min" else -np.inf
        return bool(np.all(leaf == target))
    if np.issubdtype(leaf.dtype, np.integer):
        info = np.iinfo(leaf.dtype)
        return bool(np.all(leaf == (info.max if red == "min" else info.min)))
    return False


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class Aggregator:
    """A multi-tenant aggregation node: registry + queue + fold + state.

    Args:
        name: node identity (obs labels, checkpoints, tree client ids).
        max_queue: bounded ingest queue depth; a full queue blocks the
            producer (or raises :class:`BackpressureError` with
            ``block=False``) instead of growing without bound.
        checkpoint_dir: when set, :meth:`save`/:meth:`restore` persist the
            whole registry (client snapshots + watermarks) through an
            atomic rotating :class:`~metrics_tpu.ft.CheckpointManager`.
        keep_last: checkpoint retention (see the manager).
        checkpoint_every: automatic :meth:`save` every N flushes
            (``None`` = manual saves only).
        flush_interval_s: background worker cadence for :meth:`start`.
        resilience: a :class:`~metrics_tpu.serve.resilience.ResilienceConfig`
            (or ``True`` for defaults) arming the per-client ingest
            firewall — circuit breakers on validation failures, quarantine
            of poisoned (NaN/Inf) state, and duplicate-watermark load
            shedding under queue pressure. ``None`` (default) constructs
            nothing and changes nothing.
        engine: execution backend for the per-tenant stacked folds (see
            :mod:`metrics_tpu.engine`). ``None``/``"jit"`` keep today's
            jitted path; ``"eager"`` folds in plain numpy (no compile
            ever); ``"aot"`` or an :class:`~metrics_tpu.engine.AotEngine`
            resolves one executable per (schema fingerprint, bucket)
            through the persistent program store — ``register_tenant``
            pre-lowers the ``prewarm_buckets`` programs, every fold
            bucket is recorded in the checkpoint's warmup manifest, and
            :meth:`warmup` replays that manifest so a revived node's
            first fold performs ZERO backend compiles
            (``tests/integrations/aot_smoke.py`` pins it).
        prewarm_buckets: fold bucket sizes (padded client counts)
            ``register_tenant`` pre-lowers when an AOT engine is armed.
        history: a :class:`~metrics_tpu.serve.history.HistoryConfig`
            (or ``True`` for defaults) arming the node's time-travel
            metrics database: every flush cadence-cuts per-tenant
            interval snapshots into bounded retention rings with exact
            monoid rollups, range queries (:meth:`history_query`, the
            ``/query?start=&end=`` surface) and root-evaluated alert
            rules — see :mod:`metrics_tpu.serve.history`. ``None``
            (default) constructs nothing and adds zero work to the
            ingest/fold path.

    Example::

        agg = Aggregator("root", checkpoint_dir="/tmp/agg")
        agg.register_tenant("search", lambda: MetricCollection(
            {"auroc": StreamingAUROC(num_bins=2048)}))
        agg.restore()          # no-op on fresh start
        agg.ingest(payload_bytes)
        agg.flush()
        print(agg.query("search")["values"]["auroc"])
    """

    def __init__(
        self,
        name: str = "root",
        *,
        max_queue: int = 4096,
        checkpoint_dir: Optional[str] = None,
        keep_last: Optional[int] = 3,
        checkpoint_every: Optional[int] = None,
        flush_interval_s: float = 0.05,
        resilience: Any = None,
        engine: Any = None,
        prewarm_buckets: Tuple[int, ...] = (1, 2),
        history: Any = None,
    ) -> None:
        if checkpoint_every is not None and checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1 (or None), got {checkpoint_every}")
        self.name = str(name)
        from metrics_tpu.engine import get_engine

        resolved = get_engine(engine)
        # "jit" is the default fold path already; "eager" selects the
        # numpy fold; anything else (AotEngine / custom) resolves programs
        self._eager_fold = resolved is not None and resolved.name == "eager"
        self._engine = None if (resolved is None or resolved.name in ("jit", "eager")) else resolved
        self._prewarm_buckets = tuple(int(b) for b in (prewarm_buckets or ()))
        if any(b < 1 for b in self._prewarm_buckets):
            raise ValueError(f"prewarm_buckets must be >= 1, got {prewarm_buckets}")
        self._warned_warmup_mismatch = False
        self._tenants: Dict[str, _Tenant] = {}
        self._queue: "queue.Queue[Tuple[MetricPayload, float]]" = queue.Queue(maxsize=max_queue)
        self._flush_lock = threading.Lock()
        self._registry_lock = threading.Lock()
        self._flushes = 0
        self._checkpoint_every = checkpoint_every
        self._flush_interval_s = float(flush_interval_s)
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False
        self._drain_deadline: Optional[float] = None
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # generation fences: client identity -> minimum acceptable
        # meta["generation"]. Advanced by accepted payloads carrying a
        # NEWER generation and by an explicit fence_generation() (the
        # multi-region promotion path); checked at ingest so a zombie
        # pre-failover root's ship is refused loudly at the door. Rides
        # the checkpoint manifest: a restored root must keep refusing the
        # zombie its predecessor already fenced out.
        self._generation_fences: Dict[str, int] = {}
        # free-form JSON-safe metadata bundled into every checkpoint
        # manifest (under extra.serve.node_meta) — the multi-region layer
        # persists its own generation here so promotion survives restarts
        self.manifest_extra: Dict[str, Any] = {}
        self._last_flush_s: Optional[float] = None
        self._firewall = None
        if resilience is not None and resilience is not False:
            # deferred import: resilience.py imports ServeError from here
            from metrics_tpu.serve.resilience import ClientFirewall, ResilienceConfig

            config = ResilienceConfig() if resilience is True else resilience
            self._firewall = ClientFirewall(config, node=self.name)
        self._history = None
        if history is not None and history is not False:
            # deferred import: history.py imports ServeError from here
            from metrics_tpu.serve.history import HistoryConfig, MetricHistory

            hconfig = HistoryConfig() if history is True else history
            self._history = MetricHistory(hconfig, node=self.name)
        self._manager = None
        if checkpoint_dir is not None:
            from metrics_tpu.ft.manager import CheckpointManager

            self._manager = CheckpointManager(checkpoint_dir, keep_last=keep_last)

    @property
    def firewall(self):
        """The armed :class:`~metrics_tpu.serve.resilience.ClientFirewall`,
        or None when ``resilience=`` was not given."""
        return self._firewall

    @property
    def history(self):
        """The armed :class:`~metrics_tpu.serve.history.MetricHistory`,
        or None when ``history=`` was not given."""
        return self._history

    @property
    def experiments(self):
        """The attached :class:`~metrics_tpu.experiment.DecisionEngine`,
        or None when no engine has been constructed over this node."""
        return getattr(self, "_experiment_engine", None)

    @property
    def slo(self):
        """The attached :class:`~metrics_tpu.obs.slo.SLOEngine`, or None
        when no SLO plane has been constructed over this node."""
        return getattr(self, "_slo_engine", None)

    @property
    def canary(self):
        """The attached :class:`~metrics_tpu.obs.prober.CanaryProber`,
        or None when no prober has been constructed over this node."""
        return getattr(self, "_canary_prober", None)

    # ------------------------------------------------------------------
    # Tenant registry
    # ------------------------------------------------------------------

    def register_tenant(self, tenant_id: str, metrics: Any) -> None:
        """Register a tenant: ``metrics`` is a Metric / MetricCollection
        (or a zero-arg factory returning one) defining the tenant's schema.
        Payloads for the tenant must match its schema fingerprint exactly;
        a changed sketch bin count / threshold grid is a different schema
        and is rejected loudly at ingest."""
        from metrics_tpu.collections import MetricCollection
        from metrics_tpu.metric import Metric

        tenant_id = str(tenant_id)
        # Metric instances are callable (forward), so "is it a factory"
        # must be an isinstance check, not callable()
        is_factory = callable(metrics) and not isinstance(metrics, (Metric, MetricCollection))
        collection = metrics() if is_factory else metrics
        with self._registry_lock:
            if tenant_id in self._tenants:
                raise ServeError(f"tenant {tenant_id!r} is already registered")
            tenant = self._tenants[tenant_id] = _Tenant(
                tenant_id,
                collection,
                node=self.name,
                engine=self._engine,
                eager_fold=self._eager_fold,
            )
        if self._engine is not None:
            # AOT: the tenant's stacked-fold programs exist BEFORE the
            # first payload — registration is the natural pre-lower point
            # (the schema is known, traffic has not started)
            for bucket in self._prewarm_buckets:
                tenant.fold_program(bucket)
        if _obs_enabled():
            _obs_gauge("serve.tenants", float(len(self._tenants)))

    def tenants(self) -> List[str]:
        """Registered tenant ids, sorted."""
        return sorted(self._tenants)

    def schema_hash(self, tenant_id: str) -> str:
        return self._tenant(tenant_id).schema_hash

    def client_watermark(self, tenant_id: str, client_id: str) -> Optional[Tuple[int, int]]:
        """Newest accepted ``(epoch, step)`` for a client, or None. A
        RETIRED client answers from its tombstone: a re-joining node's
        ``_resume_seq`` must derive its ship sequence above the watermark
        its predecessor identity left behind, or every post-rejoin ship
        would be dropped as a retired duplicate."""
        tenant = self._tenant(tenant_id)
        slot = tenant.clients.get(str(client_id))
        if slot is not None:
            return slot.journal.watermark
        ghost = tenant.retired.get(str(client_id))
        return None if ghost is None else ghost.watermark

    def retire_client(self, client_id: str, tenant_id: Optional[str] = None) -> int:
        """Remove a client's snapshot from the fold, leaving a watermark
        **tombstone** (the elastic rebalance primitive — see
        :mod:`metrics_tpu.serve.elastic`).

        The state leaves are dropped and the next fold excludes the client;
        the journal watermark is kept as a tombstone the dedup keeps
        enforcing: a late duplicate of the retired identity's final ship
        drops (``serve.dedup_drops{kind=retired}``), and so does a
        STALE-ROUTED end-client ship that advances past the tombstone
        (``kind=stale_route`` — accepting it would double-count the client
        at the root forever, while the drop is repaired by its next
        correctly-routed cumulative ship). Only an elastic handoff
        (``meta["rehomed_from"]``, watermark >= tombstone) or a rejoined
        ``node:*`` identity advancing its ship sequence re-admits the
        identity. A retired END client must therefore always be handed
        off to its new home — the elastic protocols do; a bare
        ``retire_client`` without a handoff orphans the identity HERE
        until a handoff pops the tombstone. Returns the number of tenant
        slots retired (``tenant_id=None`` retires across all tenants)."""
        client_id = str(client_id)
        tenants = [self._tenant(tenant_id)] if tenant_id is not None else list(self._tenants.values())
        retired = 0
        for tenant in tenants:
            with tenant.lock:
                slot = tenant.clients.pop(client_id, None)
                if slot is None:
                    continue
                tenant.tombstone(client_id, slot.journal)
                tenant.dirty = True
                retired += 1
            if _obs_enabled():
                _obs_inc("serve.retired_clients", tenant=tenant.tenant_id)
                _obs_gauge("serve.clients", float(len(tenant.clients)), tenant=tenant.tenant_id)
        return retired

    def _slot_payload(
        self, tenant: "_Tenant", client_id: str, wm, leaves, consensus
    ) -> MetricPayload:
        tree: Dict[str, Any] = {}
        for (path, _), leaf in zip(tenant.spec, leaves):
            _tree_set(tree, path, leaf)
        for path, leaf in zip(tenant.consensus_paths, consensus):
            _tree_set(tree, path, leaf)
        return MetricPayload(
            tenant=tenant.tenant_id,
            collection=tenant.tenant_id,
            client_id=str(client_id),
            watermark=(int(wm[0]), int(wm[1])),
            schema_hash=tenant.schema_hash,
            schema=tenant.schema,
            states=tree,
            meta={"rehomed_from": self.name},
        )

    def client_snapshot(self, tenant_id: str, client_id: str) -> MetricPayload:
        """Re-materialize one client's latest ACCEPTED snapshot as a
        :class:`~metrics_tpu.serve.wire.MetricPayload` — identity and
        watermark preserved, so handing it to another aggregator is
        indistinguishable from the client having shipped there itself (the
        elastic handoff path: the client's own next cumulative ship to the
        new home dedups against exactly this watermark). Read-only; the
        handoff itself uses the atomic :meth:`takeout_client`."""
        tenant = self._tenant(tenant_id)
        with tenant.lock:
            slot = tenant.clients.get(str(client_id))
            if slot is None:
                raise ServeError(
                    f"tenant {tenant.tenant_id!r} on aggregator {self.name!r} holds no"
                    f" snapshot for client {client_id!r}"
                )
            wm = slot.journal.watermark or (0, 0)
            leaves = list(slot.leaves)
            consensus = list(slot.consensus)
        return self._slot_payload(tenant, str(client_id), wm, leaves, consensus)

    def takeout_client(self, tenant_id: str, client_id: str) -> Optional[MetricPayload]:
        """ATOMICALLY remove + tombstone one client slot and return its
        snapshot — the elastic handoff's read side. Snapshot and retire
        happen under ONE tenant-lock hold: a separate read-then-retire
        would race a live flush worker accepting a newer ship in between,
        tombstoning a watermark whose state was never captured (the
        accepted snapshot would exist nowhere). Returns ``None`` when the
        tenant holds no slot for the client. If delivering the returned
        payload fails, re-accepting it HERE restores the slot (the
        tombstone it left matches the payload's watermark, and the
        ``rehomed_from`` meta re-admits it)."""
        tenant = self._tenant(tenant_id)
        client_id = str(client_id)
        with tenant.lock:
            slot = tenant.clients.pop(client_id, None)
            if slot is None:
                return None
            tenant.tombstone(client_id, slot.journal)
            tenant.dirty = True
            wm = slot.journal.watermark or (0, 0)
            leaves = list(slot.leaves)
            consensus = list(slot.consensus)
        if _obs_enabled():
            _obs_inc("serve.retired_clients", tenant=tenant.tenant_id)
            _obs_gauge("serve.clients", float(len(tenant.clients)), tenant=tenant.tenant_id)
        return self._slot_payload(tenant, client_id, wm, leaves, consensus)

    def _tenant(self, tenant_id: str) -> _Tenant:
        tenant = self._tenants.get(str(tenant_id))
        if tenant is None:
            raise UnknownTenantError(
                f"tenant {tenant_id!r} is not registered on aggregator {self.name!r}"
                f" (registered: {sorted(self._tenants) or 'none'})"
            )
        return tenant

    # ------------------------------------------------------------------
    # Generation fencing (the multi-region failover guard)
    # ------------------------------------------------------------------

    @staticmethod
    def _payload_generation(payload: MetricPayload) -> Optional[int]:
        """The payload's ``meta["generation"]`` when it is a plain int
        (the wire-minor-3 contract); anything else — absent, or a foreign
        producer's non-int — is simply unfenced traffic."""
        gen = payload.meta.get("generation")
        if isinstance(gen, bool) or not isinstance(gen, int):
            return None
        return gen

    def fence_generation(self, client_id: str, generation: int) -> int:
        """Raise the generation fence for ``client_id`` to at least
        ``generation``; returns the resulting fence.

        Once fenced, any payload for the identity whose
        ``meta["generation"]`` is OLDER is refused at ingest with
        :class:`FencedGenerationError` (and dropped at fold time if it
        raced the fence into the queue), counted under
        ``serve.fenced_ships`` — the mechanism that keeps a zombie
        pre-failover regional root from resurrecting superseded state
        (see :mod:`metrics_tpu.serve.region`). Monotonic: a value at or
        below the current fence is a no-op. Fences also advance
        automatically when a VALIDATED payload carries a newer
        generation, and they ride the checkpoint manifest so a restored
        node keeps refusing what its predecessor fenced out."""
        client_id, generation = str(client_id), int(generation)
        # under the registry lock: two concurrent learners (a promotion's
        # proactive fence + a worker accepting the promoted root's first
        # ship) must not interleave their read-modify-writes and leave the
        # LOWER generation standing
        with self._registry_lock:
            fence = self._generation_fences.get(client_id)
            if fence is None or generation > fence:
                self._generation_fences[client_id] = generation
                fence = generation
        return fence

    def generation_fence(self, client_id: str) -> Optional[int]:
        """The current fence for an identity, or None when unfenced."""
        return self._generation_fences.get(str(client_id))

    def _fence_refuses(self, payload: MetricPayload) -> Optional[int]:
        """The fence value refusing this payload, or None when admissible
        (no generation meta, no fence, or generation >= fence)."""
        gen = self._payload_generation(payload)
        if gen is None:
            return None
        fence = self._generation_fences.get(payload.client_id)
        if fence is not None and gen < fence:
            return fence
        return None

    # ------------------------------------------------------------------
    # Ingestion
    # ------------------------------------------------------------------

    def ingest(
        self,
        payload: Union[bytes, MetricPayload],
        *,
        block: bool = True,
        timeout: Optional[float] = None,
    ) -> bool:
        """Validate and enqueue one payload (bytes or decoded).

        Validation is synchronous — an unknown tenant or schema mismatch
        raises here, where the producer can still see it; dedup happens at
        fold time against the client's journal watermark. The bounded
        queue provides backpressure: full + ``block=False`` raises
        :class:`BackpressureError`, and a ``block=True`` wait is watched
        against a dead background flush worker (a queue nothing drains
        must raise, not park the producer forever). With ``resilience=``
        armed, quarantined/circuit-open clients are refused off the cheap
        header peek before any body work, and under queue pressure
        (above the config's ``shed_watermark``) duplicate-watermark
        payloads are shed at the door — they would be dedup-dropped at
        fold anyway. Returns True when enqueued, False when shed.
        """
        # in-flight admission window: drain() waits for this count to reach
        # zero before trusting queue-empty, closing the acknowledged-then-
        # stranded TOCTOU between the draining gate and the queue put. The
        # count is taken BEFORE the gate is read: checked first, a producer
        # preempted between gate and increment would be invisible to the
        # drain and could still strand a payload behind its final flush —
        # incremented first, every producer is either visible to the
        # drain's wait or sees _draining set and refuses.
        with self._inflight_lock:
            self._inflight += 1
        try:
            if self._draining:
                # refused BEFORE any decode/firewall work: a draining
                # node's whole contract is that nothing new is admitted
                # after the drain's final flush — if this node is part of
                # an elastic fleet, the Router already points the client's
                # next ship at its new home
                raise DrainingError(
                    f"aggregator {self.name!r} is draining and no longer admits"
                    " payloads; re-resolve the route and ship to the new home",
                    retry_after_s=self._drain_retry_after(),
                )
            return self._ingest(payload, block=block, timeout=timeout)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _ingest(
        self,
        payload: Union[bytes, MetricPayload],
        *,
        block: bool,
        timeout: Optional[float],
    ) -> bool:
        t0 = time.perf_counter()
        firewall = self._firewall
        identity: Optional[Tuple[str, str]] = None
        wire_bytes = 0  # decoded-object ingest (in-process hop) ships no wire
        if isinstance(payload, (bytes, bytearray, memoryview)):
            data = bytes(payload)
            wire_bytes = len(data)
            peeked = None
            if firewall is not None:
                try:
                    peeked = peek_header(data)
                    header = peeked[1]
                    identity = (str(header.get("tenant")), str(header.get("client")))
                except WireFormatError:
                    identity = None  # unframed garbage: nothing to attribute
                if identity is not None:
                    firewall.admit(*identity)
            try:
                # _peeked: the firewall already parsed the header; decode
                # must not pay that JSON parse a second time per payload
                payload = decode_state(data, _peeked=peeked)
            except WireFormatError:
                # corrupt-in-flight (crc) or lying directory: an error strike
                # against the named client — repeated strikes open its
                # circuit. Gated on a REGISTERED tenant: strikes keyed off an
                # unvalidated header must not let spoofed identities grow the
                # firewall's tracking table.
                if firewall is not None and identity is not None:
                    if _obs_enabled():
                        _obs_inc("serve.wire_errors", tenant=identity[0])
                        _obs_inc("slo.ingest_errors", tenant=identity[0], reason="wire")
                    if identity[0] in self._tenants:
                        firewall.record_error(*identity)
                raise
        elif firewall is not None:
            identity = (payload.tenant, payload.client_id)
            firewall.admit(*identity)
        try:
            tenant = self._tenant(payload.tenant)
            if payload.schema_hash != tenant.schema_hash:
                if firewall is not None and identity is not None:
                    firewall.record_error(*identity)
                diffs = schema_diff(tenant.schema, payload.schema)
                raise SchemaMismatchError(
                    f"payload schema {payload.schema_hash} does not match tenant"
                    f" {payload.tenant!r} schema {tenant.schema_hash};"
                    f" differing: {'; '.join(diffs) or 'fingerprint only'}"
                )
            fence = self._fence_refuses(payload)
            if fence is not None:
                # a zombie pre-failover root (generation < fence) must be
                # refused LOUDLY at the door — folding it would resurrect
                # superseded state, and a silent drop would leave the
                # zombie believing it is still the region's root
                if _obs_enabled():
                    _obs_inc(
                        "serve.fenced_ships", tenant=payload.tenant, client=payload.client_id
                    )
                raise FencedGenerationError(
                    f"aggregator {self.name!r} refuses payload from client"
                    f" {payload.client_id!r}: meta generation"
                    f" {self._payload_generation(payload)} is OLDER than the recorded"
                    f" fence {fence} — a newer generation was promoted for this"
                    " identity (failover); this sender is a superseded zombie and"
                    " must stand down, not retry"
                )
            if firewall is not None and self._shed_duplicate(tenant, payload):
                # the payload validated — a shed duplicate is a HEALTHY
                # client (and must resolve a pending half-open probe)
                firewall.record_ok(*identity)
                return False
            try:
                self._put_payload(payload, t0, block=block, timeout=timeout)
            except queue.Full:
                if _obs_enabled():
                    _obs_inc("serve.rejected", tenant=payload.tenant)
                    _obs_inc("slo.ingest_errors", tenant=payload.tenant, reason="backpressure")
                raise BackpressureError(
                    f"aggregator {self.name!r} ingest queue is full"
                    f" (max_queue={self._queue.maxsize}); retry with backoff"
                    " (ft.RetryPolicy with decorrelated jitter) or raise max_queue.",
                    retry_after_s=max(self._flush_interval_s * 2.0, 0.05),
                ) from None
        except SchemaMismatchError:
            raise  # the strike above already resolved any half-open probe
        except Exception:
            # unknown tenant, backpressure, dead worker, ...: the payload
            # was never JUDGED, so a half-open probe admitted above must be
            # released — a probe whose outcome is never recorded would pin
            # the circuit half-open (= refused) forever
            if firewall is not None and identity is not None:
                firewall.abandon_probe(*identity)
            raise
        if _obs_enabled():
            _obs_inc("serve.ingests", tenant=payload.tenant)
            # labeled per node: a tree hosts several aggregators in one
            # process, and an unlabeled gauge would be last-writer-wins —
            # an idle leaf masking a saturated root from HealthMonitor
            _obs_gauge("serve.queue_depth", float(self._queue.qsize()), node=self.name)
            if wire_bytes:
                # metering: decoded bytes attributed to the tenant, both as
                # an ordinary (capped, federable) counter family and into
                # the bounded top-consumer sketch (one host dict add here)
                _obs_inc("meter.wire_bytes", float(wire_bytes), tenant=payload.tenant)
                _obs_meter.charge(payload.tenant, float(wire_bytes))
        return True

    def _shed_duplicate(self, tenant: "_Tenant", payload: MetricPayload) -> bool:
        """Load shedding: above the shed watermark, a payload whose
        watermark does not advance its client is dropped at the door
        (``serve.shed``) — fold-time dedup would discard it anyway, and
        during an incident the queue slots are the scarce resource."""
        watermark = self._firewall.config.shed_watermark
        maxsize = self._queue.maxsize
        # watermark 1.0 is the documented off switch — a full queue must
        # NOT silently shed then, it falls through to normal backpressure
        if watermark >= 1.0 or maxsize <= 0 or self._queue.qsize() < watermark * maxsize:
            return False
        epoch, step = int(payload.watermark[0]), int(payload.watermark[1])
        with tenant.lock:
            slot = tenant.clients.get(payload.client_id)
            fresh = slot is None or slot.journal.should_fold(epoch, step)
        if fresh:
            return False
        if _obs_enabled():
            _obs_inc("serve.shed", tenant=payload.tenant, reason="duplicate_watermark")
            _obs_inc("slo.ingest_errors", tenant=payload.tenant, reason="shed")
        return True

    def _put_payload(
        self, payload: MetricPayload, t0: float, *, block: bool, timeout: Optional[float]
    ) -> None:
        """Enqueue, never parking forever on a queue whose worker died."""
        if not block or self._worker is None:
            # manual-flush mode keeps the plain blocking contract: the
            # caller owns draining and may be about to from another thread
            self._queue.put((payload, t0), block=block, timeout=timeout)
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._draining:
                # a producer parked in this loop when drain() began must
                # abort, not land a payload behind the drain's final flush
                raise DrainingError(
                    f"aggregator {self.name!r} began draining while this ingest"
                    " was waiting for queue space; re-resolve the route",
                    retry_after_s=self._drain_retry_after(),
                )
            worker = self._worker
            if worker is not None and not worker.is_alive() and not self._stop.is_set():
                raise ServeError(
                    f"aggregator {self.name!r}: the background flush worker has DIED"
                    " (not stopped) — ingest(block=True) would wait forever on a"
                    " queue nothing drains. Restart it with start() (or let a"
                    " serve.resilience.Supervisor heal it) and retry."
                )
            wait = 0.05
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise queue.Full
                wait = min(wait, remaining)
            try:
                self._queue.put((payload, t0), timeout=wait)
                return
            except queue.Full:
                continue

    def _accept(self, payload: MetricPayload, t0: float) -> bool:
        """Keep-latest dedup: returns True when the payload advanced its
        client's watermark (snapshot stored), False when dropped."""
        if _obs_enabled():
            # obs federation piggyback (wire minor 2): a shipping tree node
            # attaches its per-node snapshot table; accept into this
            # process's table BEFORE dedup — snapshots are keep-latest by
            # capture time themselves, so even a watermark-stale payload
            # may carry fresher telemetry
            piggyback = payload.meta.get("obs_nodes")
            if isinstance(piggyback, (list, tuple)):
                from metrics_tpu.obs import federation as _federation

                for snap in piggyback:
                    if _federation.accept_snapshot(snap):
                        _obs_inc("obs.federation_accepts", node=self.name)
        tenant = self._tenant(payload.tenant)
        if self._fence_refuses(payload) is not None:
            # the fence advanced while this payload sat in the queue (a
            # promotion raced the enqueue): same refusal as ingest, as a
            # fold-side drop — the drop-not-crash family, still counted
            if _obs_enabled():
                _obs_inc("serve.fenced_ships", tenant=payload.tenant, client=payload.client_id)
            return False
        epoch, step = int(payload.watermark[0]), int(payload.watermark[1])
        if epoch < 0 or step < 0:
            # decode_state refuses these on the wire; a directly-constructed
            # payload must hit the same drop-not-crash family (record() would
            # raise ValueError AFTER the slot insert otherwise)
            raise ServeError(
                f"payload watermark must be non-negative, got {(epoch, step)}"
            )
        with tenant.lock:
            slot = tenant.clients.get(payload.client_id)
            if slot is not None and not slot.journal.should_fold(epoch, step):
                if _obs_enabled():
                    kind = "duplicate" if slot.journal.watermark == (epoch, step) else "stale"
                    _obs_inc("serve.dedup_drops", tenant=payload.tenant, kind=kind)
                if self._firewall is not None:
                    # at-least-once redelivery is healthy behavior, not an
                    # error strike — it must reset the breaker, not feed it
                    self._firewall.record_ok(payload.tenant, payload.client_id)
                return False
            rehome_readmit = False
            if slot is None:
                ghost = tenant.retired.get(payload.client_id)
                if ghost is not None:
                    is_rehome = payload.meta.get("rehomed_from") is not None
                    is_node = payload.client_id.startswith("node:")
                    advancing = ghost.should_fold(epoch, step)
                    if is_rehome and (advancing or ghost.watermark == (epoch, step)):
                        # an elastic HANDOFF delivering the tombstone's
                        # successor state (the client's assignment bounced
                        # away and back): re-admit it rather than orphaning
                        # the state between homes. The tombstone itself is
                        # popped only at slot creation, AFTER the body
                        # validates: popping here would destroy it even when
                        # the body turns out corrupt or poisoned and nothing
                        # is admitted.
                        rehome_readmit = ghost.watermark == (epoch, step)
                    elif is_node and advancing:
                        pass  # a REJOINED node resuming above its tombstone
                        # (_resume_seq derived the sequence from it): live
                        # again, fall through to accept with the chain intact
                    else:
                        # everything else a tombstone sees is wrong-home
                        # traffic: a late duplicate/stale delivery of the
                        # retired identity's final ship, or a STALE-ROUTED
                        # end-client ship racing the rebalance (route
                        # resolved before the membership change). Accepting
                        # either would resurrect state the rebalance already
                        # re-homed — a permanent double count at the root
                        # that nothing ever reconciles; dropping is SAFE by
                        # the cumulative contract: the client's next
                        # correctly-routed ship carries everything. (Every
                        # legitimate return of an identity to this node goes
                        # through a tombstone-popping handoff or, for node:*
                        # rejoins, advances the chain — handled above.)
                        if _obs_enabled():
                            kind = "stale_route" if advancing else "retired"
                            _obs_inc("serve.dedup_drops", tenant=payload.tenant, kind=kind)
                        if self._firewall is not None:
                            self._firewall.record_ok(payload.tenant, payload.client_id)
                        return False
            # validate the body BEFORE touching the registry: a corrupted
            # payload (hash matched, leaf missing/misshapen) must not leave
            # an empty slot behind that every later fold would trip over
            try:
                leaves, consensus = tenant.flatten_payload(payload)
            except ServeError:
                if self._firewall is not None:
                    self._firewall.record_error(payload.tenant, payload.client_id)
                raise
            if self._firewall is not None:
                from metrics_tpu.serve.resilience import check_poisoned

                detail = check_poisoned(tenant.spec, leaves)
                if detail is not None:
                    # poisoned-state firewall: drop the snapshot and
                    # quarantine the client INSTEAD of folding NaN into the
                    # tenant view (which every healthy client then inherits)
                    self._firewall.record_poison(payload.tenant, payload.client_id, detail)
                    return False
                self._firewall.record_ok(payload.tenant, payload.client_id)
            if slot is None:
                slot = tenant.clients[payload.client_id] = _ClientSlot()
                ghost = tenant.retired.pop(payload.client_id, None)
                if ghost is not None and not rehome_readmit:
                    # a retired identity legitimately advanced past its
                    # tombstone (a re-joined node:* resuming its sequence, or
                    # an advancing handoff): it is live again — continue its
                    # watermark chain so dedup stays exact across the gap.
                    # (The equal-watermark rehome re-admit keeps the fresh
                    # journal instead: record() on the adopted journal would
                    # refuse the non-advance.)
                    slot.journal = ghost
            slot.journal.record(epoch, step)
            slot.leaves = leaves
            slot.consensus = consensus
            slot.last_accept_s = time.monotonic()
            if _obs_enabled():
                trace = payload.meta.get("trace")
                if isinstance(trace, dict) and "id" in trace:
                    # per-hop provenance: extend the wire trace context with
                    # THIS node's accept stamp; queue wait covers ingest ->
                    # accepted (decode + validate + queue residency + dedup)
                    queue_wait_ms = (time.perf_counter() - t0) * 1000.0
                    slot.trace = {
                        "id": str(trace["id"]),
                        "encoded_at": float(trace.get("encoded_at", time.time())),
                        "hops": list(trace.get("hops", [])),
                        "accept_ts": time.time(),
                        "queue_wait_ms": queue_wait_ms,
                    }
                    slot.trace_fresh = True
                    _obs_observe("serve.hop_queue_wait_ms", queue_wait_ms, node=self.name)
                    # ADDITIONAL per-tenant series in the same family: the
                    # node-only series keeps its exactly-one-sample-per-
                    # accept contract (tests pin it); the tenant split is
                    # what the SLO plane and /tenants need
                    _obs_observe(
                        "serve.hop_queue_wait_ms", queue_wait_ms,
                        node=self.name, tenant=payload.tenant,
                    )
                    _obs_observe("meter.queue_ms", queue_wait_ms, tenant=payload.tenant)
                    _obs_record_hop(slot.trace["id"], self.name, "queue_wait", queue_wait_ms)
            tenant.dirty = True
        gen = self._payload_generation(payload)
        if gen is not None:
            # fence learning happens only AFTER the body validated and the
            # snapshot was accepted: an unvalidated header must not be able
            # to advance the fence (it could lock out the live root)
            self.fence_generation(payload.client_id, gen)
        if _obs_enabled():
            _obs_observe("serve.ingest_ms", (time.perf_counter() - t0) * 1000.0, tenant=payload.tenant)
            _obs_gauge("serve.clients", float(len(tenant.clients)), tenant=payload.tenant)
        return True

    # ------------------------------------------------------------------
    # Folding
    # ------------------------------------------------------------------

    def flush(self) -> int:
        """Drain the queue, accept snapshots, fold every dirty tenant in
        one jitted launch each; returns the number of payloads drained.
        Thread-safe; the background worker calls exactly this. A payload
        whose BODY turns out corrupted at accept time (the schema hash
        matched at ingest, so this is hostile or bit-rotted data) is
        dropped and counted under ``serve.accept_errors`` — one bad client
        must not halt aggregation for every tenant on the node."""
        with self._flush_lock:
            drained = 0
            while True:
                try:
                    payload, t0 = self._queue.get_nowait()
                except queue.Empty:
                    break
                drained += 1
                try:
                    self._accept(payload, t0)
                except ServeError as err:
                    if _obs_enabled():
                        _obs_inc("serve.accept_errors", tenant=payload.tenant)
                        _obs_inc("slo.ingest_errors", tenant=payload.tenant, reason="accept")
                    warnings.warn(
                        f"aggregator {self.name!r} dropped a corrupted payload from"
                        f" client {payload.client_id!r}: {err}",
                        stacklevel=2,
                    )
            t_fold = time.perf_counter()
            folded_any = False
            for tenant in list(self._tenants.values()):
                if tenant.dirty:
                    try:
                        k = tenant.fold()
                    except ServeError as err:
                        # same one-bad-client contract as _accept: a tenant
                        # whose clients disagree on a consensus leaf must
                        # not abort the fold loop for every OTHER tenant on
                        # the node (its own view stays stale until a client
                        # ships a corrected snapshot and re-marks it dirty)
                        if _obs_enabled():
                            _obs_inc("serve.fold_errors", tenant=tenant.tenant_id)
                        warnings.warn(
                            f"aggregator {self.name!r} could not fold tenant"
                            f" {tenant.tenant_id!r}: {err}",
                            stacklevel=2,
                        )
                        continue
                    folded_any = True
                    if _obs_enabled():
                        _obs_inc("serve.merges", float(k), tenant=tenant.tenant_id)
            if self._history is not None:
                # the time-travel cut rides the flush (cadence-gated inside
                # maybe_cut): the merged views it snapshots were folded just
                # above, under this same _flush_lock hold. One `is None`
                # check is ALL an unarmed node pays here.
                try:
                    self._history.maybe_cut(self)
                except Exception as err:  # noqa: BLE001 — a history bug must
                    # degrade to "no new interval", never halt aggregation
                    if _obs_enabled():
                        _obs_inc("history.cut_errors", node=self.name)
                    warnings.warn(
                        f"aggregator {self.name!r} history cut failed:"
                        f" {type(err).__name__}: {err}",
                        stacklevel=2,
                    )
            self._flushes += 1
            self._last_flush_s = time.monotonic()
            if _obs_enabled():
                _obs_gauge("serve.queue_depth", float(self._queue.qsize()), node=self.name)
                if folded_any:
                    _obs_observe("serve.flush_ms", (time.perf_counter() - t_fold) * 1000.0)
            want_save = (
                self._manager is not None
                and self._checkpoint_every is not None
                and self._flushes % self._checkpoint_every == 0
            )
        # outside _flush_lock: save() re-acquires it (it must serialize
        # with flushes when called directly), so saving inline above would
        # self-deadlock on the non-reentrant lock
        if want_save:
            self.save()
        return drained

    # ------------------------------------------------------------------
    # Background worker
    # ------------------------------------------------------------------

    def start(self) -> "Aggregator":
        """Run :meth:`flush` on a daemon worker every ``flush_interval_s``
        until :meth:`stop`. Idempotent."""
        if self._worker is not None and self._worker.is_alive():
            return self
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self._flush_interval_s):
                try:
                    self.flush()
                except Exception as err:  # noqa: BLE001 — a dying worker is a
                    # silently frozen aggregator (stale /metrics reads as a
                    # healthy idle fleet); surface the error and keep draining
                    if _obs_enabled():
                        _obs_inc("serve.flush_errors")
                    warnings.warn(
                        f"aggregator {self.name!r} background flush failed:"
                        f" {type(err).__name__}: {err}",
                        stacklevel=2,
                    )

        self._worker = threading.Thread(target=loop, name=f"serve-agg-{self.name}", daemon=True)
        self._worker.start()
        return self

    def stop(self) -> None:
        """Stop the worker and run one final drain-and-fold."""
        self._stop.set()
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        self.flush()

    @property
    def draining(self) -> bool:
        """True once :meth:`drain` has begun: ingest refuses new payloads."""
        return self._draining

    def _drain_retry_after(self) -> float:
        """The ``Retry-After`` a refused-while-draining client gets: time
        to the drain's own deadline — by then the drain has completed (the
        ring points elsewhere) or timed out and rolled back, so THAT is
        when a re-resolve-and-retry becomes useful; hot-retrying sooner
        can only collect more :class:`DrainingError`. Floored at 1s; falls
        back to a couple of flush intervals if no deadline is stamped."""
        deadline = self._drain_deadline
        if deadline is None:
            return max(1.0, self._flush_interval_s * 2.0)
        return max(1.0, deadline - time.monotonic())

    def resume_admission(self) -> None:
        """Roll back a FAILED :meth:`drain`: re-open admission (and clear
        the ``/healthz/ready`` draining reason). The elastic drain protocol
        uses this when the queue could not be emptied in time and the node
        must re-enter the ring — a node left out of the ring while still
        refusing ingest would be a permanent blackhole for ~1/n of the
        keyspace. Meaningless after a COMPLETED drain (state handed off,
        worker stopped); the elastic layer never calls it then."""
        self._draining = False
        self._drain_deadline = None

    def drain(self, timeout_s: float = 30.0) -> int:
        """Graceful counterpart to :meth:`stop`: stop admitting, fold the
        ingest queue **to empty**, then stop the worker.

        :meth:`stop` runs one final flush, which drains whatever is queued
        at that instant — but a producer blocked in a full-queue ``put``
        can land a payload right after that flush's drain loop broke, and
        the payload is then stranded forever (queued, never folded).
        ``drain`` closes that window: admission is refused FIRST
        (:class:`DrainingError`), so the queue can only shrink, and the
        flush loop runs until it is actually empty — bounded by
        ``timeout_s``, raising :class:`ServeError` (never silently
        stranding) if the queue cannot be emptied in time. Idempotent: a
        second call finds nothing to drain and returns 0. Returns the
        number of payloads drained."""
        # stamp the deadline BEFORE the gate flips: every DrainingError
        # raised from here on derives its Retry-After from it
        self._drain_deadline = time.monotonic() + float(timeout_s)
        self._draining = True
        deadline = self._drain_deadline
        drained = self.flush()
        while True:
            with self._inflight_lock:
                inflight = self._inflight
            # queue-empty alone is not enough: a producer that passed the
            # admission gate before _draining was set may still be between
            # validation and its queue put — an acknowledged payload landing
            # behind the final flush would be stranded forever. Spin the
            # flush until the queue is empty AND no admitted ingest is still
            # in flight (blocked full-queue puts unblock as the flush frees
            # slots, then abort on the draining re-check).
            if inflight == 0 and self._queue.empty():
                break
            if time.monotonic() > deadline:
                raise ServeError(
                    f"aggregator {self.name!r} drain timed out after {timeout_s}s"
                    f" with {self._queue.qsize()} payload(s) still queued and"
                    f" {inflight} ingest(s) in flight — a producer is wedged or"
                    " a fold is stuck; nothing was stranded silently, retry drain()"
                )
            flushed = self.flush()
            drained += flushed
            if not flushed and inflight:
                time.sleep(0.001)  # yield to the in-flight producer
        # the worker's own final flush (inside stop) catches a payload a
        # pre-draining put() raced in between our last flush and here
        self.stop()
        if _obs_enabled():
            _obs_inc("serve.drains", node=self.name)
        return drained

    # ------------------------------------------------------------------
    # Liveness surface (read by /healthz and serve.resilience.Supervisor)
    # ------------------------------------------------------------------

    def worker_alive(self) -> Optional[bool]:
        """None when no background worker is running by design (never
        started, or cleanly stopped); otherwise the worker thread's
        liveness — False means it DIED and the queue drains nothing."""
        worker = self._worker
        if worker is None:
            return None
        return worker.is_alive()

    def last_flush_age_s(self) -> Optional[float]:
        """Seconds since the last completed :meth:`flush`, or None before
        the first — the freshness signal readiness probes gate on."""
        last = self._last_flush_s
        return None if last is None else max(0.0, time.monotonic() - last)

    def client_ages(self) -> Dict[str, float]:
        """Age (s) of each client's newest accepted snapshot, minimized
        across tenants. For ``node:*`` clients this is the child node's
        ship-sequence age — the parent-side heartbeat supervision reads."""
        now = time.monotonic()
        ages: Dict[str, float] = {}
        for tenant in list(self._tenants.values()):
            with tenant.lock:
                for client_id, slot in tenant.clients.items():
                    age = max(0.0, now - slot.last_accept_s)
                    if client_id not in ages or age < ages[client_id]:
                        ages[client_id] = age
        return ages

    # ------------------------------------------------------------------
    # Read side
    # ------------------------------------------------------------------

    def collection(self, tenant_id: str, *, flush: bool = True):
        """The tenant's live merged :class:`MetricCollection` view (folded
        first unless ``flush=False``). Read-only by convention: updates
        belong on clients."""
        if flush:
            self.flush()
        tenant = self._tenant(tenant_id)
        if tenant.merged_leaves is None:
            tenant.fold()
        return tenant.view

    def query(self, tenant_id: str) -> Dict[str, Any]:
        """Merged values for one tenant with streaming error envelopes.

        Returns ``{"tenant", "clients", "payloads_folded", "values"}``
        where each value entry carries ``value`` plus, for streaming
        metrics that document bounds, ``error_bound`` and ``bounds`` —
        the rigorous envelope, not a vibe (see ``docs/streaming.md``).
        """
        view = self.collection(tenant_id)
        tenant = self._tenant(tenant_id)
        values: Dict[str, Any] = {}
        # view_lock: a concurrent background fold() swaps the view's state
        # leaves while compute()/bounds() read them — without the lock a
        # scrape could see half of fold N and half of fold N+1
        with tenant.view_lock:
            computed = view.compute()
            members = dict(view.items())
            for name, value in computed.items():
                entry: Dict[str, Any] = {"value": _jsonable(value)}
                metric = members.get(name)
                if metric is not None and hasattr(metric, "bounds") and hasattr(metric, "error_bound"):
                    lo, hi = metric.bounds()
                    entry["bounds"] = [_jsonable(lo), _jsonable(hi)]
                    entry["error_bound"] = _jsonable(metric.error_bound())
                values[name] = entry
        return {
            "tenant": tenant.tenant_id,
            "schema_hash": tenant.schema_hash,
            "clients": len(tenant.clients),
            "payloads_folded": tenant.folded_payloads,
            "values": values,
        }

    def history_query(
        self,
        tenant_id: str,
        start: float,
        end: float,
        *,
        step: Optional[float] = None,
        mode: str = "delta",
    ) -> Dict[str, Any]:
        """Range-query the node's time-travel history (requires
        ``history=`` at construction): per-interval (``mode="delta"``)
        or as-of (``mode="cumulative"``) values with streaming
        ``bounds``/``error_bound`` envelopes — the ``/query`` surface's
        ``start``/``end``/``step``/``mode`` parameters. Flushes first so
        a due cadence cut lands before the range resolves. See
        :meth:`~metrics_tpu.serve.history.MetricHistory.range_query`."""
        if self._history is None:
            raise ServeError(
                f"aggregator {self.name!r} has no history armed; construct with"
                " Aggregator(..., history=HistoryConfig(...)) to retain interval"
                " snapshots and serve range queries"
            )
        self.flush()
        return self._history.range_query(self, tenant_id, start, end, step=step, mode=mode)

    # ------------------------------------------------------------------
    # Persistence (ft.CheckpointManager)
    # ------------------------------------------------------------------

    def save(self) -> str:
        """Atomically checkpoint every tenant's client snapshots and
        watermarks; returns the checkpoint path. Requires
        ``checkpoint_dir``."""
        manager = self._require_manager()
        proxy, extra = self._registry_state()
        with self._flush_lock:
            return manager.save(proxy, extra={"serve": extra})

    def restore(self, path: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Restore the newest (or given) checkpoint into the registry.

        Tenants must be re-registered (same schema) BEFORE restoring —
        factories don't serialize; the manifest's schema hashes verify the
        re-registration matches what was saved. Returns the manifest, or
        None on a fresh start. Restored states and watermarks are bitwise
        the saved ones, so post-restore dedup and folds continue
        exactly-once (pinned by ``tests/serve/test_aggregator.py``).
        """
        manager = self._require_manager()
        proxy, _ = self._registry_state(empty=True)
        manifest = manager.restore(proxy, path=path)
        if manifest is None:
            return None
        serve_meta = (manifest.get("extra") or {}).get("serve")
        if serve_meta is None:
            raise ServeError(
                f"checkpoint at {manager.directory} carries no serve registry metadata"
                " — it was not written by Aggregator.save()"
            )
        for tslot, tmeta in serve_meta["tenants"].items():
            tenant_id = tmeta["id"]
            tenant = self._tenants.get(tenant_id)
            if tenant is None:
                raise UnknownTenantError(
                    f"checkpoint contains tenant {tenant_id!r} but it is not"
                    " registered; register_tenant() every tenant (same schema)"
                    " before restore()."
                )
            if tenant.schema_hash != tmeta["schema_hash"]:
                diffs: List[str] = []
                if "schema" in tmeta:
                    diffs = schema_diff(tenant.schema, tmeta["schema"])
                raise SchemaMismatchError(
                    f"tenant {tenant_id!r} re-registered with schema"
                    f" {tenant.schema_hash} but the checkpoint was saved under"
                    f" {tmeta['schema_hash']}; differing: {'; '.join(diffs) or 'fingerprint only'}"
                )
            slots = proxy.tree.get(tslot, {})
            # retired-identity tombstones ride the manifest (tiny: id ->
            # watermark+folded): a restore that dropped them would let a
            # healed node resurrect a drained child's frozen final ship as
            # a live client — re-homed state counted twice, forever
            retired_meta = (serve_meta.get("retired") or {}).get(tslot, {})
            with tenant.lock:
                tenant.clients.clear()
                tenant.retired.clear()
                for client_id, (r_epoch, r_step, r_folded) in retired_meta.items():
                    tenant.retired[client_id] = BatchJournal().load_state_dict(
                        {"watermark": [int(r_epoch), int(r_step)], "folded": int(r_folded)}
                    )
                for idx, client_id in enumerate(serve_meta["clients"][tslot]):
                    data = slots[f"c{idx:06d}"]
                    slot = _ClientSlot()
                    wm = np.asarray(data["wm"]).astype(np.int64)
                    slot.journal.load_state_dict(
                        {"watermark": [int(wm[0]), int(wm[1])], "folded": int(np.asarray(data["folded"]))}
                    )
                    slot.leaves = [
                        np.asarray(data["leaves"][f"l{i:06d}"]).astype(t.dtype).reshape(t.shape)
                        for i, t in enumerate(tenant.template_leaves)
                    ]
                    slot.consensus = [
                        np.asarray(data["consensus"][f"l{i:06d}"]).astype(t.dtype).reshape(t.shape)
                        for i, t in enumerate(tenant.template_consensus)
                    ]
                    tenant.clients[client_id] = slot
                tenant.dirty = True
        for client_id, gen in (serve_meta.get("fences") or {}).items():
            # monotonic merge: a fence learned live since construction
            # must not be LOWERED by an older checkpoint's record
            self.fence_generation(client_id, int(gen))
        history_meta = serve_meta.get("history")
        if self._history is not None and history_meta is not None:
            # the retention rings resume bitwise mid-ladder: indexes, cut
            # times, per-interval generations and the eviction horizon are
            # exactly what the predecessor saved (history_smoke pins the
            # post-restore range answers against the flat oracle)
            self._history.load_checkpoint_state(
                proxy.tree.get("history", {}), history_meta, self
            )
        experiments_meta = serve_meta.get("experiments")
        engine = getattr(self, "_experiment_engine", None)
        if engine is not None and experiments_meta is not None:
            # attach the DecisionEngine (same experiments) BEFORE
            # restore(), like tenants re-register before restore: the
            # saved always-valid p-values and verdicts land wholesale
            engine.load_checkpoint_state(experiments_meta)
        slo_meta = serve_meta.get("slo")
        slo_engine = getattr(self, "_slo_engine", None)
        if slo_engine is not None and slo_meta is not None:
            # same attach-before-restore contract as experiments: the
            # saved error budgets land wholesale, bitwise
            slo_engine.load_checkpoint_state(slo_meta)
        if _obs_enabled():
            _obs_gauge("serve.tenants", float(len(self._tenants)))
        return manifest

    # ------------------------------------------------------------------
    # Warm start (metrics_tpu.engine)
    # ------------------------------------------------------------------

    def _warmup_manifest(self) -> Optional[Dict[str, Any]]:
        """The warmup half of a checkpoint manifest: the compile
        environment plus every fold bucket each tenant ever resolved —
        enough for :meth:`warmup` in a fresh process to replay exactly the
        programs this node ran (program keys are re-derived from the
        registered schemas, so the manifest stays small and carries no
        executables)."""
        if self._engine is None:
            return None
        from metrics_tpu.engine import environment_manifest

        tenants: Dict[str, List[int]] = {}
        for tenant_id, tenant in sorted(self._tenants.items()):
            # snapshot under the tenant lock: a concurrent worker fold
            # adds its bucket via fold_program() and a set mutated during
            # sorted()'s iteration raises
            with tenant.lock:
                tenants[tenant_id] = sorted(tenant.warm_buckets)
        return {"environment": environment_manifest(), "tenants": tenants}

    def warmup(self, path: Optional[str] = None) -> int:
        """Resolve and prime every fold executable BEFORE accepting traffic.

        Replays the warmup manifest of the newest (or given) checkpoint —
        tenants must be re-registered first, exactly like :meth:`restore` —
        falling back to ``prewarm_buckets`` for tenants the manifest does
        not name (or when no checkpoint exists). With a warm
        :class:`~metrics_tpu.engine.ProgramStore` every program
        deserializes straight into the runtime: the revived node's first
        fold performs zero backend compiles. Each program is also executed
        once on identity rows, so transfer paths are hot and a corrupt
        cached executable fails HERE, not under traffic.

        The manifest's recorded jax version / backend / topology are
        validated against the live process: a mismatch is a loud one-shot
        warning plus a fresh compile under the live keys (the recorded
        keys would name executables this process must not load) — never a
        crash, never a silently wrong executable.

        Returns the number of programs resolved. No-op (0) unless the
        aggregator was constructed with an AOT ``engine=``.
        """
        if self._engine is None:
            return 0
        warm: Dict[str, set] = {
            tenant_id: set(self._prewarm_buckets) for tenant_id in self._tenants
        }
        manifest = None
        if self._manager is not None:
            try:
                manifest = self._manager.read_manifest(path)
            except (OSError, ValueError):
                manifest = None
        if manifest is not None:
            serve_meta = (manifest.get("extra") or {}).get("serve") or {}
            recorded = serve_meta.get("warmup") or {}
            env = recorded.get("environment") or {}
            if env:
                from metrics_tpu.engine import environment_mismatches

                mismatches = environment_mismatches(env)
                if mismatches:
                    if _obs_enabled():
                        for field in mismatches:
                            _obs_inc("compile.warmup_mismatches", field=field)
                    if not self._warned_warmup_mismatch:
                        self._warned_warmup_mismatch = True
                        detail = "; ".join(
                            f"{field}: checkpoint={old!r} live={new!r}"
                            for field, (old, new) in sorted(mismatches.items())
                        )
                        warnings.warn(
                            f"aggregator {self.name!r} warmup: the checkpoint was"
                            f" saved under a different compile environment ({detail})."
                            " Cached executables from that environment will NOT be"
                            " loaded; programs are compiled fresh under the live"
                            " keys — correct, just cold.",
                            RuntimeWarning,
                            stacklevel=2,
                        )
            for tenant_id, buckets in (recorded.get("tenants") or {}).items():
                if tenant_id in warm:
                    warm[tenant_id].update(int(b) for b in buckets)
        for buckets in warm.values():
            if not buckets:
                # neither prewarm config nor manifest names a bucket: warm
                # the single-client program as the minimal useful floor
                buckets.add(1)
        warmed = 0
        for tenant_id, buckets in sorted(warm.items()):
            tenant = self._tenants[tenant_id]
            for bucket in sorted(buckets):
                tenant.prime_program(bucket)
                warmed += 1
        if _obs_enabled():
            _obs_gauge("serve.warmed_programs", float(warmed), node=self.name)
        return warmed

    def _require_manager(self):
        if self._manager is None:
            raise ServeError(
                f"aggregator {self.name!r} has no checkpoint_dir; construct with"
                " Aggregator(..., checkpoint_dir=...) to enable save/restore"
            )
        return self._manager

    def _registry_state(self, empty: bool = False) -> Tuple["_RegistryState", Dict[str, Any]]:
        """(orbax-safe pytree proxy, manifest metadata). Hostile tenant /
        client ids never become filesystem paths: slots are positional
        (``t000000``/``c000000``/``l000000``) and the id mapping rides the
        JSON manifest."""
        tree: Dict[str, Any] = {}
        meta: Dict[str, Any] = {"tenants": {}, "clients": {}, "retired": {}}
        warmup = self._warmup_manifest()
        if warmup is not None:
            meta["warmup"] = warmup
        if self._generation_fences:
            # generation fences ride the manifest (tiny: identity -> int):
            # a root healed from checkpoint must keep refusing the zombie
            # its predecessor fenced out, or the failover guard dies with
            # the process it protects against
            meta["fences"] = {k: int(v) for k, v in sorted(self._generation_fences.items())}
        if self.manifest_extra:
            meta["node_meta"] = dict(self.manifest_extra)
        if not empty:
            for t_idx, tenant_id in enumerate(sorted(self._tenants)):
                tenant = self._tenants[tenant_id]
                tslot = f"t{t_idx:06d}"
                meta["tenants"][tslot] = {
                    "id": tenant_id,
                    "schema_hash": tenant.schema_hash,
                    "schema": tenant.schema,
                }
                with tenant.lock:
                    order = sorted(tenant.clients)
                    meta["clients"][tslot] = order
                    meta["retired"][tslot] = {
                        client_id: [*(journal.watermark or (0, 0)), journal.folded]
                        for client_id, journal in sorted(tenant.retired.items())
                    }
                    slots: Dict[str, Any] = {}
                    for c_idx, client_id in enumerate(order):
                        slot = tenant.clients[client_id]
                        wm = slot.journal.watermark or (-1, -1)
                        slots[f"c{c_idx:06d}"] = {
                            "wm": np.asarray(wm, dtype=np.int64),
                            "folded": np.asarray(slot.journal.folded, dtype=np.int64),
                            "leaves": {f"l{i:06d}": leaf for i, leaf in enumerate(slot.leaves)},
                            "consensus": {
                                f"l{i:06d}": leaf for i, leaf in enumerate(slot.consensus)
                            },
                        }
                if slots:
                    tree[tslot] = slots
            if self._history is not None:
                # the retention rings ride the same checkpoint (atomic
                # publish, rotation, one manifest): "history" cannot
                # collide with the positional t%06d tenant slots
                htree, hmeta = self._history.state_for_checkpoint()
                if htree:
                    tree["history"] = htree
                meta["history"] = hmeta
            engine = getattr(self, "_experiment_engine", None)
            if engine is not None:
                # experiment decisions + evidence are tiny JSON records:
                # they ride the manifest beside the history rings, so a
                # restored root resumes with bitwise-identical verdicts
                meta["experiments"] = engine.state_for_checkpoint()
            slo_engine = getattr(self, "_slo_engine", None)
            if slo_engine is not None:
                # error budgets are consumed capital: a restore that reset
                # them would hand every flooding tenant a fresh budget per
                # failover, so they ride the manifest like decisions do
                meta["slo"] = slo_engine.state_for_checkpoint()
        return _RegistryState(tree), meta


def _jsonable(value: Any) -> Any:
    """Array/scalar -> plain JSON value (lists for non-scalars)."""
    arr = np.asarray(value)
    if arr.ndim == 0:
        return arr.item()
    return arr.tolist()


class _RegistryState:
    """Duck-typed single-"metric" adapter so the whole client-snapshot
    registry rides :class:`~metrics_tpu.ft.CheckpointManager` unchanged
    (atomic publish, rotation, manifest, monotonic discovery)."""

    _aux_attrs: Tuple[str, ...] = ()

    def __init__(self, tree: Dict[str, Any]) -> None:
        self.tree = tree
        self._update_count = 0
        self._computed = None
        self._defaults: Dict[str, Any] = {}

    def state_pytree(self) -> Dict[str, Any]:
        return self.tree

    def load_state_pytree(self, state: Dict[str, Any]) -> None:
        self.tree = state
