"""Elastic fleet membership: live join / drain / split / merge, invisibly.

The aggregation tree (:mod:`metrics_tpu.serve.tree`) composes to any depth,
but until this module its topology was hand-built and frozen at
construction. At millions-of-clients scale the fleet must grow, shrink and
rebalance **while traffic flows**, and a rebalance must be provably
invisible at the root. Three pieces deliver that:

* **Consistent-hash routing** — :class:`HashRing` (seeded, virtual nodes)
  behind a :class:`Router` that clients and the load generator consult
  *per ship*. Membership change moves only the clients whose ring
  assignment actually changed (≈ ``moved/total ~ 1/n`` per join), never
  reshuffles the fleet.
* **The rebalance protocol** — every client→leaf move is a
  **handoff + tombstone-retire + cumulative re-ship**:

  1. the old home re-materializes the client's latest *accepted* snapshot
     (identity and watermark preserved —
     :meth:`~metrics_tpu.serve.Aggregator.client_snapshot`) and ingests it
     into the new home, so nothing accepted is ever lost even if the
     client never ships again;
  2. the old home **retires** the slot, leaving a watermark tombstone
     (:meth:`~metrics_tpu.serve.Aggregator.retire_client`): its next fold
     excludes the client (no double count), while a late duplicate of a
     final ship is dropped against the tombstone instead of resurrecting
     re-homed state;
  3. the client's own next cumulative ship — routed to the new home by the
     ring — dedups against exactly the handed-off watermark, so the
     overlap between handoff and live traffic is safe **by construction**
     (the same exactly-once argument the tree invariant already rests on).

  Because every (tenant, client) snapshot lives in exactly one slot at
  every step, the root fold stays **bitwise-equal to the flat oracle
  merge** of the accepted snapshots throughout membership change — the
  ``elastic_smoke`` CI step pins it under seeded churn at 10% wire faults.
* **Admission / drain** — a joining node registers tenants, warms its fold
  executables through the :mod:`metrics_tpu.engine` store, and is admitted
  to the ring only after a readiness probe; a draining node stops
  admitting (:class:`~metrics_tpu.serve.aggregator.DrainingError`), folds
  its queue **to empty** (:meth:`~metrics_tpu.serve.Aggregator.drain` —
  nothing accepted may be stranded), ships one final cumulative snapshot,
  hands its clients off, and retires its ``node:*`` identity upstream.
  **Split and merge are compositions** of exactly these two operations
  (split = join a sibling; merge = drain the underloaded node), so there
  is one correctness mechanism, not four.

:class:`Autoscaler` closes the loop: it reads the fleet's scaling signals
— the ``serve.queue_depth{node=}`` worst series and the per-node
``serve.hop_queue_wait_ms`` p99 — off the **federated** obs snapshot
(:mod:`metrics_tpu.obs.federation`, so a multi-process root sees the whole
fleet) and executes split/merge through the fleet, one action per step
with a cooldown.

Every rebalance is observable: ``serve.rebalances{kind=join|drain|split|merge}``
counters, the ``serve.rebalance_ms{kind=}`` latency histogram, and a
``serve.rebalance_started_ts{node=}`` gauge the
:class:`~metrics_tpu.obs.health.HealthMonitor`'s ``rebalance_stuck``
condition watches — all federated to the root's ``/metrics`` like any
other series. See ``docs/serving.md`` §7 "Elasticity".
"""
import bisect
import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import observe as _obs_observe
from metrics_tpu.obs.registry import set_gauge as _obs_gauge
from metrics_tpu.serve.aggregator import Aggregator, ServeError
from metrics_tpu.serve.tree import AggregationTree, AggregatorNode

__all__ = [
    "Autoscaler",
    "ElasticFleet",
    "HashRing",
    "RebalancePreconditionError",
    "Router",
]


class RebalancePreconditionError(ServeError):
    """A rebalance was refused because its preconditions do not hold
    (draining the root / the last ring member / a dead node / a node under
    a dead parent). NOT retryable as-is — the operator must change the
    fleet's state first (heal, grow, pick another node); the HTTP surface
    answers 409, distinct from a genuine drain timeout's 500."""


class HashRing:
    """Seeded consistent-hash ring with virtual nodes.

    Each member owns ``vnodes`` points on a 64-bit ring (sha256 of
    ``seed|member#i``); a key is assigned to the owner of the first point
    clockwise from its own hash. The properties the rebalance protocol
    relies on, pinned by ``tests/serve/test_elastic.py``:

    * **deterministic** — same seed, same members ⇒ same assignment, on
      every process (clients and aggregators can compute routes
      independently);
    * **minimal movement** — adding a member reassigns only the keys whose
      clockwise-first point now belongs to the new member (≈ ``1/n`` of
      them); removing a member reassigns only *its* keys. Every other
      key's assignment is untouched, which is what bounds a rebalance's
      blast radius.

    Args:
        vnodes: virtual nodes per member (more ⇒ smoother balance,
            bigger ring; 64 keeps the max/min leaf load within ~2x).
        seed: folded into every hash so distinct fleets get distinct,
            reproducible rings.
    """

    def __init__(self, *, vnodes: int = 64, seed: int = 0) -> None:
        if int(vnodes) < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self._vnodes = int(vnodes)
        self._seed = int(seed)
        self._points: List[Tuple[int, str]] = []  # sorted (hash, member)
        self._members: set = set()

    def _hash(self, key: str) -> int:
        digest = hashlib.sha256(f"{self._seed}|{key}".encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def add(self, member: str) -> None:
        member = str(member)
        if member in self._members:
            raise ValueError(f"ring member {member!r} already present")
        self._members.add(member)
        for i in range(self._vnodes):
            bisect.insort(self._points, (self._hash(f"{member}#{i}"), member))

    def remove(self, member: str) -> None:
        member = str(member)
        if member not in self._members:
            raise ValueError(f"ring member {member!r} not present")
        self._members.remove(member)
        self._points = [p for p in self._points if p[1] != member]

    def assign(self, key: str) -> str:
        """The member owning ``key`` under the current membership."""
        if not self._points:
            raise ServeError("hash ring is empty: no members to assign to")
        h = self._hash(str(key))
        idx = bisect.bisect_right(self._points, (h, "￿")) % len(self._points)
        return self._points[idx][1]

    def members(self) -> List[str]:
        return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: object) -> bool:
        return member in self._members


class Router:
    """The client→leaf assignment surface clients consult **per ship**.

    A thin, thread-safe view over a :class:`HashRing` plus the live
    name → :class:`~metrics_tpu.serve.tree.AggregatorNode` map:
    ``route(client_id)`` answers "which aggregator do I ingest into right
    now". :attr:`version` bumps on every membership change, so a caller
    caching a route can cheaply detect staleness — but the contract is to
    consult the router per ship; a stale route during a rebalance is
    exactly the overlap the handoff watermarks absorb.
    """

    def __init__(self, *, vnodes: int = 64, seed: int = 0) -> None:
        self._ring = HashRing(vnodes=vnodes, seed=seed)
        self._nodes: Dict[str, AggregatorNode] = {}
        self._lock = threading.Lock()
        self._version = 0

    @property
    def version(self) -> int:
        """Monotonic membership-change counter."""
        return self._version

    def add(self, name: str, node: AggregatorNode) -> None:
        with self._lock:
            self._ring.add(name)
            self._nodes[str(name)] = node
            self._version += 1

    def remove(self, name: str) -> AggregatorNode:
        with self._lock:
            self._ring.remove(name)
            node = self._nodes.pop(str(name))
            self._version += 1
            return node

    def assign(self, client_id: str) -> str:
        """Ring member (leaf name) owning ``client_id``."""
        with self._lock:
            return self._ring.assign(client_id)

    def node(self, client_id: str) -> AggregatorNode:
        with self._lock:
            return self._nodes[self._ring.assign(client_id)]

    def route(self, client_id: str) -> Aggregator:
        """The aggregator ``client_id`` ships to under current membership."""
        return self.node(client_id).aggregator

    def member_node(self, name: str) -> AggregatorNode:
        with self._lock:
            node = self._nodes.get(str(name))
        if node is None:
            raise ServeError(f"{name!r} is not a ring member")
        return node

    def members(self) -> List[str]:
        with self._lock:
            return self._ring.members()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def __contains__(self, name: object) -> bool:
        with self._lock:
            return name in self._ring


class ElasticFleet:
    """Live membership operations over an :class:`~metrics_tpu.serve.AggregationTree`.

    Wraps a tree with a seeded :class:`Router` over its leaves and
    executes the four rebalance kinds — **join**, **drain**, **split**,
    **merge** — as compositions of the admission and drain protocols (one
    correctness mechanism). Operations are serialized under one lock: a
    rebalance is a topology mutation, and two racing mutations could
    each hand the same client off.

    Example::

        tree = AggregationTree(fan_out=(2, 4), tenants={"t": factory})
        fleet = ElasticFleet(tree, seed=7)
        fleet.router.route(client_id).ingest(payload)   # per ship
        fleet.join_node()                               # grow
        fleet.drain_node("L2.1")                        # shrink, invisibly
        fleet.pump()

    Args:
        tree: the tree to manage (its leaves seed the ring).
        vnodes / seed: ring parameters (see :class:`HashRing`).
        drain_timeout_s: bound on a draining node's queue-to-empty flush.
    """

    def __init__(
        self,
        tree: AggregationTree,
        *,
        vnodes: int = 64,
        seed: int = 0,
        drain_timeout_s: float = 30.0,
    ) -> None:
        self.tree = tree
        self.router = Router(vnodes=vnodes, seed=seed)
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = threading.RLock()
        self._split_counter = 0
        for leaf in tree.leaves:
            self.router.add(leaf.name, leaf)

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------

    @property
    def root(self) -> AggregatorNode:
        return self.tree.root

    def pump(self, rounds: int = 1) -> int:
        return self.tree.pump(rounds)

    def _resolve(self, node_or_name: Union[str, AggregatorNode]) -> AggregatorNode:
        if isinstance(node_or_name, AggregatorNode):
            return node_or_name
        return self.tree.node_by_name(str(node_or_name))

    def _with_rebalance(self, kind: str, target: str, fn: Callable[[], Any]) -> Any:
        """Run one rebalance under the telemetry contract: the
        ``serve.rebalance_started_ts{node=}`` gauge — labeled with the
        node being rebalanced, so a firing ``rebalance_stuck`` alert names
        the wedged operation's target, not just "something is stuck" — is
        set for the duration (what ``HealthMonitor(rebalance_stuck_s=...)``
        watches), and a completed rebalance lands one
        ``serve.rebalance_ms{kind=}`` sample plus a
        ``serve.rebalances{kind=}`` count — federated to the root's
        ``/metrics`` like every other series."""
        # the whole span — telemetry stamp included — runs under the fleet
        # lock (reentrant, so _join/_drain's own acquire is free): a second
        # rebalance queued behind a wedged one must BLOCK before stamping,
        # or it would overwrite the wedged rebalance's start timestamp and
        # reset the very clock rebalance_stuck pages on
        with self._lock:
            armed = _obs_enabled()
            t0 = time.perf_counter()
            if armed:
                _obs_gauge("serve.rebalance_started_ts", time.time(), node=target)
            try:
                result = fn()
            finally:
                if armed:
                    _obs_gauge("serve.rebalance_started_ts", 0.0, node=target)
            if armed:
                _obs_observe("serve.rebalance_ms", (time.perf_counter() - t0) * 1000.0, kind=kind)
                _obs_inc("serve.rebalances", kind=kind)
            return result

    def _handoff_client(
        self, src: AggregatorNode, client_id: str, targets: Optional[set] = None
    ) -> int:
        """Move one end client's accepted snapshots to its ring-assigned
        home, tenant by tenant. The read side is the ATOMIC
        :meth:`~metrics_tpu.serve.Aggregator.takeout_client` (snapshot +
        tombstone-retire under one lock hold — a separate read-then-retire
        would let the source's live flush worker accept a newer ship in
        between and tombstone state that was never captured), so every
        (tenant, client) slot lives in exactly one place at every step —
        the invariant the bitwise root equality rests on. ``targets``
        collects the receiving nodes; the caller flushes them once so the
        rebalance completes with every moved snapshot ACCEPTED, not merely
        queued."""
        from metrics_tpu.serve.aggregator import BackpressureError
        from metrics_tpu.serve.resilience import CircuitOpenError, QuarantinedClientError

        moved = 0
        for tenant_id in src.aggregator.tenants():
            payload = src.aggregator.takeout_client(tenant_id, client_id)
            if payload is None:
                continue  # this tenant holds no slot for the client
            target = self.router.node(client_id)
            try:
                try:
                    target.aggregator.ingest(payload, block=False)
                except (BackpressureError, CircuitOpenError, QuarantinedClientError):
                    # control-plane override of the target's ADMISSION
                    # gates: this snapshot was already accepted and
                    # validated once, and aborting a rebalance midway would
                    # leave the fleet double-counting (old ships frozen
                    # upstream, new homes filling). The bounded queue
                    # guards unbounded producers and the firewall judges
                    # live wire traffic — neither describes a slot-sized
                    # handoff of vetted state, so accept it synchronously.
                    # (_accept still runs the poison check, so a NaN can
                    # not ride the override into the fold.)
                    target.aggregator._accept(payload, time.perf_counter())
            except Exception:
                # delivery failed outright (a bug-level surprise): put the
                # state back where it came from — the takeout's tombstone
                # matches the payload's watermark, so this re-admits it —
                # and let the rebalance raise with nothing lost
                src.aggregator._accept(payload, time.perf_counter())
                raise
            if targets is not None:
                targets.add(target)
            moved += 1
        return moved

    def _end_clients(self, node: AggregatorNode) -> List[str]:
        """End-client ids with a live slot on ``node`` (``node:*`` child
        identities excluded — subtrees re-home by re-parenting + cumulative
        re-ship, not by handoff)."""
        out: set = set()
        agg = node.aggregator
        for tenant_id in agg.tenants():
            tenant = agg._tenant(tenant_id)
            with tenant.lock:
                out.update(c for c in tenant.clients if not c.startswith("node:"))
        return sorted(out)

    def _rehome_into(self, target: AggregatorNode, targets: Optional[set] = None) -> int:
        """Hand every OTHER ring member's end clients that the ring now
        assigns to ``target`` over to it, converging under live traffic:
        each source is FLUSHED first (a client whose accepted payload still
        sits queued-but-unfolded has no slot yet — skipping it would leave
        a frozen copy behind once the flush lands it), and the sweep
        repeats until a pass moves nothing, so ships that land at a source
        mid-sweep are caught by the next pass. Returns clients moved."""
        rehomed = 0
        max_passes = 10  # converges in 1-2 passes; bound it regardless
        for attempt in range(max_passes):
            moved_this_pass = 0
            for member in self.router.members():
                if member == target.name:
                    continue
                src = self.router.member_node(member)
                src.aggregator.flush()
                for client_id in self._end_clients(src):
                    if self.router.assign(client_id) == target.name:
                        moved_this_pass += 1 if self._handoff_client(src, client_id, targets) else 0
            rehomed += moved_this_pass
            if not moved_this_pass:
                break
        else:
            # no silent caps: falling out with work still moving means NEW
            # slots kept appearing at sources faster than the sweep drained
            # them; returning "success" would leave stragglers' old slots
            # folding next to their new homes — a double count nobody sees.
            # Raising hands control to the caller's rollback (join) or the
            # operator (retry when ingest pressure subsides).
            raise ServeError(
                f"re-homing into {target.name!r} did not converge after"
                f" {max_passes} sweep passes ({rehomed} clients moved and new"
                " slots kept appearing) — ingest pressure is outrunning the"
                " rebalance; retry when it subsides"
            )
        return rehomed

    # ------------------------------------------------------------------
    # readiness
    # ------------------------------------------------------------------

    def node_ready(self, node: AggregatorNode) -> Tuple[bool, List[str]]:
        """The admission probe: a node enters the ring only when it (a) is
        alive, (b) carries every fleet tenant at the exact fleet schema,
        (c) is not draining, (d) runs a flush worker iff the fleet does,
        and (e) completes a probe flush. Returns ``(ready, reasons)``."""
        reasons: List[str] = []
        if node.is_dead:
            return False, ["node is dead (hard-killed)"]
        agg = node.aggregator
        root_agg = self.tree.root.aggregator
        if agg.tenants() != root_agg.tenants():
            reasons.append(
                f"tenant registry mismatch: node has {agg.tenants()}, fleet has {root_agg.tenants()}"
            )
        else:
            for tenant_id in agg.tenants():
                if agg.schema_hash(tenant_id) != root_agg.schema_hash(tenant_id):
                    reasons.append(f"schema hash mismatch for tenant {tenant_id!r}")
        if getattr(agg, "draining", False):
            reasons.append("node is draining")
        if not node.parent_reachable():
            # admitting a node whose uplink is down would blackhole its
            # keyspace share at the root until a heal — every forward()
            # would drop (serve.forward_errors) while it keeps accepting
            reasons.append("parent unreachable (dead or partitioned uplink)")
        if root_agg.worker_alive() is not None and agg.worker_alive() is not True:
            reasons.append("fleet runs background flush workers but this node's is not alive")
        try:
            agg.flush()
        except Exception as err:  # noqa: BLE001 — the probe judges, never raises
            reasons.append(f"probe flush failed: {type(err).__name__}: {err}")
        return (not reasons), reasons

    # ------------------------------------------------------------------
    # join / drain / split / merge
    # ------------------------------------------------------------------

    def join_node(
        self,
        name: Optional[str] = None,
        parent: Optional[AggregatorNode] = None,
        *,
        _kind: str = "join",
    ) -> AggregatorNode:
        """Admit a new leaf while traffic flows.

        The join protocol: build the node with the tree's retained
        factories/policy/engine (tenants registered), **warm** its fold
        executables through the :mod:`metrics_tpu.engine` store
        (``warmup()`` — zero backend compiles on the first fold when the
        store is hot), start a flush worker iff the fleet runs them, run
        the **readiness probe** — and only then admit it to the ring. Ring
        admission triggers the rebalance: exactly the clients whose
        assignment moved to the new node are handed off from their old
        homes (snapshot + tombstone, watermarks preserved). A node that
        fails its probe is detached again and the join raises — a
        half-ready node must never own keys. Returns the admitted node."""
        # label the in-flight gauge with the joining node when its name is
        # known (splits always name the sibling); an anonymous join falls
        # back to the coordinator's (root's) identity
        target = str(name) if name is not None else self.tree.root.name
        return self._with_rebalance(_kind, target, lambda: self._join(name, parent))

    def _join(self, name: Optional[str], parent: Optional[AggregatorNode]) -> AggregatorNode:
        with self._lock:
            node = self.tree.add_node(name, parent)
            try:
                node.last_warmup_programs = node.aggregator.warmup()
                if self.tree.root.aggregator.worker_alive() is not None:
                    # the fleet drains queues with background workers; a
                    # joining node nobody start()s would silently freeze
                    node.aggregator.start()
                ready, reasons = self.node_ready(node)
                if not ready:
                    raise ServeError(
                        f"joining node {node.name!r} failed its readiness probe"
                        f" ({'; '.join(reasons)}); it was NOT admitted to the ring"
                    )
            except Exception:
                # a failed admission must not leak the worker started above:
                # the detached aggregator's daemon thread would keep waking
                # per flush interval forever (one orphan per failed join)
                try:
                    node.aggregator.stop()
                except Exception:  # noqa: BLE001 — rollback must not mask the probe failure
                    pass
                self.tree.remove_node(node)
                raise
            self.router.add(node.name, node)
            try:
                # re-home exactly the clients the ring moved to the new node
                # (sources flushed first; sweep repeats until dry — see
                # _rehome_into for why both matter under live traffic)
                self._rehome_into(node)
                # the join completes with every moved snapshot ACCEPTED at
                # the new node (watermark queryable), not merely queued
                node.aggregator.flush()
            except Exception:
                # roll the ADMISSION back, mirroring the drain's failure
                # path: a node left in the ring with the re-home incomplete
                # would keep receiving its share of ships while the
                # not-yet-moved clients' old slots fold on — a permanent
                # double count, and the join would not even be retryable
                # (the name is taken). Leave the ring, hand everything that
                # already moved in back to its restored old homes, detach.
                self.router.remove(node.name)
                # FLUSH before enumerating: snapshots already handed off sit
                # in this node's ingest queue until folded — enumerating the
                # slot table alone would miss (and then discard) them
                node.aggregator.flush()
                targets: set = set()
                for client_id in self._end_clients(node):
                    self._handoff_client(node, client_id, targets)
                for target in targets:
                    target.aggregator.flush()
                try:
                    node.aggregator.stop()
                except Exception:  # noqa: BLE001 — rollback must not mask the cause
                    pass
                self.tree.remove_node(node)
                raise
            if _obs_enabled():
                _obs_gauge("serve.ring_members", float(len(self.router)), node=self.tree.root.name)
            return node

    def drain_node(
        self,
        node_or_name: Union[str, AggregatorNode],
        *,
        timeout_s: Optional[float] = None,
        _kind: str = "drain",
    ) -> Dict[str, Any]:
        """Remove a node while traffic flows, losing nothing it accepted.

        The drain protocol, in order: (1) leave the ring — the router
        stops assigning new ships here; (2)
        :meth:`~metrics_tpu.serve.Aggregator.drain` — admission refused,
        the ingest queue folded **to empty** (bounded by the timeout; a
        queued-but-unfolded payload is never stranded), worker stopped;
        (3) one final cumulative ship upward, so the parent's view stays
        complete while re-homed state is in flight; (4) every end client
        handed off to its new ring home (snapshot + tombstone-retire);
        (5) child subtrees re-parented to a peer (ship sequence reset so
        ``_resume_seq`` re-derives against the new parent — the heal
        mechanism, reused); (6) the node's ``node:*`` identity retired at
        its parent, tombstoned so a late duplicate of the final ship
        cannot resurrect the moved state; (7) the node detached. Returns
        an action summary dict."""
        node = self._resolve(node_or_name)
        # coerce BEFORE any mutation: a malformed timeout must fail here,
        # not after the ring exit (which would roll back for nothing)
        timeout_s = None if timeout_s is None else float(timeout_s)
        return self._with_rebalance(_kind, node.name, lambda: self._drain(node, timeout_s))

    def _drain(self, node: AggregatorNode, timeout_s: Optional[float]) -> Dict[str, Any]:
        with self._lock:
            if node is self.tree.root:
                raise RebalancePreconditionError("cannot drain the root: it is the state of record")
            if node.is_dead:
                raise RebalancePreconditionError(
                    f"node {node.name!r} is dead; drain needs a live node —"
                    " heal it first (Supervisor.heal) or leave it to supervision"
                )
            if node.parent is not None and node.parent.is_dead:
                # without a live parent the final ship drops AND the
                # node:* tombstone-retire is impossible — a parent healed
                # later from a pre-drain checkpoint would resurrect the
                # drained child's frozen state next to the re-homed live
                # clients, forever. Same rule as add_node: heal first.
                raise RebalancePreconditionError(
                    f"cannot drain {node.name!r}: its parent {node.parent.name!r} is"
                    " dead, so the final ship and the tombstoned retirement have"
                    " nowhere to land — heal the parent first (Supervisor.heal)"
                )
            in_ring = node.name in self.router
            if in_ring and len(self.router) <= 1:
                raise RebalancePreconditionError("cannot drain the last ring member: clients need a home")
            if in_ring:
                self.router.remove(node.name)
            try:
                drained = node.aggregator.drain(
                    self.drain_timeout_s if timeout_s is None else float(timeout_s)
                )
            except Exception:
                # none of THIS node's slots moved yet: RE-OPEN admission and
                # re-admit to the ring, so a node left out of it while still
                # refusing ingest cannot blackhole ~1/n of the keyspace.
                # But traffic did not stop during the wedged drain — clients
                # this node owns were routed to OTHER leaves meanwhile, and
                # the restored ring points their future ships back here:
                # those interim copies must be handed back (not frozen at
                # the detour leaves forever, a permanent double count)
                node.aggregator.resume_admission()
                if in_ring:
                    self.router.add(node.name, node)
                    self._rehome_into(node)
                    node.aggregator.flush()
                raise
            # final cumulative ship: everything this node ever accepted is
            # at the parent BEFORE the handoffs start — the no-loss half of
            # the protocol (forward() survives transport failures by
            # contract, so from here the drain runs to completion; the
            # handoffs themselves absorb target backpressure rather than
            # abort, because a half-rebalanced fleet double-counts)
            node.forward()
            # DETACH under the forward lock: a concurrent pump's in-flight
            # forward either completed before this (its ship is folded by
            # the parent flush below and retired with the rest) or starts
            # after and no-ops — without this, a late ship landing after
            # the retire would ADVANCE the tombstone and be re-admitted as
            # a rejoined node, resurrecting the frozen state forever
            # (caught by the concurrent-pump verify drive)
            with node._forward_lock:
                node.detached = True
            # drain() folded the queue to empty with admission closed, so
            # the slot table is complete and frozen — one enumeration pass
            # suffices here (unlike the live-source join sweep)
            clients = self._end_clients(node)
            targets: set = set()
            for client_id in clients:
                self._handoff_client(node, client_id, targets)
            for target in targets:
                # same acceptance guarantee as the join: when drain_node
                # returns, every re-homed client's watermark is queryable
                # at its new home — the no-loss check the smoke asserts
                target.aggregator.flush()
            kids = self.tree.children(node)
            if kids:
                peers = [
                    n
                    for lvl in self.tree.levels
                    if node in lvl
                    for n in lvl
                    if n is not node and not n.is_dead
                ] or [node.parent]
                for i, child in enumerate(kids):
                    self.tree.reparent(child, peers[i % len(peers)])
            if node.parent is not None and not node.parent.is_dead:
                # tombstone the upward identity: the parent stops folding
                # the frozen final ship (its content now lives in the new
                # homes), and a chaos-duplicated copy of that ship drops
                # against the tombstone instead of double counting forever.
                # The parent must FLUSH first — the final ship may still sit
                # in its ingest queue, and a retire that runs before the
                # acceptance would tombstone nothing, letting the next flush
                # resurrect the slot (caught by the drain bitwise tests).
                node.parent.aggregator.flush()
                node.parent.aggregator.retire_client(f"node:{node.name}")
                if node.parent.aggregator._manager is not None:
                    # make the retirement DURABLE: a checkpointing parent
                    # (the root) healed from its newest checkpoint must come
                    # back post-drain — tombstones ride the manifest, but
                    # only a checkpoint taken after the retire carries them;
                    # reviving a pre-drain one would resurrect the drained
                    # child's frozen final ship as a live client forever
                    node.parent.aggregator.save()
            self.tree.remove_node(node)
            if _obs_enabled():
                _obs_gauge("serve.ring_members", float(len(self.router)), node=self.tree.root.name)
            return {
                "node": node.name,
                "drained": int(drained),
                "rehomed_clients": len(clients),
                "reparented": [k.name for k in kids],
            }

    def split_node(
        self,
        node_or_name: Union[str, AggregatorNode],
        name: Optional[str] = None,
    ) -> AggregatorNode:
        """Relieve an overloaded leaf by **joining a sibling** under the
        same parent — a pure composition of the join protocol (counted as
        ``kind=split``). The ring hands the new sibling its share of keys,
        including part of the overloaded node's; nothing else moves."""
        victim = self._resolve(node_or_name)
        if victim.name not in self.router:
            raise ServeError(
                f"{victim.name!r} is not a ring member; split applies to leaves"
            )
        if name is None:
            with self._lock:
                self._split_counter += 1
                name = f"{victim.name}.s{self._split_counter}"
        return self.join_node(name, victim.parent, _kind="split")

    def merge_node(
        self,
        node_or_name: Union[str, AggregatorNode],
        *,
        timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Fold an underloaded leaf back into the fleet — a pure
        composition of the drain protocol (counted as ``kind=merge``):
        its keys redistribute to the surviving ring members."""
        return self.drain_node(node_or_name, timeout_s=timeout_s, _kind="merge")


def _series_by_node(table: Dict[str, Any], family: str) -> Dict[str, Any]:
    """Per-node values of one series family out of a snapshot table
    (``family{node=...}`` keys, quoted labels handled by the exposition
    parser). Multi-label series keep the worst (max) value per node."""
    from metrics_tpu.obs.export import _parse_labels

    out: Dict[str, Any] = {}
    prefix = family + "{"
    for key, value in table.items():
        if not key.startswith(prefix) or not key.endswith("}"):
            continue
        labels = dict(_parse_labels(key[len(prefix) : -1]))
        node = labels.get("node")
        if node is None:
            continue
        if isinstance(value, (int, float)):
            out[node] = max(float(value), out.get(node, float("-inf")))
        else:
            # histogram snapshots cannot be max()ed directly: keep the
            # BUSIEST series per node, so if a family ever grows a second
            # label (tenant=, like serve.dedup_drops) dict order cannot
            # silently shadow a saturated series with an idle one
            prev = out.get(node)
            count = float(value.get("count", 0)) if isinstance(value, dict) else 0.0
            prev_count = float(prev.get("count", 0)) if isinstance(prev, dict) else -1.0
            if count >= prev_count:
                out[node] = value
    return out


class Autoscaler:
    """Queue-pressure-driven split/merge policy over an :class:`ElasticFleet`.

    Reads the scaling signals the serving tier already exports — the
    ``serve.queue_depth{node=}`` gauge series and the per-node
    ``serve.hop_queue_wait_ms`` histogram p99 — off the **federated** obs
    snapshot (:func:`metrics_tpu.obs.federation.federated_snapshot`, which
    degrades to the local registry on a single-process fleet), so the
    root's autoscaler sees the deepest queue anywhere in the tree.
    :meth:`evaluate` returns decisions without acting (testable policy);
    :meth:`step` executes at most ONE decision per call, rate-limited by
    ``cooldown_s`` — autoscaling oscillation is a failure mode, and one
    bounded action per cooldown window keeps every step auditable
    (``serve.autoscaler_decisions{action=}``).

    Args:
        fleet: the :class:`ElasticFleet` to act on.
        split_queue_depth: split the worst leaf when its queue depth
            gauge reaches this (``None`` disarms the depth trigger).
        split_queue_wait_p99_ms: split when the worst leaf's
            ``serve.hop_queue_wait_ms`` p99 exceeds this (``None``
            disarms).
        merge_queue_depth: merge the least-loaded leaf when EVERY leaf's
            queue depth is at or below this (``None`` disarms merging).
        min_leaves / max_leaves: hard bounds on ring membership.
        cooldown_s: minimum seconds between executed actions.
    """

    def __init__(
        self,
        fleet: ElasticFleet,
        *,
        split_queue_depth: Optional[float] = None,
        split_queue_wait_p99_ms: Optional[float] = None,
        merge_queue_depth: Optional[float] = None,
        min_leaves: int = 1,
        max_leaves: int = 64,
        cooldown_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if min_leaves < 1:
            raise ValueError(f"min_leaves must be >= 1, got {min_leaves}")
        if max_leaves < min_leaves:
            raise ValueError(f"max_leaves must be >= min_leaves, got {max_leaves}")
        self.fleet = fleet
        self.split_queue_depth = split_queue_depth
        self.split_queue_wait_p99_ms = split_queue_wait_p99_ms
        self.merge_queue_depth = merge_queue_depth
        self.min_leaves = int(min_leaves)
        self.max_leaves = int(max_leaves)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._last_action_s: Optional[float] = None

    def _signals(self) -> Tuple[Dict[str, float], Dict[str, float], set]:
        """(queue depth, queue-wait p99 ms, members-with-a-live-depth-series)
        per ring member, off the federated snapshot. Missing series read
        as 0 for the SPLIT triggers (0 never exceeds a threshold — fails
        safe); the returned presence set lets the merge trigger refuse to
        act on absent telemetry, which would otherwise read a cold/disarmed
        obs registry as a uniformly idle fleet."""
        from metrics_tpu.obs import federation as _federation
        from metrics_tpu.obs.registry import HistogramSnapshot

        snap = _federation.federated_snapshot()
        depths = _series_by_node(snap.get("gauges", {}) or {}, "serve.queue_depth")
        waits_raw = _series_by_node(snap.get("histograms", {}) or {}, "serve.hop_queue_wait_ms")
        members = self.fleet.router.members()
        depth = {m: float(depths.get(m, 0.0)) for m in members}
        present = {m for m in members if m in depths}
        wait: Dict[str, float] = {}
        for m in members:
            hist = waits_raw.get(m)
            if isinstance(hist, dict):
                try:
                    hist = HistogramSnapshot.from_dict(hist)
                except (TypeError, ValueError, KeyError):
                    hist = None
            wait[m] = float(hist.p99) if hist is not None and hist.count else 0.0
        return depth, wait, present

    def evaluate(self) -> List[Dict[str, Any]]:
        """Policy verdicts under the current signals (no side effects):
        a list of ``{"action": "split"|"merge", "node", "reason"}``."""
        members = self.fleet.router.members()
        if not members:
            return []
        depth, wait, present = self._signals()
        decisions: List[Dict[str, Any]] = []
        # each trigger judges ITS OWN worst node: the deepest-queue leaf
        # and the slowest-wait leaf need not be the same one, and testing
        # the wait threshold against the deepest queue would let a
        # saturated-but-shallow leaf starve forever
        worst_depth = max(members, key=lambda m: (depth[m], m))
        worst_wait = max(members, key=lambda m: (wait[m], m))
        over_depth = (
            self.split_queue_depth is not None
            and depth[worst_depth] >= self.split_queue_depth
        )
        over_wait = (
            self.split_queue_wait_p99_ms is not None
            and wait[worst_wait] >= self.split_queue_wait_p99_ms
        )
        if (over_depth or over_wait) and len(members) < self.max_leaves:
            if over_depth:
                target = worst_depth
                signal = f"queue_depth={depth[worst_depth]:.0f}"
            else:
                target = worst_wait
                signal = f"hop_queue_wait_p99={wait[worst_wait]:.1f}ms"
            decisions.append(
                {
                    "action": "split",
                    "node": target,
                    "reason": f"overloaded: {signal} at/over the split threshold",
                }
            )
        elif (
            self.merge_queue_depth is not None
            and len(members) > self.min_leaves
            # every member must have a LIVE depth series: absent telemetry
            # (obs disarmed, registry reset, a node not yet scraped) must
            # be inert, not read as "idle" — the split triggers fail safe
            # on missing data, but merging on it would drain a loaded
            # fleet down to min_leaves one cooldown window at a time
            and present == set(members)
            and all(depth[m] <= self.merge_queue_depth for m in members)
        ):
            idlest = min(members, key=lambda m: (depth[m], wait[m], m))
            decisions.append(
                {
                    "action": "merge",
                    "node": idlest,
                    "reason": (
                        f"underloaded fleet: every leaf's queue_depth <="
                        f" {self.merge_queue_depth:.0f}; folding the idlest leaf back in"
                    ),
                }
            )
        return decisions

    def step(self) -> List[Dict[str, Any]]:
        """Evaluate and execute at most one decision (cooldown-gated);
        returns the executed decisions (empty when idle or cooling down)."""
        now = self._clock()
        if (
            self._last_action_s is not None
            and self.cooldown_s > 0
            and now - self._last_action_s < self.cooldown_s
        ):
            return []
        decisions = self.evaluate()
        if not decisions:
            return []
        decision = decisions[0]
        # the ATTEMPT arms the cooldown, success or not: a wedged merge
        # that raised after its 30s drain timeout must not be re-attempted
        # on the very next tick with zero backoff — that would defeat the
        # anti-oscillation rate limit this class exists to provide
        self._last_action_s = self._clock()
        try:
            if decision["action"] == "split":
                node = self.fleet.split_node(decision["node"])
                decision["joined"] = node.name
            else:
                summary = self.fleet.merge_node(decision["node"])
                decision["rehomed_clients"] = summary["rehomed_clients"]
        except ServeError as err:
            # a failed action is REPORTED, not raised: a periodic policy
            # tick must keep ticking (the fleet's own rollback already left
            # the topology consistent), and the failure is visible both in
            # the returned decision and in obs
            decision["error"] = str(err)
            if _obs_enabled():
                _obs_inc("serve.autoscaler_errors", action=decision["action"])
            return [decision]
        if _obs_enabled():
            _obs_inc("serve.autoscaler_decisions", action=decision["action"])
        return [decision]
