"""Hierarchical aggregation topology: a node is a client of its parent.

Scaling past one aggregator is structural, not algorithmic: because
payloads are cumulative snapshots and the fold is an exact monoid over
sketch / integer-count leaves, an :class:`~metrics_tpu.serve.Aggregator`'s
merged state is itself a valid client snapshot. A node therefore ships its
merged state **upward with the same wire format clients use** — client id
= node name, watermark = a per-node monotonic ship sequence — and the
parent's keep-latest dedup works unchanged. Any depth and any fan-in
compose this way (process → host → pod → global), and the **pinned
invariant** is:

    folding the tree bottom-up produces bitwise the same root state as one
    flat fold over every client's latest snapshot,

for sketch states and integer-valued ``sum`` / all ``min``/``max`` leaves
(``tests/serve/test_tree.py`` pins it across arities and fan-ins; see
``docs/serving.md`` for why non-integer float sums are the one exception —
ordinary float summation is not associative bitwise).

The in-process :class:`AggregationTree` helper wires N levels together for
tests, smokes and the load generator; a production deployment runs the
same :class:`AggregatorNode.forward` loop against a parent's ``/ingest``
endpoint instead of an in-memory parent.
"""
import itertools
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence

from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import new_trace_id as _new_trace_id
from metrics_tpu.obs.registry import observe as _obs_observe
from metrics_tpu.obs.registry import record_hop as _obs_record_hop
from metrics_tpu.serve.aggregator import Aggregator, BackpressureError, DrainingError
from metrics_tpu.serve.resilience import (
    CircuitOpenError,
    NodeDownError,
    QuarantinedClientError,
)
from metrics_tpu.serve.wire import WireFormatError, encode_state

__all__ = ["AggregationTree", "AggregatorNode"]

# send/flush failures forward() survives: the transport (or the peer) is
# down or refusing — transient by contract, repaired by the next interval's
# cumulative ship. Anything else (a bug in OUR encode/fold) still raises.
# DrainingError belongs here too: a parent mid-drain refuses ingest until
# the elastic protocol reparents this child, whose next cumulative ship
# then lands at the NEW parent — one draining hop must not abort the
# whole pump sweep.
_TRANSPORT_ERRORS = (
    NodeDownError,
    BackpressureError,
    CircuitOpenError,
    DrainingError,
    QuarantinedClientError,
    ConnectionError,
    OSError,
)


class _DeadAggregator:
    """Tombstone behind a hard-killed node: every operation raises
    :class:`~metrics_tpu.serve.resilience.NodeDownError`, exactly like the
    RPCs against a SIGKILLed process would fail — until a Supervisor heal
    swaps a rebuilt :class:`Aggregator` back in."""

    def __init__(self, name: str) -> None:
        self.name = name

    def __getattr__(self, item: str) -> Any:
        raise NodeDownError(
            f"aggregator node {self.name!r} is down (hard-killed); a Supervisor"
            " heal() (AggregationTree.revive) must rebuild it before use"
        )


class AggregatorNode:
    """One tree position: an aggregator plus the upward client identity.

    Args:
        aggregator: this node's :class:`~metrics_tpu.serve.Aggregator`.
        parent: the node to ship merged state to (None = root).
        send: override the upward transport — a callable taking the
            encoded payload bytes (default: in-process
            ``parent.aggregator.ingest``). Point it at an HTTP client to
            cross process boundaries; the payload bytes are identical.
        probe: override the parent-reachability probe (zero-arg callable
            returning bool) — across an HTTP boundary, a cheap
            ``GET /healthz/live``. Default: the in-process parent is
            reachable unless hard-killed.
    """

    def __init__(
        self,
        aggregator: Aggregator,
        parent: Optional["AggregatorNode"] = None,
        send: Optional[Callable[[bytes], None]] = None,
        probe: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.aggregator = aggregator
        self.parent = parent
        self._send = send
        self._probe = probe
        self._ship_seq: Optional["itertools.count"] = None
        self._killed_with_worker = False
        # set (under _forward_lock) by the elastic drain after the final
        # ship: a detached node's forward() is a no-op. Without this, a
        # pump thread's in-flight forward could land AFTER the parent
        # tombstone-retired this identity — and, advancing the watermark,
        # be re-admitted under the node-rejoin rule, resurrecting the
        # drained node's frozen state next to its re-homed clients forever
        self.detached = False
        self._forward_lock = threading.Lock()
        # programs resolved by the last revive's warmup (0 = no AOT engine)
        self.last_warmup_programs = 0
        # previous forward's send latency: a hop record is built BEFORE its
        # own send runs, so the wire carries the last completed measurement
        # (the serve.hop_ship_ms{node=} histogram carries every one)
        self._last_ship_ms: Optional[float] = None

    @property
    def name(self) -> str:
        return self.aggregator.name

    # -- liveness --------------------------------------------------------

    @property
    def is_dead(self) -> bool:
        """True after :meth:`hard_kill` and before :meth:`revive`."""
        return isinstance(self.aggregator, _DeadAggregator)

    def hard_kill(self) -> None:
        """Simulate a SIGKILL of this node's process: the in-memory
        aggregator (client snapshots, queue, tenant views) vanishes with
        no cleanup — only on-disk checkpoints survive. The chaos harness's
        in-process analogue of the real-signal arm in
        ``tests/integrations/serve_smoke.py``; children's ships now fail
        with ``NodeDownError`` until a Supervisor heal rebuilds the node.
        """
        agg = self.aggregator
        if isinstance(agg, _DeadAggregator):
            return
        # remember whether the node ran a background flush worker, so a
        # heal rebuilds the node in the SAME drain mode it died in — a
        # revived aggregator nobody start()s would silently re-freeze
        self._killed_with_worker = agg.worker_alive() is True
        # the orphaned worker thread must not keep folding a zombie — a
        # real SIGKILL takes every thread with the process
        agg._stop.set()
        self.aggregator = _DeadAggregator(agg.name)

    def revive(self, aggregator: Aggregator) -> None:
        """Swap a rebuilt aggregator in and RESET the ship sequence so the
        next :meth:`forward` re-runs :meth:`_resume_seq` — without this the
        healed node ships below the parent's recorded watermark and the
        whole subtree is dropped as stale forever. A node that was running
        a background flush worker when killed gets one started on the
        rebuilt aggregator — without it nothing would drain the healed
        node's queue and the silent freeze would be reintroduced by the
        repair itself."""
        self.aggregator = aggregator
        self._ship_seq = None
        if self._killed_with_worker and aggregator.worker_alive() is None:
            aggregator.start()
        self._killed_with_worker = False

    def parent_reachable(self) -> bool:
        """Child-side uplink heartbeat; True at the root."""
        if self._probe is not None:
            return bool(self._probe())
        if self.parent is None:
            return True
        return not self.parent.is_dead

    def _resume_seq(self) -> int:
        """First ship sequence number: one past whatever the parent last
        accepted from this node identity.

        A restarted node (or a fresh node shipping into a parent that
        RESTORED older watermarks) that restarted its sequence at 0 would
        have every ship dropped as stale until the count crawled past the
        parent's recorded watermark — a silently frozen subtree. In-process
        the parent is queryable; across an HTTP boundary the operator's
        transport should recover the watermark the same way (the parent's
        ``/query`` accounting exposes it) or simply use a restart-unique
        high epoch. Tested by the serve smoke's kill-and-restore arm.
        """
        if self.parent is None:
            return 0
        last = -1
        for tenant_id in self.aggregator.tenants():
            try:
                wm = self.parent.aggregator.client_watermark(tenant_id, f"node:{self.name}")
            except Exception:  # noqa: BLE001 — tenant not registered upstream (yet)
                continue
            if wm is not None:
                last = max(last, wm[1])
        return last + 1

    def forward(self) -> int:
        """Flush, then ship one cumulative snapshot per tenant upward.

        The ship sequence number is this node's upward watermark — each
        forward supersedes the previous at the parent (keep-latest), so a
        lost or duplicated ship is repaired by the next interval. Returns
        the number of payloads shipped (0 at the root).

        Transport failures (dead/partitioned parent, backpressure, an open
        circuit upstream, socket errors — and this node itself being
        hard-killed) are SURVIVED, not raised: the drop is counted under
        ``serve.forward_errors{node=}`` with a one-shot warning, and the
        next interval's cumulative snapshot repairs the parent's view —
        raising here would let one dead hop abort the whole pump loop,
        turning a one-node failure into a fleet-wide one.
        """
        with self._forward_lock:
            return self._forward_locked()

    def _forward_locked(self) -> int:
        # the lock is what makes an elastic drain's detach ATOMIC against
        # in-flight forwards: a forward holding it completes (its ship is
        # folded by the parent before the retire); one starting after the
        # detach no-ops. It also serializes concurrent pumps per node,
        # which the ship-sequence counter wants anyway.
        if self.detached:
            return 0
        if self.parent is None and self._send is None:
            return 0
        try:
            self.aggregator.flush()
        except NodeDownError:
            self._note_forward_error("flush")
            return 0
        if self._ship_seq is None:
            self._ship_seq = itertools.count(self._resume_seq())
        seq = next(self._ship_seq)
        shipped = 0
        armed = _obs_enabled()
        for index, tenant_id in enumerate(self.aggregator.tenants()):
            view = self.aggregator.collection(tenant_id, flush=False)
            tenant = self.aggregator._tenant(tenant_id)
            meta = {"node": self.name, "clients": len(tenant.clients)}
            if armed:
                # trace context for the upward hop: follow the CRITICAL PATH
                # — the stalest-encode contribution's id and hop chain, plus
                # this node's own provenance record. e2e freshness at the
                # root then measures the worst client, not the luckiest.
                oldest = tenant.oldest_trace
                hop = {
                    "node": self.name,
                    "accept_ts": oldest["accept_ts"] if oldest else None,
                    "queue_wait_ms": oldest["queue_wait_ms"] if oldest else None,
                    "fold_ms": tenant.last_fold_ms,
                    "ship_ms": self._last_ship_ms,
                }
                meta["trace"] = {
                    "id": oldest["id"] if oldest else _new_trace_id(),
                    "encoded_at": oldest["encoded_at"] if oldest else time.time(),
                    "hops": (list(oldest["hops"]) if oldest else []) + [hop],
                }
                if index == 0 and self._send is not None:
                    # obs federation piggyback, once per forward: this
                    # node's snapshot plus every remote one it holds, so
                    # subtree telemetry transits each hop. Armed-only — the
                    # unarmed wire stays byte-for-byte free of it — and
                    # cross-process-only: an in-process parent shares this
                    # registry and identity, so it would discard the copy
                    # anyway (metrics_tpu.obs.federation).
                    from metrics_tpu.obs import federation as _federation

                    meta["obs_nodes"] = _federation.wire_snapshots()
            # view_lock: this node's background worker (if start()ed) may
            # fold concurrently; encoding leaf-by-leaf without the lock
            # could ship a snapshot mixing two folds' states upward
            with tenant.view_lock:
                try:
                    payload = encode_state(
                        view,
                        tenant=tenant_id,
                        client_id=f"node:{self.name}",
                        watermark=(0, seq),
                        meta=meta,
                    )
                except WireFormatError:
                    if "obs_nodes" not in meta:
                        # the DATA path overflowed the wire cap — a real
                        # contract violation; survive it like a transport
                        # failure, the next interval retries
                        self._note_forward_error("encode:WireFormatError")
                        continue
                    # the telemetry piggyback pushed the payload over the
                    # wire cap: drop the TELEMETRY, never the metric state
                    # — the side-channel must not take down the data path
                    # it observes. Counted so a fleet too big to piggyback
                    # is visible rather than silently unfederated.
                    meta.pop("obs_nodes")
                    _obs_inc("obs.federation_oversized", node=self.name)
                    try:
                        payload = encode_state(
                            view,
                            tenant=tenant_id,
                            client_id=f"node:{self.name}",
                            watermark=(0, seq),
                            meta=meta,
                        )
                    except WireFormatError:
                        self._note_forward_error("encode:WireFormatError")
                        continue
            t_send = time.perf_counter()
            try:
                if self._send is not None:
                    self._send(payload)
                else:
                    self.parent.aggregator.ingest(payload)
            except _TRANSPORT_ERRORS as err:
                self._note_forward_error(f"send:{type(err).__name__}")
                continue
            if armed:
                ship_ms = (time.perf_counter() - t_send) * 1000.0
                self._last_ship_ms = ship_ms
                _obs_observe("serve.hop_ship_ms", ship_ms, node=self.name)
                _obs_record_hop(meta["trace"]["id"], self.name, "ship", ship_ms)
            shipped += 1
        return shipped

    def _note_forward_error(self, reason: str) -> None:
        if _obs_enabled():
            _obs_inc("serve.forward_errors", node=self.name)
        if not getattr(self, "_warned_forward", False):
            self._warned_forward = True
            warnings.warn(
                f"tree node {self.name!r} could not ship upward ({reason}); the"
                " next interval's cumulative snapshot repairs the parent's view."
                " Further drops are counted under serve.forward_errors without"
                " warning again.",
                stacklevel=3,
            )


class AggregationTree:
    """An in-process client → leaf → … → root hierarchy.

    Args:
        fan_out: nodes per level below the root, top-down — ``(4, 16)``
            builds 1 root, 4 intermediates, 16 leaves (clients attach to
            leaves round-robin via :meth:`leaf_for`).
        tenants: ``{tenant_id: collection factory}`` registered on every
            node (each node folds independently, so each needs its own
            collection instance).
        checkpoint_root: when set, the ROOT aggregator checkpoints under
            this directory (the root is the state of record; interior
            nodes are reconstructable from their children's next ships).
        engine: execution backend every node's aggregator folds with (see
            :class:`~metrics_tpu.serve.Aggregator`). An engine spec is
            resolved ONCE so all nodes share one
            :class:`~metrics_tpu.engine.ProgramStore` — and since the
            tenants share schemas, the whole tree shares each bucket's
            executable. :meth:`revive` then restores a killed node's
            states AND executables together (``warmup()`` before the node
            re-enters traffic).

    Example::

        tree = AggregationTree(
            fan_out=(2, 4),
            tenants={"search": lambda: MetricCollection(
                {"auroc": StreamingAUROC(num_bins=256)})},
        )
        tree.leaf_for(client_index).ingest(payload_bytes)
        tree.pump()                       # fold + forward every level
        tree.root.query("search")
    """

    def __init__(
        self,
        fan_out: Sequence[int],
        tenants: Dict[str, Callable[[], Any]],
        *,
        checkpoint_root: Optional[str] = None,
        max_queue: int = 65536,
        resilience: Any = None,
        engine: Any = None,
    ) -> None:
        if any(int(n) < 1 for n in fan_out):
            raise ValueError(f"fan_out entries must be >= 1, got {tuple(fan_out)}")
        from metrics_tpu.engine import get_engine

        # retained so a Supervisor heal (revive) can rebuild a dead node
        # with the same registration and policy the original carried;
        # the engine is resolved ONCE so every node (and every revival)
        # shares the same program store and in-memory executables
        self.tenant_factories = dict(tenants)
        self._checkpoint_root = checkpoint_root
        self._max_queue = int(max_queue)
        self._resilience = resilience
        self._engine = get_engine(engine)
        self.root = AggregatorNode(self._build_aggregator("root", checkpoint_dir=checkpoint_root))
        self.levels: List[List[AggregatorNode]] = [[self.root]]
        for depth, width in enumerate(fan_out):
            parents = self.levels[-1]
            level = []
            for i in range(int(width)):
                agg = self._build_aggregator(f"L{depth + 1}.{i}")
                level.append(AggregatorNode(agg, parent=parents[i % len(parents)]))
            self.levels.append(level)

    @property
    def leaves(self) -> List[AggregatorNode]:
        return self.levels[-1]

    @property
    def nodes(self) -> List[AggregatorNode]:
        return [node for level in self.levels for node in level]

    def children(self, node: AggregatorNode) -> List[AggregatorNode]:
        """Nodes currently shipping into ``node``."""
        return [n for level in self.levels for n in level if n.parent is node]

    def node_by_name(self, name: str) -> AggregatorNode:
        for node in self.nodes:
            if node.name == str(name):
                return node
        raise ValueError(f"no node named {name!r} in this tree")

    # ------------------------------------------------------------------
    # Live membership (the primitives serve.elastic composes)
    # ------------------------------------------------------------------

    def add_node(
        self,
        name: Optional[str] = None,
        parent: Optional[AggregatorNode] = None,
        *,
        level: Optional[int] = None,
    ) -> AggregatorNode:
        """Build a NEW node with the tree's retained tenant factories /
        queue bound / resilience policy / execution engine and attach it
        under ``parent`` (default: the least-loaded node of the level
        above the leaves). This is construction + attachment ONLY — ring
        admission, warmup and the readiness probe are the elastic join
        protocol's job (:meth:`metrics_tpu.serve.elastic.ElasticFleet.join_node`)."""
        if parent is not None:
            if parent.is_dead:
                raise ValueError(
                    f"parent {parent.name!r} is dead (hard-killed); heal it before"
                    " attaching a new node — its children's ships would all drop"
                )
            for depth_idx, lvl in enumerate(self.levels):
                if parent in lvl:
                    depth = depth_idx + 1
                    break
            else:
                raise ValueError(f"parent {parent.name!r} is not in this tree")
            if level is not None and int(level) != depth:
                raise ValueError(
                    f"level={level} contradicts parent {parent.name!r} at depth {depth - 1}"
                )
            if depth >= len(self.levels):
                raise ValueError(
                    f"parent {parent.name!r} is a leaf; the tree does not grow new levels"
                )
        else:
            depth = (len(self.levels) - 1) if level is None else int(level)
            if not 1 <= depth < len(self.levels):
                raise ValueError(f"level must be in [1, {len(self.levels) - 1}], got {depth}")
            # dead nodes are not attachment candidates: a new leaf under an
            # unhealed hard-killed intermediate would have every ship drop
            parents = [p for p in self.levels[depth - 1] if not p.is_dead]
            if not parents:
                raise ValueError(
                    f"level {depth - 1} has no live node to attach under; heal first"
                )
            load = {id(p): 0 for p in parents}
            for n in self.levels[depth]:
                if id(n.parent) in load:
                    load[id(n.parent)] += 1
            parent = min(parents, key=lambda p: load[id(p)])
        existing = {n.name for n in self.nodes}
        if name is None:
            i = len(self.levels[depth])
            while f"L{depth}.{i}" in existing:
                i += 1
            name = f"L{depth}.{i}"
        elif str(name) in existing:
            raise ValueError(f"node name {name!r} already exists in this tree")
        node = AggregatorNode(self._build_aggregator(str(name)), parent=parent)
        self.levels[depth].append(node)
        return node

    def _build_aggregator(self, name: str, *, checkpoint_dir: Optional[str] = None) -> Aggregator:
        """ONE recipe for building a node's aggregator from the tree's
        retained configuration — shared by construction-time levels,
        :meth:`add_node` (elastic join) and :meth:`revive` (heal), so a
        future policy knob cannot drift between joined and healed nodes."""
        agg = Aggregator(
            name,
            checkpoint_dir=checkpoint_dir,
            max_queue=self._max_queue,
            resilience=self._resilience,
            engine=self._engine,
        )
        for tenant_id, factory in self.tenant_factories.items():
            agg.register_tenant(tenant_id, factory)
        return agg

    def remove_node(self, node: AggregatorNode) -> None:
        """Detach ``node`` from the tree. Refuses the root and any node
        that still has children (reparent them first) — the elastic drain
        protocol handles both, plus re-homing the node's clients and
        retiring its ``node:*`` identity at the parent."""
        if node is self.root:
            raise ValueError("cannot remove the root (it is the state of record)")
        kids = self.children(node)
        if kids:
            raise ValueError(
                f"node {node.name!r} still has children"
                f" {[k.name for k in kids]}; reparent them first"
            )
        for lvl in self.levels:
            if node in lvl:
                lvl.remove(node)
                if not lvl and lvl is not self.levels[0]:
                    # an emptied interior level (every intermediate drained,
                    # children re-parented upward) is pruned so `leaves`
                    # keeps naming the level end clients actually ship to
                    self.levels.remove(lvl)
                return
        raise ValueError(f"node {node.name!r} is not in this tree")

    def reparent(self, node: AggregatorNode, new_parent: AggregatorNode) -> None:
        """Move a subtree under a new parent and RESET its ship sequence so
        the next :meth:`AggregatorNode.forward` re-derives it via
        ``_resume_seq`` against the NEW parent's watermarks — the exact
        mechanism a healed node uses, reused for rebalancing (one
        correctness mechanism, not two). The caller (the elastic drain
        protocol) retires the ``node:*`` slot at the OLD parent; without
        that the old parent would keep folding a frozen copy of the
        subtree forever. In-process transport only: a node with a custom
        ``send`` hook keeps it, so HTTP-wired nodes must re-point it."""
        if node is self.root:
            raise ValueError("cannot reparent the root")
        cursor: Optional[AggregatorNode] = new_parent
        while cursor is not None:
            if cursor is node:
                raise ValueError(
                    f"reparenting {node.name!r} under {new_parent.name!r} would create a cycle"
                )
            cursor = cursor.parent
        node.parent = new_parent
        node._ship_seq = None

    def leaf_for(self, client_index: int) -> Aggregator:
        """The leaf aggregator client ``client_index`` ingests into."""
        return self.leaves[client_index % len(self.leaves)].aggregator

    def pump(self, rounds: int = 1) -> int:
        """Propagate state bottom-up: flush + forward every non-root level
        (deepest first), then flush the root; returns payloads shipped."""
        shipped = 0
        for _ in range(int(rounds)):
            for level in reversed(self.levels[1:]):
                for node in level:
                    shipped += node.forward()
            try:
                self.root.aggregator.flush()
            except NodeDownError:
                # a dead root must not abort the pump: the rest of the tree
                # keeps folding, and the heal's restore + re-ships catch up
                continue
        return shipped

    def save(self) -> str:
        """Checkpoint the root (the state of record); see
        :meth:`~metrics_tpu.serve.Aggregator.save`."""
        return self.root.aggregator.save()

    def restore(self, path: Optional[str] = None):
        """Restore the root from its newest checkpoint. Interior nodes are
        NOT restored — they rebuild from their children's next ships, and
        their first :meth:`AggregatorNode.forward` resumes the ship
        sequence above the root's restored watermark so the rebuilt
        subtree is never dropped as stale. Call BEFORE the first
        :meth:`pump`."""
        return self.root.aggregator.restore(path)

    def revive(self, node: AggregatorNode):
        """Rebuild a hard-killed node in place (the Supervisor heal path):
        a fresh :class:`Aggregator` with the tree's retained tenant
        factories / queue bound / resilience policy / execution engine,
        restored from its latest checkpoint when it has one (the root),
        and the node's ship sequence reset so ``_resume_seq`` re-derives
        it above the parent's watermark. Interior nodes come back EMPTY by
        design — their state is reconstructed by their children's next
        cumulative ships.

        With an AOT engine armed the rebuilt node is also **warmed before
        it re-enters traffic**: ``warmup()`` replays the checkpoint's
        warmup manifest (falling back to the pre-warm buckets for interior
        nodes) so states and executables are restored together and the
        healed node's first fold performs zero backend compiles. The
        program count lands on ``node.last_warmup_programs`` (what
        :meth:`~metrics_tpu.serve.resilience.Supervisor.heal` reports).
        Returns the restore manifest (None when nothing was restored)."""
        is_root = node is self.root
        agg = self._build_aggregator(
            node.name, checkpoint_dir=self._checkpoint_root if is_root else None
        )
        # warm BEFORE restore: executables are ready the moment states land
        node.last_warmup_programs = agg.warmup()
        manifest = None
        if is_root and self._checkpoint_root is not None:
            manifest = agg.restore()
        node.revive(agg)
        return manifest
