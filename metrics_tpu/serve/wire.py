"""Versioned wire format for metric-state payloads.

The serving tier moves **metric state**, not samples: a client folds its
local stream into bounded state (a few KB of sketch/count leaves) and ships
one self-describing payload per interval. This module is that payload —
the contract every :class:`~metrics_tpu.serve.aggregator.Aggregator` hop
(client → leaf → intermediate → root) speaks:

* **framing** — ``MAGIC | major | minor | header_len | header JSON | raw
  leaf bytes``. The header carries tenant / collection / client identity,
  the ``(epoch, step)`` watermark of the snapshot, the schema fingerprint,
  free-form ``meta``, and a leaf directory (dtype / shape / byte extents);
  the body is the concatenated little-endian leaf buffers. Everything is
  length-checked, so truncation is detected, never silently decoded.
* **versioning** — a payload from a *newer minor* decodes fine (unknown
  header and ``meta`` keys are preserved, not rejected): minors add
  optional fields. A different **major** is rejected loudly — majors may
  change framing, and guessing would corrupt tenant state.
* **schema fingerprint** — :func:`schema_fingerprint` hashes the metric
  *configuration* (member names, per-state reduction kinds, default
  dtype/shape, sketch class + static config). Two parties merge only when
  their fingerprints match; a changed bin count or threshold grid is a
  **different schema** and the aggregator rejects it with the exact
  differing path (:func:`schema_diff`) instead of silently merging
  incompatible histograms.
* **state packing** — member states ride the same
  ``utilities.checkpoint`` packing orbax checkpoints use
  (:func:`~metrics_tpu.utilities.checkpoint.metric_state_to_tree`), so
  every reduction kind round-trips: plain ``sum``/``max``/``min`` leaves,
  ``cat`` lists (length sentinel), ``CapacityBuffer`` contents and
  ``dist_reduce_fx="sketch"`` states (class + static config + leaves).

Payloads are **cumulative snapshots**: the watermark names the last
``(epoch, step)`` folded in, and a later snapshot supersedes an earlier
one from the same client. That choice is what makes the aggregation tier's
exactly-once story simple — duplicates and reordering reduce to a
watermark comparison (see ``docs/serving.md``).
"""
import hashlib
import json
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "MAX_WIRE_BYTES",
    "WIRE_MAJOR",
    "WIRE_MINOR",
    "MetricPayload",
    "SchemaMismatchError",
    "WireFormatError",
    "apply_payload",
    "decode_state",
    "encode_state",
    "peek_header",
    "schema_diff",
    "schema_fingerprint",
    "schema_of",
]

WIRE_MAGIC = b"MTSV"
WIRE_MAJOR = 1
# minor 1: every leaf-directory entry carries a crc32 of its raw bytes
# (integrity firewall — a bit-flipped body is refused at decode instead of
# silently folding garbage into tenant state). Minor-0 decoders ignore the
# unknown entry key; minor-0 payloads (no crc32) still decode here — the
# forward/backward asymmetry the versioning contract promises.
# minor 2: observability side-channel in ``meta`` — ``meta["trace"]``
# (trace id, client encode timestamp, per-hop provenance records) and
# ``meta["obs_nodes"]`` (piggybacked per-node obs snapshots for the fleet
# federation table). Both are attached ONLY while the obs layer is armed:
# an unarmed fleet ships byte-identical minor-2 payloads with empty meta.
# Older decoders preserve the unknown meta keys untouched — additive, per
# the minor contract.
# minor 3: multi-region meta — ``meta["region"]`` (origin region name of a
# cross-root replica, identity ``region:<name>``) and ``meta["generation"]``
# (the monotonic failover generation stamped at standby promotion; an
# aggregator holding a generation fence for the identity refuses OLDER
# generations loudly instead of resurrecting pre-failover state). Plain
# additive meta: a pre-upgrade aggregator decodes the payload, preserves
# both keys untouched, and folds it like any other snapshot — the
# rolling-regional-upgrade contract tests/serve/test_wire.py pins.
WIRE_MINOR = 3
# bounded-size payloads are the design contract (sketches are <=64KB by
# construction); the default cap leaves headroom for multi-member
# collections while still refusing an unbounded cat state that would turn
# the aggregation tier back into a sample mover
MAX_WIRE_BYTES = 1 << 20

_PREAMBLE = struct.Struct("<4sHHI")


class WireFormatError(ValueError):
    """Malformed, truncated or incompatible-major payload bytes."""


class SchemaMismatchError(ValueError):
    """Payload schema fingerprint differs from the registered tenant's."""


def _members(obj: Any) -> Dict[str, Any]:
    """Normalize a Metric or MetricCollection to ``{member_name: metric}``.

    A bare metric gets its class name — the same key
    ``MetricCollection([m])`` would give it, so a client shipping one
    metric and a tenant registered as a one-member collection agree.
    """
    if hasattr(obj, "items") and not hasattr(obj, "state_pytree"):  # MetricCollection
        return dict(obj.items())
    return {type(obj).__name__: obj}


def _default_spec(default: Any) -> Dict[str, Any]:
    """Schema entry for one state default — exactly the configuration that
    must match for a merge to be meaningful."""
    from metrics_tpu.streaming.sketches import Sketch
    from metrics_tpu.utilities.buffers import CapacityBuffer

    if isinstance(default, Sketch):
        return {"kind": "sketch", "class": type(default).__name__, "config": default.config()}
    if isinstance(default, CapacityBuffer):
        return {"kind": "buffer", "capacity": int(default.capacity)}
    if isinstance(default, list):
        return {"kind": "cat"}
    arr = np.asarray(default)
    return {"kind": "array", "dtype": str(arr.dtype), "shape": list(arr.shape)}


def schema_of(obj: Any) -> Dict[str, Any]:
    """The canonical schema dict for a Metric / MetricCollection: per
    member, per state, the reduction kind and the default's configuration.
    This is what :func:`schema_fingerprint` hashes and what
    :func:`schema_diff` compares for the loud mismatch message."""
    schema: Dict[str, Any] = {}
    for name, metric in sorted(_members(obj).items()):
        states = {}
        for state, red in metric._reductions.items():
            red_name = red if isinstance(red, str) or red is None else f"callable:{getattr(red, '__name__', 'fn')}"
            states[state] = {"reduction": red_name, **_default_spec(metric._defaults[state])}
        schema[name] = {"type": type(metric).__name__, "states": states}
    return schema


def _fingerprint_of_schema(schema: Dict[str, Any]) -> str:
    blob = json.dumps(schema, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def schema_fingerprint(obj: Any) -> str:
    """Stable hex fingerprint of :func:`schema_of` — the merge
    compatibility key carried in every payload header."""
    return _fingerprint_of_schema(schema_of(obj))


def schema_diff(a: Dict[str, Any], b: Dict[str, Any], path: str = "") -> List[str]:
    """Human-readable paths where two schema dicts differ (both directions),
    so a fingerprint rejection can name the exact bin count / threshold /
    member that changed instead of just "hash mismatch"."""
    diffs: List[str] = []
    for key in sorted(set(a) | set(b)):
        here = f"{path}.{key}" if path else str(key)
        if key not in a:
            diffs.append(f"{here}: only in payload ({b[key]!r})")
        elif key not in b:
            diffs.append(f"{here}: only in registered schema ({a[key]!r})")
        elif isinstance(a[key], dict) and isinstance(b[key], dict):
            diffs.extend(schema_diff(a[key], b[key], here))
        elif a[key] != b[key]:
            diffs.append(f"{here}: registered {a[key]!r} != payload {b[key]!r}")
    return diffs


@dataclass
class MetricPayload:
    """One decoded wire payload: identity, watermark, schema and states.

    ``states`` maps member name -> the member's packed state tree (the
    :func:`~metrics_tpu.utilities.checkpoint.metric_state_to_tree` shape:
    state leaves plus ``__update_count`` and optional ``__aux``), with
    numpy array leaves. ``meta`` is the free-form forward-compatible side
    channel; unknown keys survive the round trip untouched.
    """

    tenant: str
    collection: str
    client_id: str
    watermark: Tuple[int, int]
    schema_hash: str
    schema: Dict[str, Any]
    states: Dict[str, Dict[str, Any]]
    meta: Dict[str, Any] = field(default_factory=dict)
    wire_version: Tuple[int, int] = (WIRE_MAJOR, WIRE_MINOR)

    @property
    def nbytes(self) -> int:
        """Total state bytes carried (leaf buffers only)."""
        total = 0
        for tree in self.states.values():
            for leaf in _iter_leaves(tree):
                total += leaf[1].nbytes
        return total


def _iter_leaves(tree: Any, path: Tuple[str, ...] = ()) -> List[Tuple[Tuple[str, ...], np.ndarray]]:
    """Depth-first ``(path, numpy leaf)`` pairs of a packed state tree."""
    out: List[Tuple[Tuple[str, ...], np.ndarray]] = []
    if isinstance(tree, dict):
        for key in sorted(tree):
            out.extend(_iter_leaves(tree[key], path + (str(key),)))
        return out
    out.append((path, np.asarray(tree)))
    return out


def _dtype_from_name(name: str) -> np.dtype:
    """Resolve a dtype name, falling back to the ml_dtypes extended family
    (bfloat16 et al.) that plain ``np.dtype`` does not know by name."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _set_path(tree: Dict[str, Any], path: List[str], value: np.ndarray) -> None:
    node = tree
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


def encode_state(
    obj: Any,
    *,
    tenant: str,
    client_id: str,
    watermark: Tuple[int, int],
    collection: Optional[str] = None,
    meta: Optional[Dict[str, Any]] = None,
    max_bytes: Optional[int] = MAX_WIRE_BYTES,
) -> bytes:
    """Serialize a Metric / MetricCollection snapshot into one payload.

    Args:
        obj: the metric or collection whose *current* state to ship.
        tenant: tenant id the state belongs to.
        client_id: stable identity of the shipping process (or tree node);
            the aggregator keys its exactly-once watermark on it.
        watermark: ``(epoch, step)`` of the LAST batch folded into this
            snapshot (a :class:`~metrics_tpu.ft.journal.BatchJournal`
            watermark, or any per-client monotonic counter).
        collection: logical collection name (defaults to ``tenant``).
        meta: free-form JSON-safe side data (forward-compatible: decoders
            keep keys they don't understand). Reserved keys in use:
            ``trace`` (hop provenance, added below when obs is armed),
            ``rehomed_from`` / ``generation`` (elastic handoff and
            failover fencing), and ``canary: True`` — stamped by
            :class:`metrics_tpu.obs.prober.CanaryProber` so synthetic
            known-answer traffic through the reserved ``__canary__``
            tenant is distinguishable on the wire from real tenant data
            (no structural change; the payload folds like any other).
        max_bytes: refuse to build a payload larger than this (``None``
            disables the check). Bounded payloads are the serving-tier
            contract — an unbounded ``cat`` state should stream through a
            sketch instead (see ``metrics_tpu.streaming``).
    """
    from metrics_tpu.utilities.checkpoint import metric_state_to_tree

    epoch, step = int(watermark[0]), int(watermark[1])
    if epoch < 0 or step < 0:
        raise ValueError(f"watermark must be non-negative, got {(epoch, step)}")
    meta = dict(meta or {})
    if "trace" not in meta:
        # armed-only trace context (wire minor 2): a fresh trace id plus the
        # encode wall timestamp the root's serve.e2e_freshness_ms measures
        # against, and an empty hop list each aggregator hop appends its
        # provenance record to. Unarmed, the key is absent — zero wire bytes.
        from metrics_tpu.obs.registry import enabled as _obs_enabled
        from metrics_tpu.obs.registry import new_trace_id as _new_trace_id

        if _obs_enabled():
            meta["trace"] = {"id": _new_trace_id(), "encoded_at": time.time(), "hops": []}
    states = {name: metric_state_to_tree(m) for name, m in _members(obj).items()}

    directory: List[Dict[str, Any]] = []
    buffers: List[bytes] = []
    offset = 0
    for member in sorted(states):
        for path, leaf in _iter_leaves(states[member]):
            raw = np.ascontiguousarray(leaf).tobytes()
            directory.append(
                {
                    "member": member,
                    "path": list(path),
                    # dtype NAME, not .str: extended dtypes (bfloat16 via
                    # ml_dtypes) stringify as opaque void records, but their
                    # names resolve on both ends (_dtype_from_name)
                    "dtype": np.asarray(leaf).dtype.name,
                    "shape": list(np.asarray(leaf).shape),
                    "offset": offset,
                    "nbytes": len(raw),
                    # minor-1 integrity firewall: a bit flip anywhere in this
                    # leaf's extent is refused at decode instead of folded
                    "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                }
            )
            buffers.append(raw)
            offset += len(raw)

    schema = schema_of(obj)
    header = {
        "tenant": str(tenant),
        "collection": str(collection if collection is not None else tenant),
        "client": str(client_id),
        "watermark": [epoch, step],
        "schema_hash": _fingerprint_of_schema(schema),
        "schema": schema,
        "meta": meta,
        "leaves": directory,
    }
    header_bytes = json.dumps(header, sort_keys=True, default=str).encode()
    payload = _PREAMBLE.pack(WIRE_MAGIC, WIRE_MAJOR, WIRE_MINOR, len(header_bytes)) + header_bytes + b"".join(buffers)
    if max_bytes is not None and len(payload) > max_bytes:
        raise WireFormatError(
            f"payload for tenant {tenant!r} client {client_id!r} is {len(payload)} bytes"
            f" (> max_bytes={max_bytes}). The serving tier moves BOUNDED state; an"
            " unbounded cat/buffer accumulation should stream through a bounded"
            " sketch (metrics_tpu.streaming) before shipping."
        )
    return payload


def peek_header(data: bytes, *, max_bytes: Optional[int] = MAX_WIRE_BYTES) -> Tuple[Tuple[int, int], Dict[str, Any]]:
    """Parse only the preamble + header JSON of a payload — no body work.

    Returns ``((major, minor), header_dict)``. This is the cheap
    identity/routing read the ingest firewall needs: a quarantined client's
    payload is refused off the header alone, and a payload whose BODY fails
    its crc can still be attributed to the tenant/client the header names.
    Raises :class:`WireFormatError` exactly where :func:`decode_state`
    would (size cap, truncation, magic, major, header JSON) — the header
    contract is shared; only the leaf work is skipped.
    """
    if max_bytes is not None and len(data) > max_bytes:
        raise WireFormatError(
            f"payload is {len(data)} bytes (> max_bytes={max_bytes}); the serving"
            " tier moves BOUNDED state — refusing to decode"
        )
    if len(data) < _PREAMBLE.size:
        raise WireFormatError(f"payload truncated: {len(data)} bytes < {_PREAMBLE.size}-byte preamble")
    magic, major, minor, header_len = _PREAMBLE.unpack_from(data)
    if magic != WIRE_MAGIC:
        raise WireFormatError(f"bad magic {magic!r}: not a metrics_tpu serve payload")
    if major != WIRE_MAJOR:
        raise WireFormatError(
            f"incompatible wire major version {major} (this build speaks {WIRE_MAJOR})."
            " Majors may change framing; refusing to guess. Upgrade the"
            f" {'aggregator' if major > WIRE_MAJOR else 'client'} so both ends agree."
        )
    body_start = _PREAMBLE.size + header_len
    if len(data) < body_start:
        raise WireFormatError(f"payload truncated inside header ({len(data)} < {body_start} bytes)")
    try:
        header = json.loads(data[_PREAMBLE.size : body_start].decode())
    except (UnicodeDecodeError, ValueError) as err:
        raise WireFormatError(f"payload header is not valid JSON: {err}") from err
    if not isinstance(header, dict):
        raise WireFormatError(f"payload header must be a JSON object, got {type(header).__name__}")
    return (int(major), int(minor)), header


def decode_state(
    data: bytes,
    *,
    max_bytes: Optional[int] = MAX_WIRE_BYTES,
    _peeked: Optional[Tuple[Tuple[int, int], Dict[str, Any]]] = None,
) -> MetricPayload:
    """Parse payload bytes back into a :class:`MetricPayload`.

    Raises :class:`WireFormatError` on truncation, bad magic, an
    incompatible **major** version or an oversized payload — the bounded
    contract is enforced on BOTH ends (a hostile sender does not run our
    ``encode_state``, so the decode side must refuse too; ``max_bytes=None``
    disables for trusted offline tooling). A newer **minor** version
    decodes: unknown header keys are ignored and unknown ``meta`` keys
    preserved — that asymmetry (minor adds, major breaks) is the whole
    versioning contract, pinned by ``tests/serve/test_wire.py``.

    ``_peeked`` hands in a prior :func:`peek_header` result for these same
    bytes so callers that already peeked (the ingest firewall's identity
    read) do not pay the header JSON parse twice per payload.
    """
    (major, minor), header = _peeked if _peeked is not None else peek_header(data, max_bytes=max_bytes)
    body_start = _PREAMBLE.size + _PREAMBLE.unpack_from(data)[3]
    for required in ("tenant", "collection", "client", "watermark", "schema_hash", "leaves"):
        if required not in header:
            raise WireFormatError(f"payload header missing required key {required!r}")

    body = data[body_start:]
    states: Dict[str, Dict[str, Any]] = {}
    try:
        entries = list(header["leaves"])
        wm = header["watermark"]
        epoch, step = int(wm[0]), int(wm[1])
    except (TypeError, IndexError, KeyError, ValueError) as err:
        raise WireFormatError(f"malformed payload header: {err}") from err
    if epoch < 0 or step < 0:
        raise WireFormatError(f"payload watermark must be non-negative, got {(epoch, step)}")
    for entry in entries:
        try:
            offset, nbytes = int(entry["offset"]), int(entry["nbytes"])
        except (TypeError, KeyError, ValueError) as err:
            raise WireFormatError(f"malformed leaf directory entry {entry!r}: {err}") from err
        if offset < 0 or offset + nbytes > len(body):
            raise WireFormatError(
                f"payload truncated: leaf {entry.get('member')}/{'/'.join(entry.get('path', []))}"
                f" spans bytes [{offset}, {offset + nbytes}) of a {len(body)}-byte body"
            )
        # crc is optional on the wire (minor-0 senders don't emit it) but
        # verified whenever present: refusing a flipped bit HERE, naming the
        # exact leaf, is what keeps one corrupt client from poisoning a
        # tenant's merged state three folds later where nothing can say whose
        # bytes were bad
        declared_crc = entry.get("crc32")
        if declared_crc is not None:
            actual_crc = zlib.crc32(body[offset : offset + nbytes]) & 0xFFFFFFFF
            if actual_crc != int(declared_crc):
                raise WireFormatError(
                    f"leaf {entry.get('member')}/{'/'.join(str(p) for p in entry.get('path', []))}"
                    f" failed its crc32 integrity check (header declares"
                    f" {int(declared_crc):#010x}, body bytes hash to {actual_crc:#010x}):"
                    " the payload was corrupted in flight — refusing to fold it"
                )
        try:
            leaf = np.frombuffer(body[offset : offset + nbytes], dtype=_dtype_from_name(str(entry["dtype"])))
            leaf = leaf.reshape([int(s) for s in entry["shape"]])
            member = str(entry["member"])
            path = [str(p) for p in entry["path"]]
        except (ValueError, TypeError, KeyError, AttributeError) as err:
            raise WireFormatError(
                f"leaf directory entry {entry.get('member') if isinstance(entry, dict) else entry!r}"
                f" is inconsistent (dtype/shape/nbytes/path disagree): {err}"
            ) from err
        if not path:
            raise WireFormatError(f"leaf directory entry for member {member!r} has an empty path")
        _set_path(states.setdefault(member, {}), path, leaf)

    return MetricPayload(
        tenant=str(header["tenant"]),
        collection=str(header["collection"]),
        client_id=str(header["client"]),
        watermark=(epoch, step),
        schema_hash=str(header["schema_hash"]),
        schema=header.get("schema", {}),
        states=states,
        meta=dict(header.get("meta", {})),
        wire_version=(int(major), int(minor)),
    )


def apply_payload(obj: Any, payload: MetricPayload) -> Any:
    """Load a payload's member states INTO a compatible metric/collection
    (offline consumer path: rebuild a client's snapshot for inspection or a
    flat reference merge). Returns ``obj``. Aggregators never need this —
    they fold packed trees directly — but tests and tooling do."""
    from metrics_tpu.utilities.checkpoint import load_metric_state_tree

    ours, theirs = schema_fingerprint(obj), payload.schema_hash
    if ours != theirs:
        diffs = schema_diff(schema_of(obj), payload.schema)
        raise SchemaMismatchError(
            f"payload schema {theirs} != target schema {ours};"
            f" differing: {'; '.join(diffs) or 'fingerprint only (schema summary absent)'}"
        )
    members = _members(obj)
    for name, metric in members.items():
        if name in payload.states:
            load_metric_state_tree(metric, payload.states[name])
    return obj
