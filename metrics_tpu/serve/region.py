"""Multi-region serving: cross-root replication, partition tolerance,
generation-fenced failover.

Every other layer of the serving tier terminates in ONE root — a single
region outage takes down the global ``/query`` surface for every tenant.
This module closes that gap with **zero new consistency machinery**:
because every reduction the tier serves is an exact monoid
(sketch / integer-sum / min / max — the same classes the tree invariant
pins), the *global* answer is just the merge of the regions' *cumulative*
snapshots, and replication, partition healing and failover all reduce to
mechanisms the tier already proved:

* **cross-root replication as ordinary wire traffic** — each
  :class:`Region`'s root periodically ships its regional cumulative
  aggregate to every peer as a wire client with identity
  ``region:<name>`` (:mod:`metrics_tpu.serve.wire` minor 3 adds the
  ``region`` / ``generation`` meta keys). The receiving side is a plain
  :class:`~metrics_tpu.serve.Aggregator` (the region's **global view**),
  so watermark keep-latest dedup makes the cross-merge **exactly-once and
  order-free** — a duplicated, reordered or re-sent replica is absorbed
  by the same journal comparison every client ship is.
* **partition tolerance by construction** — during a DCN partition each
  region keeps answering ``/query`` with **local-complete /
  global-stale** values: its own clients' contributions are current, the
  unreachable peers' replicas simply age. :meth:`Region.query_global`
  reports per-region freshness, and an optional ``max_staleness_s``
  policy either *marks* the answer degraded or *rejects* it
  (:class:`StaleGlobalViewError` → HTTP 503). On heal, the next
  cumulative cross-ship repairs the global view **bitwise** — cumulative
  snapshots mean there is nothing to anti-entropy: the newest replica IS
  the whole region.
* **replication loop with bounded backoff** —
  :meth:`RegionalMesh.replicate` (and the :meth:`RegionalMesh.start`
  background loop) drives each ship under an
  :class:`~metrics_tpu.ft.RetryPolicy` whose ``deadline_s`` caps the
  whole retry cycle below the replication cadence (a cross-region call
  must not stack a full backoff schedule past the caller's tick).
  Failures are counted (``serve.replication_errors{peer=}``), surface as
  the ``serve.peers_unreachable{node=}`` gauge, and per-peer staleness is
  exported as ``serve.peer_staleness_ms{peer=}`` — the signals the
  :class:`~metrics_tpu.obs.health.HealthMonitor` ``partition_detected`` /
  ``peer_stale`` conditions watch.
* **generation-fenced failover** — :meth:`RegionalMesh.promote` builds a
  warm standby for a dead region: the global view restores from
  :class:`~metrics_tpu.ft.CheckpointManager`, fold executables pre-warm
  through the :mod:`metrics_tpu.engine` store (**zero backend compiles**
  on promotion — the PR 11 contract), peers' next replicas repair the
  rest, and a **monotonic generation number** — persisted in the
  checkpoint manifest, stamped into wire meta on every ship — is bumped.
  Peers fence the promoted generation
  (:meth:`~metrics_tpu.serve.Aggregator.fence_generation`), so a zombie
  old-generation root's ships are refused loudly
  (``serve.fenced_ships``, :class:`~metrics_tpu.serve.FencedGenerationError`)
  instead of resurrecting pre-failover state. The generation also rides
  the replica **watermark epoch**, so the promoted root's ship sequence
  restarts at ``(generation+1, 0) > (generation, anything)`` — resume
  needs no watermark archaeology.

The acceptance bar is the one PR 7/8/13 established:
``tests/integrations/region_smoke.py`` pins every region's global
``/query`` **bitwise-equal to the flat oracle merge of exactly the
accepted snapshots** after partition + heal AND after kill +
generation-fenced promotion, under 10% seeded wire chaos, with every
injected fault visible in obs counters. See ``docs/serving.md`` §9.
"""
import itertools
import threading
import time
import warnings
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import observe as _obs_observe
from metrics_tpu.obs.registry import set_gauge as _obs_gauge
from metrics_tpu.serve.aggregator import Aggregator, ServeError
from metrics_tpu.serve.wire import peek_header

__all__ = [
    "Region",
    "RegionDownError",
    "RegionalMesh",
    "StaleGlobalViewError",
]


class RegionDownError(ServeError):
    """The region's root is down (killed / partitioned away): a standby
    must be promoted (:meth:`RegionalMesh.promote`) before it serves."""


class StaleGlobalViewError(ServeError):
    """The region's global view violates its ``max_staleness_s`` policy:
    one or more peers' replicas have aged out (partition or dead peer).
    Carries :attr:`stale_regions` and :attr:`retry_after_s` — the HTTP
    surface answers 503, and the caller may instead query with the
    ``degraded``-marking policy to read the local-complete values."""

    def __init__(
        self,
        message: str,
        stale_regions: Sequence[str] = (),
        retry_after_s: Optional[float] = None,
    ) -> None:
        super().__init__(message)
        self.stale_regions = list(stale_regions)
        self.retry_after_s = retry_after_s


class Region:
    """One region of a :class:`RegionalMesh`: a regional aggregation tier
    plus the region's **global view**.

    Two aggregation surfaces, deliberately separate:

    * the **regional root** folds only this region's own clients (either a
      bare :class:`~metrics_tpu.serve.Aggregator`, or the root of an
      :class:`~metrics_tpu.serve.AggregationTree` when ``fan_out`` is
      given — optionally wrapped in an
      :class:`~metrics_tpu.serve.ElasticFleet` with ``elastic=True``, so a
      regional fleet keeps its live join/drain/split/merge). Its merged
      state is what ships to peers — shipping the *global* view instead
      would transitively double-count every peer's contribution.
    * the **global view** (``<name>.global``) is an ordinary aggregator
      whose clients are the regions themselves (``region:<name>``
      identities, this region included). Its merged state answers global
      ``/query``; keep-latest watermark dedup makes the cross-merge
      exactly-once and order-free.

    Args:
        name: region identity — the ``region:<name>`` wire client id.
        tenants: ``{tenant_id: collection factory}`` registered on every
            aggregator of the region.
        fan_out: build an in-region :class:`AggregationTree` with this
            shape (``None`` = a single regional aggregator).
        elastic: wrap the regional tree in an :class:`ElasticFleet`
            (requires ``fan_out``); exposed as :attr:`fleet`.
        checkpoint_dir: the GLOBAL VIEW's checkpoint directory — the
            region's state of record, what a promoted standby restores.
        engine: execution backend for every fold (see
            :class:`~metrics_tpu.serve.Aggregator`); share one
            :class:`~metrics_tpu.engine.AotEngine` store across the
            original and its standby so promotion performs zero backend
            compiles.
        max_staleness_s: the degraded-read policy bound — a peer whose
            replica is older than this is STALE (None = report freshness,
            never judge).
        stale_reads: ``"degraded"`` (default) marks the global answer
            (``degraded: true`` + ``stale_regions``) when peers age out;
            ``"reject"`` raises :class:`StaleGlobalViewError` instead
            (the HTTP 503 contract).
        resilience / max_queue / seed: forwarded to the regional tier.
        generation: starting failover generation (normally 0; a promoted
            standby is built by :meth:`standby` with the successor value).
        history: arm the GLOBAL view's time-travel tier — ``True`` for
            :class:`~metrics_tpu.serve.history.HistoryConfig` defaults, or
            a config instance. Interval cuts stamp the region's failover
            generation, so delta range queries across a promotion are
            fenced (:class:`~metrics_tpu.serve.history.GenerationFencedRangeError`)
            until re-asked per generation or as ``mode=cumulative``;
            retained in the standby recipe, so a promoted successor is
            history-armed too.
    """

    def __init__(
        self,
        name: str,
        tenants: Dict[str, Callable[[], Any]],
        *,
        fan_out: Optional[Sequence[int]] = None,
        elastic: bool = False,
        checkpoint_dir: Optional[str] = None,
        engine: Any = None,
        max_staleness_s: Optional[float] = None,
        stale_reads: str = "degraded",
        resilience: Any = None,
        max_queue: int = 4096,
        seed: int = 0,
        generation: int = 0,
        history: Any = None,
    ) -> None:
        if stale_reads not in ("degraded", "reject"):
            raise ValueError(f"stale_reads must be 'degraded' or 'reject', got {stale_reads!r}")
        if elastic and fan_out is None:
            raise ValueError("elastic=True requires a fan_out (an in-region tree to manage)")
        self.name = str(name)
        # retained so standby() can rebuild this region's exact recipe —
        # the failover analogue of AggregationTree's retained factories
        self._config = dict(
            tenants=dict(tenants),
            fan_out=None if fan_out is None else tuple(fan_out),
            elastic=bool(elastic),
            checkpoint_dir=checkpoint_dir,
            engine=engine,
            max_staleness_s=max_staleness_s,
            stale_reads=stale_reads,
            resilience=resilience,
            max_queue=int(max_queue),
            seed=int(seed),
            history=history,
        )
        self.max_staleness_s = None if max_staleness_s is None else float(max_staleness_s)
        self.stale_reads = stale_reads
        self.generation = int(generation)
        self.down = False

        # BOTH tiers checkpoint (when a dir is given): the global view is
        # the region's replica table (peers + own), but the REGIONAL root's
        # per-client slots are the only decomposable record of local
        # traffic — a standby restored without them would ship an empty
        # (generation+1) cumulative that SUPERSEDES the peers' last good
        # replica of this region. With both restored, the promoted root's
        # first ship carries the checkpointed regional state and the
        # clients' own cumulative re-ships repair everything since (the
        # at-least-once contract every restart in this tier leans on).
        import os as _os

        local_ckpt = None if checkpoint_dir is None else _os.path.join(checkpoint_dir, "local")
        global_ckpt = None if checkpoint_dir is None else _os.path.join(checkpoint_dir, "global")
        self.tree = None
        self.fleet = None
        if fan_out is not None:
            from metrics_tpu.serve.tree import AggregationTree

            self.tree = AggregationTree(
                fan_out,
                tenants,
                checkpoint_root=local_ckpt,
                max_queue=max_queue,
                resilience=resilience,
                engine=engine,
            )
            if elastic:
                from metrics_tpu.serve.elastic import ElasticFleet

                self.fleet = ElasticFleet(self.tree, seed=seed)
            self.local_root = self.tree.root.aggregator
        else:
            self.local_root = Aggregator(
                f"{self.name}.local",
                max_queue=max_queue,
                checkpoint_dir=local_ckpt,
                resilience=resilience,
                engine=engine,
            )
            for tenant_id, factory in tenants.items():
                self.local_root.register_tenant(tenant_id, factory)

        # history arms the GLOBAL view: the replica table is the one state
        # whose intervals answer "per tenant, across every region, over
        # time" — and its checkpoint/restore + generation fencing ride the
        # same global_ckpt manifest the failover protocol already repairs
        self.global_view = Aggregator(
            f"{self.name}.global",
            max_queue=max_queue,
            checkpoint_dir=global_ckpt,
            engine=engine,
            history=history,
        )
        for tenant_id, factory in tenants.items():
            self.global_view.register_tenant(tenant_id, factory)
        if self.global_view.history is not None:
            self.global_view.history.generation = int(generation)
        self._stamp_manifest_extra()

        # replica ship sequence WITHIN the current generation: watermark =
        # (generation, seq), so a promoted successor's (gen+1, 0) always
        # supersedes every predecessor ship — resume without archaeology
        self._ship_seq = itertools.count(0)
        self._peers: List[str] = []  # mesh-wired peer names (freshness surface)
        self._peer_last_accept: Dict[str, float] = {}  # peer -> monotonic stamp
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    # regional (client-facing) tier
    # ------------------------------------------------------------------

    def route(self, client_id: str) -> Aggregator:
        """The regional aggregator ``client_id`` ships to: the elastic
        router's live assignment, a stable leaf of the regional tree, or
        the single regional aggregator."""
        self._require_up()
        if self.fleet is not None:
            return self.fleet.router.route(client_id)
        if self.tree is not None:
            leaves = self.tree.leaves
            return leaves[zlib.crc32(str(client_id).encode()) % len(leaves)].aggregator
        return self.local_root

    def ingest(self, payload: Any, client_id: Optional[str] = None, **kwargs: Any) -> bool:
        """Ingest one client payload into the regional tier (routing by
        ``client_id`` when given — pass it to honor the elastic per-ship
        Router contract; header-peeked otherwise for raw bytes)."""
        if client_id is None and isinstance(payload, (bytes, bytearray, memoryview)):
            try:
                _, header = peek_header(bytes(payload))
                client_id = str(header.get("client"))
            except Exception:  # noqa: BLE001 — unframed garbage: any route refuses it
                client_id = "?"
        return self.route(client_id if client_id is not None else "?").ingest(payload, **kwargs)

    def pump(self, rounds: int = 1) -> int:
        """Propagate the regional tree bottom-up (no-op for a bare
        regional aggregator beyond a flush)."""
        self._require_up()
        if self.tree is not None:
            return self.tree.pump(rounds)
        self.local_root.flush()
        return 0

    # ------------------------------------------------------------------
    # replication surface (what the mesh drives)
    # ------------------------------------------------------------------

    def snapshot_payloads(self, tenants: Optional[Sequence[str]] = None) -> List[bytes]:
        """Encode this region's cumulative aggregate — one wire payload
        per tenant (all registered tenants, or just ``tenants``), identity
        ``region:<name>``, watermark ``(generation, seq)``, meta carrying
        ``region`` + ``generation`` (wire minor 3). All tenants share one
        ship sequence (the :class:`~metrics_tpu.serve.tree.AggregatorNode`
        convention); a single-tenant ship at seq N followed by a full
        sweep at N+1 is safe by the cumulative contract."""
        self._require_up()
        self.local_root.flush()
        with self._lock:
            seq = next(self._ship_seq)
            generation = self.generation
        payloads: List[bytes] = []
        from metrics_tpu.serve.wire import encode_state

        for tenant_id in (
            self.local_root.tenants() if tenants is None else [str(t) for t in tenants]
        ):
            view = self.local_root.collection(tenant_id, flush=False)
            tenant = self.local_root._tenant(tenant_id)
            with tenant.view_lock:
                payloads.append(
                    encode_state(
                        view,
                        tenant=tenant_id,
                        client_id=f"region:{self.name}",
                        watermark=(generation, seq),
                        meta={"region": self.name, "generation": generation},
                    )
                )
        return payloads

    def accept_replica(self, data: bytes) -> bool:
        """Receive one peer replica (or a self-ship) into the global view.

        Plain :meth:`~metrics_tpu.serve.Aggregator.ingest` — watermark
        dedup and the generation fence do all the correctness work; this
        wrapper only adds the per-peer staleness bookkeeping and the
        ``serve.cross_region_merges`` count. Raises exactly what ingest
        raises (:class:`~metrics_tpu.serve.FencedGenerationError` for a
        zombie, wire/schema errors for corrupt or incompatible replicas —
        ``schema_diff`` names the exact differing path when regions
        disagree on a tenant's schema)."""
        self._require_up()
        peer = header = None
        try:
            _, header = peek_header(bytes(data))
            meta = header.get("meta") or {}
            peer = str(meta.get("region")) if meta.get("region") is not None else None
        except Exception:  # noqa: BLE001 — ingest below raises the loud version
            header = None
        before = None
        if header is not None:
            try:
                before = self.global_view.client_watermark(
                    str(header["tenant"]), str(header["client"])
                )
            except Exception:  # noqa: BLE001 — unknown tenant: ingest raises below
                before = None
        accepted = self.global_view.ingest(data)
        if accepted:
            # fold synchronously: replication runs at control-plane cadence,
            # not the hot ingest path, and the caller needs the dedup
            # verdict NOW — ingest only enqueues, so "did this replica
            # advance its region's watermark" (and the fence learning that
            # rides acceptance) materializes at this flush
            self.global_view.flush()
            if header is not None:
                try:
                    wm = (int(header["watermark"][0]), int(header["watermark"][1]))
                    after = self.global_view.client_watermark(
                        str(header["tenant"]), str(header["client"])
                    )
                    # accepted = this payload ADVANCED the watermark to its
                    # own mark; a duplicate (before == wm) or a stale /
                    # fence-dropped delivery (after unchanged) did not
                    accepted = after == wm and before != wm
                except Exception:  # noqa: BLE001 — accounting only; the fold stands
                    accepted = False
        if peer is not None and peer != self.name:
            # even a dedup-shed duplicate proves the peer is alive and its
            # link healthy — staleness measures REACHABILITY, not novelty
            with self._lock:
                self._peer_last_accept[peer] = time.monotonic()
            if _obs_enabled() and accepted:
                _obs_inc("serve.cross_region_merges", node=self.name, peer=peer)
        return accepted

    def peer_staleness_s(self) -> Dict[str, Optional[float]]:
        """Per-peer replica age in seconds (None = never heard from).
        Exports ``serve.peer_staleness_ms{node=,peer=}`` gauges as a side
        effect — the surface :class:`~metrics_tpu.obs.health.HealthMonitor`'s
        ``peer_stale`` condition reads."""
        now = time.monotonic()
        out: Dict[str, Optional[float]] = {}
        with self._lock:
            peers = list(self._peers)
            stamps = dict(self._peer_last_accept)
        armed = _obs_enabled()
        for peer in peers:
            last = stamps.get(peer)
            age = None if last is None else max(0.0, now - last)
            out[peer] = age
            if armed and age is not None:
                _obs_gauge("serve.peer_staleness_ms", age * 1000.0, node=self.name, peer=peer)
        return out

    # ------------------------------------------------------------------
    # degraded-read contract
    # ------------------------------------------------------------------

    def query_global(self, tenant_id: str, *, refresh_local: bool = True) -> Dict[str, Any]:
        """The region's GLOBAL answer with per-region freshness.

        Extends :meth:`Aggregator.query` over the global view with a
        ``regions`` freshness map (this region reads fresh by
        construction — ``refresh_local`` self-ships the regional
        cumulative first, so the answer is always **local-complete**),
        the ``degraded`` verdict and ``stale_regions`` under the
        ``max_staleness_s`` policy. With ``stale_reads="reject"`` a
        policy violation raises :class:`StaleGlobalViewError` instead of
        answering — the HTTP surface's 503. Observes the answer's
        worst-peer staleness into ``serve.global_query_staleness_ms``."""
        self._require_up()
        if refresh_local:
            # only the QUERIED tenant: a multi-tenant node must not pay
            # T-1 irrelevant full-state encodes on every read
            for blob in self.snapshot_payloads(tenants=[tenant_id]):
                self.global_view.ingest(blob)
        out = self.global_view.query(tenant_id)
        staleness = self.peer_staleness_s()
        regions: Dict[str, Any] = {
            self.name: {"staleness_s": 0.0, "stale": False, "generation": self.generation}
        }
        stale_regions: List[str] = []
        worst_ms = 0.0
        for peer, age in sorted(staleness.items()):
            stale = age is None or (
                self.max_staleness_s is not None and age > self.max_staleness_s
            )
            regions[peer] = {
                "staleness_s": age,
                "stale": bool(stale),
                "generation": self.global_view.generation_fence(f"region:{peer}"),
            }
            if stale:
                stale_regions.append(peer)
            if age is not None:
                worst_ms = max(worst_ms, age * 1000.0)
        out["region"] = self.name
        out["generation"] = self.generation
        out["regions"] = regions
        out["local_complete"] = True
        out["degraded"] = bool(stale_regions)
        out["stale_regions"] = stale_regions
        if _obs_enabled():
            _obs_observe("serve.global_query_staleness_ms", worst_ms, node=self.name)
        if stale_regions and self.stale_reads == "reject":
            raise StaleGlobalViewError(
                f"region {self.name!r} global view is STALE for"
                f" {len(stale_regions)} peer region(s) ({', '.join(stale_regions)})"
                + (
                    f" beyond max_staleness_s={self.max_staleness_s}"
                    if self.max_staleness_s is not None
                    else " (never replicated)"
                )
                + " — answering would silently misrepresent the fleet; query this"
                " region's local tier, a healthy region, or accept degraded reads"
                " (stale_reads='degraded')",
                stale_regions=stale_regions,
                retry_after_s=self.max_staleness_s,
            )
        return out

    # ------------------------------------------------------------------
    # failure / failover surface
    # ------------------------------------------------------------------

    def _require_up(self) -> None:
        if self.down:
            raise RegionDownError(
                f"region {self.name!r} is down (its root was killed); promote a"
                " standby via RegionalMesh.promote() before using it"
            )

    def hard_kill(self) -> None:
        """Simulate losing the region's root process: the regional tree's
        root is hard-killed (state gone, no cleanup) and every region
        surface raises :class:`RegionDownError` until a standby is
        promoted. The global-view checkpoint on disk — and the peers'
        copies of this region's replicas — are all that survive, which is
        the whole failover design point."""
        if self.tree is not None:
            self.tree.root.hard_kill()
        self.down = True

    def _stamp_manifest_extra(self) -> None:
        # the generation rides the checkpoint manifest so promotion
        # survives restarts: a standby restored from this checkpoint minted
        # its generation strictly above what is recorded here
        self.global_view.manifest_extra = {
            "region": self.name,
            "generation": int(self.generation),
        }

    def set_generation(self, generation: int) -> None:
        """Adopt a (promotion-minted) generation: stamped into every later
        ship's watermark epoch + meta, persisted via the manifest; the
        ship sequence restarts — ``(generation, 0)`` supersedes every
        older-generation watermark by lexicographic comparison."""
        with self._lock:
            self.generation = int(generation)
            self._ship_seq = itertools.count(0)
        if self.global_view.history is not None:
            # later interval cuts stamp the new generation, fencing delta
            # range queries across the failover boundary (pre-promotion
            # intervals keep their OLD stamp — cumulative reads stay exact)
            self.global_view.history.generation = int(generation)
        self._stamp_manifest_extra()
        if _obs_enabled():
            _obs_gauge("serve.region_generation", float(self.generation), region=self.name)

    def save(self) -> str:
        """Checkpoint the region's state of record: the regional root's
        per-client slots AND the global view (replica slots + watermarks +
        fences + generation manifest). Returns the global view's
        checkpoint path."""
        self._stamp_manifest_extra()
        if self.local_root._manager is not None:
            self.local_root.save()
        return self.global_view.save()

    def restore(self, path: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Restore both tiers from their newest checkpoints (regional root
        first — its per-client slots are what the first post-restore ship
        carries); adopts the global manifest's recorded generation when it
        is ahead of ours. Returns the global manifest (None on a fresh
        start). No-op (None) for a region built without ``checkpoint_dir``
        — a checkpointless region's failover relies wholly on the peers'
        replicas and the clients' cumulative re-ships."""
        if self.local_root._manager is not None:
            self.local_root.restore()
        if self.global_view._manager is None:
            return None
        manifest = self.global_view.restore(path)
        if manifest is not None:
            recorded = ((manifest.get("extra") or {}).get("serve") or {}).get("node_meta") or {}
            gen = recorded.get("generation")
            if gen is not None and int(gen) > self.generation:
                self.set_generation(int(gen))
        return manifest

    def warmup(self) -> int:
        """Pre-warm fold executables before traffic (global view + the
        regional root): with a shared AOT program store this performs
        zero backend compiles — the promotion path's cold-start
        contract. Returns programs resolved."""
        warmed = self.global_view.warmup()
        warmed += self.local_root.warmup()
        return warmed

    def standby(self) -> "Region":
        """Build this region's warm standby from the retained recipe: the
        same name (the ``region:<name>`` identity IS the region — failover
        replaces the root, not the region), tenants, topology, policy,
        checkpoint dir and engine store. The mesh's
        :meth:`~RegionalMesh.promote` restores + warms it and mints the
        successor generation."""
        return Region(self.name, self._config["tenants"], **{
            k: v for k, v in self._config.items() if k != "tenants"
        })


class RegionalMesh:
    """N regional roots cross-merging via the ordinary wire format.

    Wires every region pair with a replication link (default: in-process
    ``dst.accept_replica``; point :meth:`set_link` at an HTTP client to
    cross real process boundaries — the payload bytes are identical), and
    drives the replication loop: each :meth:`replicate` tick ships every
    region's cumulative aggregate to itself and every peer under the
    retry policy. Per-link failures never abort the sweep — they are
    counted, surfaced as gauges, and repaired by the next tick's
    cumulative ship (the same transient-by-contract stance
    :meth:`~metrics_tpu.serve.tree.AggregatorNode.forward` takes).

    Args:
        regions: the mesh members (names must be unique).
        retry_policy: per-ship :class:`~metrics_tpu.ft.RetryPolicy`; the
            default caps the whole cycle with ``deadline_s`` well below
            typical replication cadences and decorrelates the jitter per
            (source, peer) link.
        replicate_interval_s: the :meth:`start` background cadence.

    Example::

        mesh = RegionalMesh([
            Region("us", tenants, checkpoint_dir=ckpt_us),
            Region("eu", tenants, checkpoint_dir=ckpt_eu),
            Region("ap", tenants, checkpoint_dir=ckpt_ap),
        ])
        mesh.region("us").ingest(payload)    # clients ship regionally
        mesh.replicate()                     # or mesh.start()
        mesh.region("eu").query_global("t")  # any region answers globally
    """

    def __init__(
        self,
        regions: Sequence[Region],
        *,
        retry_policy: Any = None,
        replicate_interval_s: float = 1.0,
    ) -> None:
        from metrics_tpu.ft.retry import RetryPolicy

        self._regions: Dict[str, Region] = {}
        self._links: Dict[Tuple[str, str], Callable[[bytes], Any]] = {}
        self._link_failures: Dict[Tuple[str, str], int] = {}
        self._lock = threading.RLock()
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.replicate_interval_s = float(replicate_interval_s)
        if retry_policy is None:
            # deadline_s: each LINK's whole retry cycle (attempts + backoff)
            # is a fraction of the cadence — links are retried sequentially
            # within a sweep, so a budget equal to the full tick would let
            # ONE dead peer push every source's sweep past the interval and
            # age healthy peers' replicas too. A quarter-tick per link keeps
            # even a several-dead-peer sweep inside a couple of intervals.
            retry_policy = RetryPolicy(
                max_retries=2,
                backoff_s=0.05,
                max_backoff_s=1.0,
                deadline_s=max(0.1, self.replicate_interval_s / 4.0),
                jitter="decorrelated",
                jitter_seed=0,
                degraded_fallback=True,
            )
        self.retry_policy = retry_policy
        for region in regions:
            self.add_region(region)

    # ------------------------------------------------------------------
    # membership / wiring
    # ------------------------------------------------------------------

    def add_region(self, region: Region) -> Region:
        with self._lock:
            if region.name in self._regions:
                raise ServeError(f"region {region.name!r} is already in the mesh")
            self._regions[region.name] = region
            for peer_name, peer in self._regions.items():
                if peer_name == region.name:
                    continue
                self._links[(region.name, peer_name)] = self._default_link(peer)
                self._links[(peer_name, region.name)] = self._default_link(region)
            self._rewire_peer_lists()
        if _obs_enabled():
            _obs_gauge("serve.mesh_regions", float(len(self._regions)))
        return region

    @staticmethod
    def _default_link(dst: Region) -> Callable[[bytes], Any]:
        return dst.accept_replica

    def _rewire_peer_lists(self) -> None:
        names = sorted(self._regions)
        for name, region in self._regions.items():
            with region._lock:
                region._peers = [n for n in names if n != name]

    def set_link(self, src: str, dst: str, send: Callable[[bytes], Any]) -> None:
        """Override one directed replication link (e.g. an HTTP POST to
        the peer's ``/ingest`` — the bytes are the same). The chaos
        :func:`~metrics_tpu.ft.faults.region_partition` injector swaps
        these too."""
        key = (str(src), str(dst))
        with self._lock:
            if key not in self._links:
                raise ServeError(f"no replication link {src!r} -> {dst!r} in this mesh")
            self._links[key] = send

    def region(self, name: str) -> Region:
        with self._lock:
            region = self._regions.get(str(name))
        if region is None:
            raise ServeError(
                f"no region {name!r} in this mesh (regions: {sorted(self._regions)})"
            )
        return region

    def regions(self) -> List[str]:
        with self._lock:
            return sorted(self._regions)

    # ------------------------------------------------------------------
    # the replication loop
    # ------------------------------------------------------------------

    def replicate(self, rounds: int = 1) -> int:
        """One (or more) full replication sweep(s): every live region
        ships its cumulative regional aggregate to itself and every peer.
        Returns payloads delivered (self-ships included). Per-peer
        failures are retried under the policy (bounded by its
        ``deadline_s``), then counted under
        ``serve.replication_errors{node=,peer=}`` and reflected in the
        ``serve.peers_unreachable{node=}`` gauge — never raised: the next
        sweep's cumulative ship repairs everything a missed one skipped."""
        from dataclasses import replace

        from metrics_tpu.ft.retry import call_with_retries

        delivered = 0
        for _ in range(int(rounds)):
            with self._lock:
                regions = dict(self._regions)
                links = dict(self._links)
            for src_name, src in sorted(regions.items()):
                if src.down:
                    continue
                try:
                    payloads = src.snapshot_payloads()
                except Exception as err:  # noqa: BLE001 — a source that cannot
                    # snapshot (marked down, or its tree root died without the
                    # kill_region seam) must not abort the sweep for every
                    # OTHER region; the (src, src) failure key reads as "the
                    # source itself", counted and one-shot-warned like a link
                    if not isinstance(err, RegionDownError):
                        self._note_link_failure(src_name, src_name, err)
                    self._export_unreachable(src_name)
                    continue
                with self._lock:
                    # a healthy snapshot clears the source's own failure key
                    # (nothing else ever would — the success pop below only
                    # covers real (src, dst) links, and a permanently stale
                    # entry would page partition_detected on a healed mesh)
                    self._link_failures.pop((src_name, src_name), None)
                # self-ship first: the region's own global view must be
                # local-complete even when every peer is unreachable
                for blob in payloads:
                    src.global_view.ingest(blob)
                    delivered += 1
                for dst_name in sorted(regions):
                    if dst_name == src_name:
                        continue
                    link = links[(src_name, dst_name)]
                    # distinct (src, dst) jitter streams: two regions that
                    # lose the same peer at the same instant must not
                    # thunder back in lockstep
                    policy = replace(
                        self.retry_policy,
                        jitter_seed=(
                            None
                            if self.retry_policy.jitter_seed is None
                            else self.retry_policy.jitter_seed
                            + (zlib.crc32(f"{src_name}->{dst_name}".encode()) & 0xFFFF)
                        ),
                    )

                    def _ship(link=link, payloads=payloads):
                        for blob in payloads:
                            link(blob)
                        return len(payloads)

                    try:
                        delivered += call_with_retries(
                            _ship,
                            op=f"region.replicate:{src_name}->{dst_name}",
                            policy=policy,
                            fallback=None,
                        )
                        with self._lock:
                            self._link_failures.pop((src_name, dst_name), None)
                    except Exception as err:  # noqa: BLE001 — one bad link must
                        # not abort the sweep for every other peer. The family
                        # is broad on purpose: retries exhausted
                        # (DegradedSyncError), a dead/unpromoted peer
                        # (RegionDownError), a fenced zombie identity, and a
                        # cross-region SCHEMA disagreement (SchemaMismatchError
                        # — whose message carries schema_diff's exact differing
                        # path) all land in the same counted, one-shot-warned
                        # bucket; the warning text names the real cause.
                        self._note_link_failure(src_name, dst_name, err)
                self._export_unreachable(src_name)
            for region in regions.values():
                # refresh the serve.peer_staleness_ms gauges every sweep:
                # a BLACK-HOLING partition fails no link (the drop looks
                # like success), so without this the peer_stale health
                # condition would be blind until some global query happened
                # to run — the background loop must keep the receiver-side
                # signal live on its own
                if not region.down:
                    region.peer_staleness_s()
        return delivered

    def _note_link_failure(self, src: str, dst: str, err: BaseException) -> None:
        with self._lock:
            first = (src, dst) not in self._link_failures
            self._link_failures[(src, dst)] = self._link_failures.get((src, dst), 0) + 1
        if _obs_enabled():
            _obs_inc("serve.replication_errors", node=src, peer=dst)
        if first:
            warnings.warn(
                f"region {src!r} could not replicate to peer {dst!r} ({err});"
                " the peer's global view serves LOCAL-COMPLETE / GLOBAL-STALE"
                " answers until a sweep succeeds (cumulative ships repair on"
                " heal; serve.replication_errors counts further failures).",
                stacklevel=2,
            )

    def _export_unreachable(self, src: str) -> None:
        if not _obs_enabled():
            return
        with self._lock:
            unreachable = sum(1 for (s, _d) in self._link_failures if s == src)
        _obs_gauge("serve.peers_unreachable", float(unreachable), node=src)

    def start(self, interval_s: Optional[float] = None) -> "RegionalMesh":
        """Run :meth:`replicate` on a daemon worker every
        ``replicate_interval_s`` until :meth:`stop`. Idempotent."""
        if interval_s is not None:
            self.replicate_interval_s = float(interval_s)
        if self._worker is not None and self._worker.is_alive():
            return self
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.replicate_interval_s):
                try:
                    self.replicate()
                except Exception as err:  # noqa: BLE001 — a dying loop is a
                    # silently-partitioned mesh; surface and keep sweeping
                    if _obs_enabled():
                        _obs_inc("serve.replication_loop_errors")
                    warnings.warn(
                        f"mesh replication sweep failed: {type(err).__name__}: {err}",
                        stacklevel=2,
                    )

        self._worker = threading.Thread(target=loop, name="serve-mesh-replicate", daemon=True)
        self._worker.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._worker is not None:
            self._worker.join()
            self._worker = None

    # ------------------------------------------------------------------
    # failover
    # ------------------------------------------------------------------

    def promote(self, name: str) -> Region:
        """Promote a warm standby for region ``name``'s (dead) root.

        The standby is built from the region's retained recipe, **warmed
        before traffic** (fold executables resolve through the shared
        engine store — zero backend compiles when the store is warm),
        restored from the global view's newest checkpoint (replica slots,
        watermarks, fences, recorded generation), and minted the
        **successor generation**: strictly above both the checkpoint's
        record and the old in-memory root's. Every reachable peer fences
        the promoted generation immediately (``fence_generation``), so a
        zombie predecessor's ships are refused from this moment — even
        before the standby's first replica teaches them. Peers' next
        replicas repair anything the checkpoint missed (cumulative
        snapshots; nothing to anti-entropy). The standby replaces the old
        region in the mesh and is returned; the displaced object is left
        untouched as the would-be zombie."""
        t0 = time.perf_counter()
        with self._lock:
            old = self._regions.get(str(name))
            if old is None:
                raise ServeError(f"no region {name!r} in this mesh to promote")
        standby = old.standby()
        # warm FIRST: executables are ready the moment states land, and a
        # corrupt cached program fails HERE, not under promoted traffic
        standby.warmup()
        standby.restore()
        generation = max(standby.generation, old.generation) + 1
        standby.set_generation(generation)
        if standby.global_view._manager is not None:
            # the minted generation must survive the next crash; a region
            # built WITHOUT checkpoint_dir still promotes — its state
            # repairs entirely from peers' replicas and client re-ships,
            # and its generation floor is the displaced root's memory
            standby.save()
        with self._lock:
            self._regions[str(name)] = standby
            # rebuild every link touching the region: the old object's
            # bound methods must not keep receiving (or sending) replicas
            for peer_name, peer in self._regions.items():
                if peer_name == str(name):
                    continue
                self._links[(str(name), peer_name)] = self._default_link(peer)
                self._links[(peer_name, str(name))] = self._default_link(standby)
                self._link_failures.pop((peer_name, str(name)), None)
            self._rewire_peer_lists()
            peers = [r for n, r in self._regions.items() if n != str(name)]
        for peer in peers:
            # proactive fence advance: the window between promotion and the
            # standby's first replica must not admit a zombie ship
            try:
                peer.global_view.fence_generation(f"region:{name}", generation)
            except Exception:  # noqa: BLE001 — an unreachable peer learns the
                # fence from the standby's first accepted replica instead
                continue
        if _obs_enabled():
            _obs_inc("serve.promotions", region=str(name))
            _obs_gauge("serve.region_generation", float(generation), region=str(name))
            _obs_observe("serve.promote_ms", (time.perf_counter() - t0) * 1000.0)
        return standby

    # ------------------------------------------------------------------
    # read side
    # ------------------------------------------------------------------

    def query(self, tenant_id: str, region: Optional[str] = None) -> Dict[str, Any]:
        """Global query at ``region`` (default: the first live region) —
        the single-pane read over the whole mesh."""
        if region is not None:
            return self.region(region).query_global(tenant_id)
        for name in self.regions():
            candidate = self.region(name)
            if not candidate.down:
                return candidate.query_global(tenant_id)
        raise RegionDownError("every region in the mesh is down")
