"""Core ``Metric`` base class: state registry, lifecycle, sync, algebra.

TPU-native re-design of the reference's ``torchmetrics/metric.py`` (``Metric``
:44, ``add_state`` :165, ``forward`` :235, ``_sync_dist`` :279, ``sync``/
``unsync``/``sync_context`` :325/:361/:383, ``reset`` :456, ``state_dict``
:571, operator overloads :652-756, ``CompositionalMetric`` :762).

Design differences from the reference (deliberate, TPU-first):

* **State is a pytree of jnp arrays** (plus Python lists for cat-states),
  HBM-resident. ``state_pytree()``/``load_state_pytree()`` expose it for
  ``jax.jit``/``shard_map`` pipelines and orbax checkpointing.
* **forward is a single fused step.** The reference runs ``update`` twice per
  batch (metric.py:248 + :263). Here, when ``full_state_update`` is False
  (the default — correct for every monoid-accumulated metric), ``forward``
  computes batch-local sufficient statistics once, derives the batch value
  from them, and merges them into the accumulated state via the per-state
  reduction (sum -> add, max -> maximum, min -> minimum, cat -> append).
* **Distributed sync lowers to mesh collectives.** Cross-process (DCN) sync
  uses ``gather_all_tensors`` (multihost allgather with uneven-shape
  padding); in-jit SPMD sync uses ``lax.psum/pmin/pmax/all_gather`` via
  ``metrics_tpu.utilities.distributed.sync_reduce_in_context``. The
  reference's ``process_group`` maps to mesh axis names.

There is no nn.Module here: device placement is XLA's job, and torch.jit
scriptability is replaced by the update/compute kernels being jit-traceable.
"""
import functools
import inspect
import time
from abc import ABC, abstractmethod
from contextlib import contextmanager
from copy import deepcopy
from typing import Any, Callable, Dict, Generator, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.buffers import CapacityBuffer
from metrics_tpu.utilities.data import (
    _flatten,
    _squeeze_if_scalar,
    apply_to_collection,
    coerce_foreign_tensors,
    foreign_coercion_scope,
    dim_zero_cat,
)
from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import observe as _obs_observe
from metrics_tpu.obs.registry import set_gauge as _obs_gauge
from metrics_tpu.obs.tracing import pytree_nbytes as _obs_nbytes
from metrics_tpu.obs.tracing import trace_span as _obs_span
from metrics_tpu.streaming.sketches import Sketch
from metrics_tpu.utilities.distributed import distributed_available, gather_all_tensors
from metrics_tpu.utilities.exceptions import MetricsTPUUserError
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

# "sketch" marks a state whose value is a mergeable summary
# (metrics_tpu.streaming.sketches.Sketch): merged with state.merge(other)
# in forward folds and the eager gather, and leafwise psum/pmin/pmax under
# shard_map (utilities.distributed.sync_sketch_in_context)
_VALID_REDUCTIONS = ("sum", "mean", "cat", "min", "max", "sketch")

# named reductions registered at runtime via register_state_reduction():
# {name: {"merge": a,b -> merged, "fold": (B, *state) -> state,
#         "list_reduce": [per-rank states] -> state}}
_CUSTOM_REDUCTIONS: Dict[str, Dict[str, Callable]] = {}


def register_state_reduction(
    name: str,
    *,
    merge: Callable,
    fold: Optional[Callable] = None,
    list_reduce: Optional[Callable] = None,
) -> None:
    """Register a custom named ``dist_reduce_fx`` for :meth:`Metric.add_state`.

    The hook extends the reduce registries end to end: the eager
    ``forward`` merge and cross-process gather (this module), and the
    merge-combinable fast paths of :func:`metrics_tpu.steps.make_epoch` /
    the fused collection factories (``_MERGE_OPS``/``_FOLD_OPS``) — a
    metric whose every state uses a registered reduction rides the
    flattened one-launch epoch and the collection update-dedup grouping
    exactly like a ``sum`` state.

    Args:
        name: the registry key (usable as ``dist_reduce_fx=name``). Must
            not collide with a built-in reduction.
        merge: ``(acc, batch) -> merged`` — MUST be associative and
            commutative with the state default as identity, and merging
            per-batch contributions must equal one update over the
            concatenated batches (the same invariant the DDP gather-reduce
            sync and the flattened-epoch fast path rely on for sum/max/min).
        fold: ``stacked (B, *state) -> state`` down the leading axis;
            defaults to a left fold of ``merge`` over that axis.
        list_reduce: ``[per-rank states] -> state`` for the eager DCN
            gather; defaults to a left fold of ``merge``.

    Note:
        In-jit mesh sync (``axis_name=``) still requires one of the
        built-in collective reductions; custom names are for the eager
        gather and the merge-combinable single-launch paths.
    """
    global _VALID_REDUCTIONS
    if not name or not isinstance(name, str):
        raise ValueError(f"Reduction name must be a non-empty string, got {name!r}")
    if name in _VALID_REDUCTIONS and name not in _CUSTOM_REDUCTIONS:
        raise ValueError(f"Cannot override the built-in reduction {name!r}")
    if not callable(merge):
        raise ValueError("`merge` must be callable")
    if fold is None:
        def fold(stacked: Any, _merge: Callable = merge) -> Any:
            return functools.reduce(_merge, [stacked[i] for i in range(stacked.shape[0])])
    if list_reduce is None:
        def list_reduce(outputs: List[Any], _merge: Callable = merge) -> Any:
            return functools.reduce(_merge, outputs)
    _CUSTOM_REDUCTIONS[name] = {"merge": merge, "fold": fold, "list_reduce": list_reduce}
    if name not in _VALID_REDUCTIONS:
        _VALID_REDUCTIONS = _VALID_REDUCTIONS + (name,)
    # propagate into the step-fusion registries (deferred import: steps
    # imports this module at load)
    from metrics_tpu import steps as _steps

    _steps._MERGE_OPS[name] = merge
    _steps._FOLD_OPS[name] = fold


def jit_distributed_available() -> bool:
    """Availability probe (parity with reference ``metric.py:40``)."""
    return distributed_available()


class Metric(ABC):
    """Base class for all metrics.

    States registered with :meth:`add_state` live as jnp arrays (or lists of
    arrays for ``cat``-accumulated states). Subclasses implement
    :meth:`update` (accumulate a batch into state) and :meth:`compute`
    (state -> metric value); both are wrapped automatically with the
    lifecycle machinery (sync guard, result caching, dist sync context).

    Args:
        compute_on_cpu: move list states to host memory after each update
            (parity with reference ``metric.py:125``; frees TPU HBM for
            unbounded-accumulation metrics).
        dist_sync_on_step: synchronize state across processes on every
            ``forward`` (parity with reference ``metric.py:131``).
        process_group: process subset / mesh-axis names to sync over (API
            parity; the eager path syncs over all processes).
        dist_sync_fn: custom gather ``(tensor, group) -> List[tensor]``
            (parity with reference ``metric.py:139``).
        sync_on_compute: automatically sync in :meth:`compute`.
    """

    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False
    # update accepts a per-sample weight vector whose semantics equal sample
    # repetition (update(value, weight) with weight=c == c repeats) — lets
    # BootStrapper express the poisson bootstrap as one vmapped weighted
    # update instead of N variable-size resamples
    supports_sample_weights: bool = False
    # extra update-derived Python attrs (e.g. detected input mode) that must
    # survive a checkpoint round-trip alongside the array states
    _aux_attrs: tuple = ()

    def __init__(
        self,
        compute_on_cpu: bool = False,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        sync_on_compute: bool = True,
        distributed_available_fn: Optional[Callable] = None,
        **kwargs: Any,
    ) -> None:
        if kwargs:
            raise ValueError(f"Unexpected keyword arguments: {', '.join(sorted(kwargs))}")
        # kwarg type validation, mirroring reference metric.py:125-143
        if not isinstance(compute_on_cpu, bool):
            raise ValueError(f"Expected keyword argument `compute_on_cpu` to be a `bool` but got {compute_on_cpu}")
        if not isinstance(dist_sync_on_step, bool):
            raise ValueError(f"Expected keyword argument `dist_sync_on_step` to be a `bool` but got {dist_sync_on_step}")
        if not isinstance(sync_on_compute, bool):
            raise ValueError(f"Expected keyword argument `sync_on_compute` to be a `bool` but got {sync_on_compute}")
        if dist_sync_fn is not None and not callable(dist_sync_fn):
            raise ValueError(f"Expected keyword argument `dist_sync_fn` to be a callable function but got {dist_sync_fn}")
        if distributed_available_fn is not None and not callable(distributed_available_fn):
            raise ValueError(
                f"Expected keyword argument `distributed_available_fn` to be a callable function but got {distributed_available_fn}"
            )
        self.compute_on_cpu = compute_on_cpu
        self.dist_sync_on_step = dist_sync_on_step
        self.process_group = process_group
        self.dist_sync_fn = dist_sync_fn
        self.sync_on_compute = sync_on_compute
        self.distributed_available_fn = distributed_available_fn or distributed_available

        self._defaults: Dict[str, Union[Array, List]] = {}
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, Union[str, Callable, None]] = {}
        # declarative per-state sharding (utilities.sharding.StateShardSpec):
        # which dim of the state's arrays distributes over the sync mesh
        # axis — consumed by state_shardings() (the pjit layout) and the
        # make_step(sharded_state=True) gather-free compute path
        self._shard_specs: Dict[str, Any] = {}
        self._dtype = jnp.asarray(0.0).dtype

        self._update_count = 0
        self._computed: Any = None
        self._forward_cache: Any = None
        self._dtype_forced = False
        self._to_sync = sync_on_compute
        self._should_unsync = True
        self._is_synced = False
        self._cache: Optional[Dict[str, Union[Array, List]]] = None

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        if "update" in cls.__dict__ and not getattr(cls.__dict__["update"], "_lifecycle_wrapped", False):
            cls.update = _wrap_update(cls.__dict__["update"])
        if "compute" in cls.__dict__ and not getattr(cls.__dict__["compute"], "_lifecycle_wrapped", False):
            cls.compute = _wrap_compute(cls.__dict__["compute"])

    # ------------------------------------------------------------------
    # State registry
    # ------------------------------------------------------------------

    def add_state(
        self,
        name: str,
        default: Union[Array, List],
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
        shard_spec: Optional[Any] = None,
    ) -> None:
        """Register a metric state (reference ``metric.py:165``).

        ``default`` is a jnp array (the reset value) or an empty list (a
        ``cat``-accumulated state). ``dist_reduce_fx`` in ``{"sum", "mean",
        "cat", "min", "max", None, callable}`` declares how the state
        synchronizes across devices/processes.

        ``shard_spec`` (a
        :class:`~metrics_tpu.utilities.sharding.StateShardSpec`) declares
        which dimension of the state distributes over the sync mesh axis —
        the layout :meth:`state_shardings` lowers to pjit ``NamedSharding``
        and the ``make_step(sharded_state=True)`` path reduce-scatters
        along. Defaults: ``CapacityBuffer`` states shard their rows (dim
        0), sketch states shard per their class's ``_shard_dims``
        declaration, everything else stays replicated.
        """
        if isinstance(default, CapacityBuffer):
            if default:
                raise ValueError("`default` CapacityBuffer state must be initially empty")
            if dist_reduce_fx not in ("cat", None):
                raise ValueError("CapacityBuffer states require dist_reduce_fx='cat' or None")
        elif isinstance(default, Sketch):
            if dist_reduce_fx is None:
                dist_reduce_fx = "sketch"
            elif dist_reduce_fx != "sketch":
                raise ValueError("Sketch states require dist_reduce_fx='sketch' or None")
        elif isinstance(default, (np.ndarray, np.generic)):
            default = jnp.asarray(default)
        if dist_reduce_fx == "sketch" and not isinstance(default, Sketch):
            raise ValueError("dist_reduce_fx='sketch' requires a streaming.sketches.Sketch default")
        # python scalars/other types are rejected like the reference
        # (metric.py:188-191)
        if not isinstance(default, (list, jnp.ndarray, jax.Array, CapacityBuffer, Sketch)):
            raise ValueError("Invalid `default`: state must be a jax array or an empty list")
        if isinstance(default, list) and default:
            raise ValueError("`default` list state must be initially empty")
        if dist_reduce_fx is not None and not callable(dist_reduce_fx) and dist_reduce_fx not in _VALID_REDUCTIONS:
            raise ValueError(f"`dist_reduce_fx` must be callable or one of {_VALID_REDUCTIONS + (None,)}")

        if shard_spec is not None:
            from metrics_tpu.utilities.sharding import StateShardSpec

            if not isinstance(shard_spec, StateShardSpec):
                raise ValueError(
                    f"`shard_spec` must be a utilities.sharding.StateShardSpec, got {shard_spec!r}"
                )
        elif isinstance(default, CapacityBuffer):
            from metrics_tpu.utilities.sharding import StateShardSpec

            # rows distribute over the mesh (the buffer's declared axis)
            shard_spec = StateShardSpec(dim=CapacityBuffer.SHARD_DIM)

        self._defaults[name] = deepcopy(default)
        self._persistent[name] = persistent
        self._reductions[name] = dist_reduce_fx
        if shard_spec is not None:
            self._shard_specs[name] = shard_spec
        setattr(self, name, deepcopy(default))

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    @abstractmethod
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Accumulate a batch into state."""

    @abstractmethod
    def compute(self) -> Any:
        """Aggregate state into the metric value."""

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate the batch AND return the batch-local metric value."""
        if _obs_enabled():
            name = type(self).__name__
            _obs_inc("metric.forwards", metric=name)
            with _obs_span(f"{name}.forward", category="forward"):
                return self._forward_impl(*args, **kwargs)
        return self._forward_impl(*args, **kwargs)

    def _forward_impl(self, *args: Any, **kwargs: Any) -> Any:
        # convert any torch inputs ONCE here: the full-state path calls
        # update() twice on the same batch, and the per-update coercion
        # would pay the host transfer twice
        args = coerce_foreign_tensors(args)
        kwargs = coerce_foreign_tensors(kwargs)
        with foreign_coercion_scope(args, kwargs):  # updates below must not re-walk these
            if self.full_state_update:
                return self._forward_full_state_update(*args, **kwargs)
            return self._forward_reduce_state_update(*args, **kwargs)

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        # Reference semantics (metric.py:235-275): global update, then the
        # batch value via reset -> update(batch) -> compute on scratch state.
        self.update(*args, **kwargs)
        _update_count = self._update_count
        cache = self._snapshot_state()

        self.reset()
        self.update(*args, **kwargs)
        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        self._forward_cache = self.compute()

        self._restore_state(cache)
        self._update_count = _update_count
        self._to_sync = self.sync_on_compute
        self._should_unsync = True
        self._computed = None
        self._is_synced = False
        self._cache = None
        return self._forward_cache

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        # Single fused step: batch stats once, value from them, monoid merge.
        global_state = self._snapshot_state()
        _update_count = self._update_count
        self.reset()

        self.update(*args, **kwargs)
        # Snapshot the *local* batch state BEFORE compute: with
        # dist_sync_on_step=True compute leaves the state cross-process
        # synced (no unsync), and merging that into the local accumulator
        # would double-count other processes at the final compute.
        batch_state = self._snapshot_state()
        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        self._forward_cache = self.compute()

        self._restore_state(global_state)
        self._update_count = _update_count
        self._reduce_states(batch_state)
        self._update_count = _update_count + 1
        self._to_sync = self.sync_on_compute
        self._should_unsync = True
        self._computed = None
        self._is_synced = False
        self._cache = None
        return self._forward_cache

    def _reduce_states(self, incoming: Dict[str, Union[Array, List]]) -> None:
        """Merge a batch-local state into accumulated state per reduction."""
        for name, reduce_fx in self._reductions.items():
            acc = getattr(self, name)
            new = incoming[name]
            if isinstance(acc, CapacityBuffer):
                if isinstance(new, CapacityBuffer) and new:
                    acc.append(new.materialize())
                setattr(self, name, acc)
                continue
            if isinstance(acc, list):
                setattr(self, name, acc + list(new))
                continue
            if reduce_fx == "sketch":
                setattr(self, name, acc.merge(new))
                continue
            if reduce_fx == "mean":
                # Running average over update calls (stack-mean over two
                # partials would mis-weight unequal histories).
                n = self._update_count
                merged = (acc * n + new) / (n + 1) if n > 0 else new
            elif reduce_fx is None:
                merged = new  # keep the newest value
            elif reduce_fx == "sum":
                # broadcasting binary ops: a scalar default merges cleanly
                # with a vector batch state (e.g. multioutput sums)
                merged = acc + new
            elif reduce_fx == "max":
                merged = jnp.maximum(acc, new)
            elif reduce_fx == "min":
                merged = jnp.minimum(acc, new)
            else:
                merged = _apply_reduction(reduce_fx, [acc, new])
            setattr(self, name, merged)

    def _snapshot_state(self) -> Dict[str, Union[Array, List]]:
        out: Dict[str, Union[Array, List]] = {}
        for name in self._defaults:
            value = getattr(self, name)
            if isinstance(value, CapacityBuffer):
                out[name] = deepcopy(value)
            else:
                out[name] = list(value) if isinstance(value, list) else value
        return out

    def _restore_state(self, cache: Dict[str, Union[Array, List]]) -> None:
        for name, value in cache.items():
            setattr(self, name, list(value) if isinstance(value, list) else value)

    def reset(self) -> None:
        """Reset state to defaults (reference ``metric.py:456``)."""
        if _obs_enabled():
            _obs_inc("metric.resets", metric=type(self).__name__)
            with _obs_span(f"{type(self).__name__}.reset", category="reset"):
                self._reset_impl()
            return
        self._reset_impl()

    def _reset_impl(self) -> None:
        self._update_count = 0
        self._forward_cache = None
        self._computed = None
        for name, default in self._defaults.items():
            setattr(self, name, deepcopy(default) if isinstance(default, (list, CapacityBuffer)) else default)
        self._cache = None
        self._is_synced = False

    def _move_list_states_to_cpu(self) -> None:
        """Offload cat-list states to host memory (reference ``metric.py:318``)."""
        cpu = jax.devices("cpu")[0]
        for name in self._defaults:
            value = getattr(self, name)
            if isinstance(value, list):
                setattr(self, name, [jax.device_put(v, cpu) for v in value])

    # ------------------------------------------------------------------
    # Distributed sync (eager cross-process path)
    # ------------------------------------------------------------------

    def _sync_dist(self, dist_sync_fn: Callable = gather_all_tensors, process_group: Optional[Any] = None) -> None:
        """Gather + reduce every state across processes (reference ``metric.py:279``).

        Degradation is atomic across the metric's states: each state is a
        separate eager gather, and if ANY of them falls back to its
        per-host partial (retries exhausted — see ``metrics_tpu.ft.retry``)
        the whole sync degrades to local-only state. A hybrid — one state
        globally summed, another local — would compute values that are
        neither the global nor the local answer (e.g. a global numerator
        over a local denominator).
        """
        from metrics_tpu.ft.retry import degraded_sync_scope

        input_dict = {name: getattr(self, name) for name in self._reductions}
        sketch_defs: Dict[str, Any] = {}
        for name, value in input_dict.items():
            if isinstance(value, list) and value:
                input_dict[name] = [dim_zero_cat(value)]
            elif isinstance(value, CapacityBuffer):
                input_dict[name] = [value.materialize()] if value else []
            elif isinstance(value, Sketch):
                # gather each static-shape leaf, rebuild one sketch per
                # rank below, then fold them with the merge monoid
                leaves, sketch_defs[name] = jax.tree_util.tree_flatten(value)
                input_dict[name] = leaves

        with degraded_sync_scope() as scope:
            output_dict = apply_to_collection(
                input_dict,
                (jnp.ndarray, jax.Array),
                dist_sync_fn,
                group=process_group or self.process_group,
            )
        if scope["degraded"]:
            # local-only for EVERY state: the per-host shape each gather's
            # own fallback produces, applied consistently
            output_dict = apply_to_collection(
                input_dict, (jnp.ndarray, jax.Array), lambda x, group=None: [x], group=None
            )

        for name, outputs in output_dict.items():
            if name in sketch_defs:
                # outputs is [leaf][rank]; regroup per rank and merge
                n_ranks = len(outputs[0]) if outputs else 1
                ranks = [
                    jax.tree_util.tree_unflatten(sketch_defs[name], [leaf_out[r] for leaf_out in outputs])
                    for r in range(n_ranks)
                ]
                setattr(self, name, functools.reduce(lambda a, b: a.merge(b), ranks))
                continue
            if isinstance(getattr(self, name), (list, CapacityBuffer)):
                # outputs is a list-of-lists: one gathered list per original
                # (pre-concatenated) element — flatten to per-rank tensors.
                if outputs and isinstance(outputs[0], list):
                    outputs = _flatten(outputs)
                setattr(self, name, list(outputs))
                continue
            reduce_fn = self._reductions[name]
            if reduce_fn is None:
                reduced = jnp.stack(outputs)  # hand per-rank stack to compute
            else:
                reduced = _apply_reduction(reduce_fn, outputs)
            setattr(self, name, reduced)

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available_fn: Optional[Callable] = None,
    ) -> None:
        """Synchronize state across processes (reference ``metric.py:325``)."""
        if self._is_synced and should_sync:
            raise MetricsTPUUserError("The Metric has already been synced.")
        is_distributed = (distributed_available_fn or self.distributed_available_fn)()
        if not should_sync or not is_distributed:
            if _obs_enabled():
                _obs_inc("metric.sync_noops", metric=type(self).__name__)
            return
        if dist_sync_fn is None:
            dist_sync_fn = self.dist_sync_fn or gather_all_tensors
        if _obs_enabled():
            _obs_inc("metric.syncs", metric=type(self).__name__)
            # one straggler probe per LOGICAL sync (per-leaf gathers would
            # align the hosts on the first barrier and record ~0 after);
            # internally gated on the OPT-IN arrival_skew_probe knob +
            # multi-process — the probe is a collective, so it only runs
            # where the operator armed it fleet-wide
            from metrics_tpu.utilities.distributed import record_arrival_skew

            record_arrival_skew()
        _t0 = time.perf_counter()
        with _obs_span(f"{type(self).__name__}.sync", category="sync"):
            self._cache = self._snapshot_state()
            self._sync_dist(dist_sync_fn, process_group=process_group)
        if _obs_enabled():
            # whole-metric sync latency (every state's gather) as a
            # distribution — the per-gather op=gather_all_tensors histogram
            # in utilities.distributed carries the per-collective view
            _obs_observe("metric.sync_ms", (time.perf_counter() - _t0) * 1000.0, metric=type(self).__name__)
        self._is_synced = True

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore pre-sync local state (reference ``metric.py:361``)."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise MetricsTPUUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise MetricsTPUUserError("The internal cache should exist to unsync the Metric.")
        self._restore_state(self._cache)
        self._is_synced = False
        self._cache = None

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available_fn: Optional[Callable] = None,
    ) -> Generator[None, None, None]:
        """Sync on entry, unsync on exit (reference ``metric.py:383``)."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            process_group=process_group,
            should_sync=should_sync,
            distributed_available_fn=distributed_available_fn,
        )
        yield
        self.unsync(should_unsync=self._is_synced and should_unsync)

    # ------------------------------------------------------------------
    # Pytree / serialization
    # ------------------------------------------------------------------

    def state_pytree(self) -> Dict[str, Union[Array, List[Array]]]:
        """The metric state as a pytree (for jit/shard_map pipelines, orbax)."""
        return self._snapshot_state()

    def state_shardings(self, mesh: Any, axis_name: Union[str, tuple]) -> Dict[str, Any]:
        """The declarative shard specs lowered to a ``NamedSharding`` pytree
        matching :meth:`state_pytree` — the pjit layout that keeps
        ``CapacityBuffer`` rows and sketch bins RESIDENT across ``mesh``
        (pass as ``in_shardings``/``out_shardings`` or to
        ``jax.device_put``). See
        :func:`metrics_tpu.utilities.sharding.state_named_shardings`."""
        from metrics_tpu.utilities.sharding import state_named_shardings

        return state_named_shardings(self, mesh, axis_name)

    def load_state_pytree(self, state: Dict[str, Union[Array, List[Array]]]) -> None:
        for name in self._defaults:
            if name in state:
                v = state[name]
                if isinstance(v, CapacityBuffer):
                    setattr(self, name, deepcopy(v))
                elif isinstance(v, Sketch):
                    setattr(self, name, v)  # immutable summary: share directly
                else:
                    setattr(self, name, list(v) if isinstance(v, (list, tuple)) else jnp.asarray(v))

    def state_dict(self, prefix: str = "") -> Dict[str, Any]:
        """Persistent-state snapshot (reference ``metric.py:571``)."""
        out: Dict[str, Any] = {}
        for name in self._defaults:
            if self._persistent[name]:
                value = getattr(self, name)
                out[prefix + name] = deepcopy(value) if isinstance(value, (list, CapacityBuffer)) else value
        return out

    def load_state_dict(self, state_dict: Dict[str, Any], prefix: str = "") -> None:
        for name in self._defaults:
            key = prefix + name
            if key in state_dict:
                v = state_dict[key]
                if isinstance(v, CapacityBuffer):
                    setattr(self, name, deepcopy(v))
                elif isinstance(v, Sketch):
                    setattr(self, name, v)
                else:
                    setattr(self, name, list(v) if isinstance(v, (list, tuple)) else jnp.asarray(v))

    def persistent(self, mode: bool = False) -> None:
        """Toggle persistence of all states (reference ``metric.py:566``)."""
        for name in self._persistent:
            self._persistent[name] = mode

    def save(self, path: Any) -> None:
        """Atomically persist this metric's state to ``path``.

        The state pytree (including cat lists, ``CapacityBuffer`` contents
        and ``_update_count``) is staged and published with one rename, so
        a crash mid-save never leaves a corrupt checkpoint. In a
        distributed setting save inside ``sync_context()`` so the persisted
        state is the globally-reduced one. For rotation, manifests, async
        saves and exactly-once resume cursors use
        :class:`metrics_tpu.ft.CheckpointManager`.
        """
        from metrics_tpu.utilities.checkpoint import save_state

        save_state(path, self)

    def restore(self, path: Any) -> "Metric":
        """Restore state saved by :meth:`save` into this metric; returns
        ``self``, which continues accumulating from the restored point."""
        from metrics_tpu.utilities.checkpoint import restore_state

        restore_state(path, self)
        return self

    # ------------------------------------------------------------------
    # Misc protocol
    # ------------------------------------------------------------------

    def clone(self) -> "Metric":
        return deepcopy(self)

    def set_dtype(self, dst_type: Any) -> "Metric":
        """Cast all floating-point states (reference ``metric.py:542``)."""
        self._dtype = jnp.dtype(dst_type)
        self._dtype_forced = True

        def _cast(x: Array) -> Array:
            return x.astype(dst_type) if jnp.issubdtype(x.dtype, jnp.floating) else x

        for name in self._defaults:
            value = getattr(self, name)
            if isinstance(value, list):
                setattr(self, name, [_cast(v) for v in value])
            elif isinstance(value, CapacityBuffer):
                if value.data is not None and jnp.issubdtype(value.data.dtype, jnp.floating):
                    value.data = value.data.astype(dst_type)
                    value.dtype = jnp.dtype(dst_type)  # future appends cast too
            elif isinstance(value, Sketch):
                pass  # summary counts keep their exact-integer f32 dtype
            else:
                setattr(self, name, _cast(value))
            default = self._defaults[name]
            if not isinstance(default, (list, CapacityBuffer, Sketch)):
                self._defaults[name] = _cast(default)
        return self

    @property
    def dtype(self):
        return self._dtype

    def type(self, dst_type: Any) -> "Metric":
        return self.set_dtype(dst_type)

    def float(self) -> "Metric":
        return self.set_dtype(jnp.float32)

    def double(self) -> "Metric":
        return self.set_dtype(jnp.float64)

    def half(self) -> "Metric":
        return self.set_dtype(jnp.bfloat16)

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Filter kwargs down to the update signature (reference ``metric.py:611``)."""
        sig = inspect.signature(self.update)
        params = sig.parameters
        if any(p.kind == p.VAR_KEYWORD for p in params.values()):
            return kwargs
        names = {
            n for n, p in params.items()
            if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY) and n != "self"
        }
        return {k: v for k, v in kwargs.items() if k in names}

    def _effective_update_count(self) -> int:
        return self._update_count

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    # ------------------------------------------------------------------
    # Operator algebra -> CompositionalMetric (reference metric.py:652-756)
    # ------------------------------------------------------------------

    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, other, self)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, self, other)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, other, self)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, self, other)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, other, self)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, self, other)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, other, self)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, other, self)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, self, other)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, other, self)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, other, self)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, self, other)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, other, self)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, other, self)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, self, other)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, other, self)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, self, other)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, other, self)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.equal, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.not_equal, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less_equal, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater_equal, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.negative, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __invert__(self) -> "CompositionalMetric":
        # bitwise (not logical) negation, matching reference metric.py:742-746
        return CompositionalMetric(jnp.invert, self, None)

    def __getitem__(self, idx: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x: x[idx], self, None)


def _apply_reduction(reduce_fx: Union[str, Callable], outputs: List[Array]) -> Array:
    """Reduce a list of per-partial state values into one (shared by the
    forward merge and the cross-process sync)."""
    if reduce_fx == "sum":
        return jnp.stack(outputs).sum(axis=0)
    if reduce_fx == "mean":
        return jnp.stack(outputs).mean(axis=0)
    if reduce_fx == "max":
        return jnp.stack(outputs).max(axis=0)
    if reduce_fx == "min":
        return jnp.stack(outputs).min(axis=0)
    if reduce_fx == "cat":
        return jnp.concatenate([jnp.atleast_1d(o) for o in outputs], axis=0)
    if reduce_fx == "sketch":
        return functools.reduce(lambda a, b: a.merge(b), outputs)
    if isinstance(reduce_fx, str) and reduce_fx in _CUSTOM_REDUCTIONS:
        return _CUSTOM_REDUCTIONS[reduce_fx]["list_reduce"](outputs)
    if callable(reduce_fx):
        return reduce_fx(jnp.stack(outputs))
    raise MetricsTPUUserError(f"Unsupported dist_reduce_fx {reduce_fx}")


def _wrap_update(update: Callable) -> Callable:
    @functools.wraps(update)
    def wrapped_update(self: Metric, *args: Any, **kwargs: Any) -> None:
        if self._is_synced:
            raise MetricsTPUUserError(
                "The Metric has already been synced and the state can not be modified. Call `unsync()` first."
            )
        self._computed = None
        self._update_count += 1
        args = coerce_foreign_tensors(args)
        kwargs = coerce_foreign_tensors(kwargs)
        # annotate_always: disabled mode keeps emitting exactly the bare
        # TraceAnnotation this site always had; enabled adds named_scope +
        # the host span + counters
        with _obs_span(f"{type(self).__name__}.update", category="update", annotate_always=True):
            update(self, *args, **kwargs)
        if _obs_enabled():
            _obs_inc("metric.updates", metric=type(self).__name__)
        if self._dtype_forced:
            # jnp ops promote dtypes (no in-place torch semantics); pin
            # non-list float states back to the forced dtype.
            for name in self._defaults:
                value = getattr(self, name)
                if isinstance(value, (jnp.ndarray, jax.Array)) and jnp.issubdtype(value.dtype, jnp.floating):
                    setattr(self, name, value.astype(self._dtype))
        if self.compute_on_cpu:
            self._move_list_states_to_cpu()

    wrapped_update._lifecycle_wrapped = True
    return wrapped_update


def _wrap_compute(compute: Callable) -> Callable:
    @functools.wraps(compute)
    def wrapped_compute(self: Metric) -> Any:
        if self._effective_update_count() == 0:
            rank_zero_warn(
                f"The ``compute`` method of metric {self.__class__.__name__} was called before the ``update``"
                " method which may lead to errors, as metric states have yet to be updated.",
                UserWarning,
            )
        if self._computed is not None:
            return self._computed
        if _obs_enabled():
            name = type(self).__name__
            _obs_inc("metric.computes", metric=name)
            # accumulated-state footprint at its per-epoch peak, BEFORE the
            # sync context (local state). Recorded here rather than per
            # update: walking a list/cat state's B arrays on every one of B
            # updates would be O(B^2) over an epoch, and the pre-compute
            # value is the one capacity planning needs anyway.
            _obs_gauge(
                "metric.state_bytes",
                _obs_nbytes({n: getattr(self, n) for n in self._defaults}),
                metric=name,
            )
        with self.sync_context(
            dist_sync_fn=self.dist_sync_fn,
            should_sync=self._to_sync,
            should_unsync=self._should_unsync,
        ):
            with _obs_span(f"{type(self).__name__}.compute", category="compute", annotate_always=True):
                value = compute(self)
            self._computed = _squeeze_if_scalar(value)
        return self._computed

    wrapped_compute._lifecycle_wrapped = True
    return wrapped_compute


class CompositionalMetric(Metric):
    """Lazy DAG over metrics built by operator overloads (reference ``metric.py:762``)."""

    full_state_update = True

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, float, int, Array, None],
        metric_b: Union[Metric, float, int, Array, None],
    ) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = metric_a if isinstance(metric_a, Metric) or metric_a is None else jnp.asarray(metric_a)
        self.metric_b = metric_b if isinstance(metric_b, Metric) or metric_b is None else jnp.asarray(metric_b)

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None, process_group: Optional[Any] = None) -> None:
        pass  # children sync themselves

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def _effective_update_count(self) -> int:
        # Children carry the real update counts.
        counts = [self._update_count]
        for child in (self.metric_a, self.metric_b):
            if isinstance(child, Metric):
                counts.append(child._effective_update_count())
        return max(counts)

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            self._forward_cache = None
        elif val_b is None:
            self._forward_cache = None if isinstance(self.metric_b, Metric) else self.op(val_a)
        else:
            self._forward_cache = self.op(val_a, val_b)
        return self._forward_cache

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()
        self._update_count = 0
        self._computed = None
        self._forward_cache = None

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        op_name = getattr(self.op, "__name__", "op")
        return f"{self.__class__.__name__}(\n  {op_name}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))
