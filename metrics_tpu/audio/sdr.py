"""SDR / SI-SDR metric classes.

Behavioral equivalents of reference ``torchmetrics/audio/sdr.py:25,143``.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio, signal_distortion_ratio
from metrics_tpu.metric import Metric

Array = jax.Array


class SignalDistortionRatio(Metric):
    """Mean SDR over all evaluated signals (native JAX distortion-filter solve).

    Args:
        use_cg_iter: solve the filter with this many CG iterations (FFT
            matvecs) instead of a dense solve.
        filter_length: distortion filter taps.
        zero_mean: zero-mean the signals first.
        load_diag: diagonal loading for stability.

    Example:
        >>> import jax
        >>> from metrics_tpu import SignalDistortionRatio
        >>> k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.normal(k1, (8000,))
        >>> target = jax.random.normal(k2, (8000,))
        >>> sdr = SignalDistortionRatio()
        >>> sdr(preds, target)  # doctest: +SKIP
        Array(-12.1, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag
        self.add_state("sum_sdr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sdr_batch = signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )
        self.sum_sdr = self.sum_sdr + jnp.sum(sdr_batch)
        self.total = self.total + sdr_batch.size

    def compute(self) -> Array:
        return self.sum_sdr / jnp.asarray(self.total, dtype=self.sum_sdr.dtype)


class ScaleInvariantSignalDistortionRatio(Metric):
    """Mean SI-SDR over all evaluated signals.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ScaleInvariantSignalDistortionRatio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> si_sdr = ScaleInvariantSignalDistortionRatio()
        >>> si_sdr(preds, target)
        Array(18.403925, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_si_sdr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_sdr_batch = scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_si_sdr = self.sum_si_sdr + jnp.sum(si_sdr_batch)
        self.total = self.total + si_sdr_batch.size

    def compute(self) -> Array:
        return self.sum_si_sdr / jnp.asarray(self.total, dtype=self.sum_si_sdr.dtype)
