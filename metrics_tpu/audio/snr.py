"""SNR / SI-SNR metric classes.

Behavioral equivalents of reference ``torchmetrics/audio/snr.py:22,102``:
mean over all evaluated sample scores via sum/count states.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.snr import scale_invariant_signal_noise_ratio, signal_noise_ratio
from metrics_tpu.metric import Metric

Array = jax.Array


class SignalNoiseRatio(Metric):
    """Mean SNR over all evaluated signals.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SignalNoiseRatio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> snr = SignalNoiseRatio()
        >>> snr(preds, target)
        Array(16.180521, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_snr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        snr_batch = signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_snr = self.sum_snr + jnp.sum(snr_batch)
        self.total = self.total + snr_batch.size

    def compute(self) -> Array:
        return self.sum_snr / jnp.asarray(self.total, dtype=self.sum_snr.dtype)


class ScaleInvariantSignalNoiseRatio(Metric):
    """Mean SI-SNR over all evaluated signals.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ScaleInvariantSignalNoiseRatio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> si_snr = ScaleInvariantSignalNoiseRatio()
        >>> si_snr(preds, target)
        Array(15.091808, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_si_snr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_snr_batch = scale_invariant_signal_noise_ratio(preds=preds, target=target)
        self.sum_si_snr = self.sum_si_snr + jnp.sum(si_snr_batch)
        self.total = self.total + si_snr_batch.size

    def compute(self) -> Array:
        return self.sum_si_snr / jnp.asarray(self.total, dtype=self.sum_si_snr.dtype)
