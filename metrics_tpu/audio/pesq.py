"""PerceptualEvaluationSpeechQuality metric class.

Behavioral equivalent of reference ``torchmetrics/audio/pesq.py:25``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.imports import _PESQ_AVAILABLE

Array = jax.Array


class PerceptualEvaluationSpeechQuality(Metric):
    """Mean PESQ (ITU-T P.862, host-side C library) over evaluated signals.

    Args:
        fs: sampling frequency (8000 or 16000).
        mode: ``'wb'`` or ``'nb'``.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, fs: int, mode: str, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PESQ_AVAILABLE:
            raise ModuleNotFoundError(
                "PerceptualEvaluationSpeechQuality metric requires that `pesq` is installed. Either install as "
                "`pip install metrics-tpu[audio]` or `pip install pesq`."
            )
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        self.mode = mode

        self.add_state("sum_pesq", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        pesq_batch = perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode)
        self.sum_pesq = self.sum_pesq + jnp.sum(pesq_batch)
        self.total = self.total + pesq_batch.size

    def compute(self) -> Array:
        return self.sum_pesq / jnp.asarray(self.total, dtype=self.sum_pesq.dtype)
