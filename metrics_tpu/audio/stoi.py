"""ShortTimeObjectiveIntelligibility metric class.

Behavioral equivalent of reference ``torchmetrics/audio/stoi.py:25``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.stoi import short_time_objective_intelligibility
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.imports import _PYSTOI_AVAILABLE

Array = jax.Array


class ShortTimeObjectiveIntelligibility(Metric):
    """Mean STOI (host-side pystoi) over evaluated signals.

    Args:
        fs: sampling frequency.
        extended: use the extended STOI variant.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PYSTOI_AVAILABLE:
            raise ModuleNotFoundError(
                "STOI metric requires that `pystoi` is installed. Either install as "
                "`pip install metrics-tpu[audio]` or `pip install pystoi`."
            )
        self.fs = fs
        self.extended = extended

        self.add_state("sum_stoi", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        stoi_batch = short_time_objective_intelligibility(preds, target, self.fs, self.extended)
        self.sum_stoi = self.sum_stoi + jnp.sum(stoi_batch)
        self.total = self.total + stoi_batch.size

    def compute(self) -> Array:
        return self.sum_stoi / jnp.asarray(self.total, dtype=self.sum_stoi.dtype)
