"""ShortTimeObjectiveIntelligibility metric class.

Behavioral equivalent of reference ``torchmetrics/audio/stoi.py:25`` — but
self-contained: unlike the reference (which hard-requires ``pystoi``), the
metric runs on the in-repo native STOI/ESTOI implementation when the
package is absent.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.stoi import short_time_objective_intelligibility
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.imports import _PYSTOI_AVAILABLE

Array = jax.Array


class ShortTimeObjectiveIntelligibility(Metric):
    """Mean STOI (host-side) over evaluated signals.

    Args:
        fs: sampling frequency.
        extended: use the extended STOI variant.
        implementation: ``"auto"`` (pystoi when installed, else the native
            algorithm), ``"native"``, or ``"pystoi"``.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self, fs: int, extended: bool = False, implementation: str = "auto", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if implementation not in ("auto", "native", "pystoi"):
            raise ValueError(
                f"Expected argument `implementation` to be 'auto', 'native' or 'pystoi' but got {implementation}"
            )
        if implementation == "pystoi" and not _PYSTOI_AVAILABLE:
            raise ModuleNotFoundError(
                "implementation='pystoi' requires that `pystoi` is installed. Either install as "
                "`pip install metrics-tpu[audio]` or `pip install pystoi` — or use implementation='native'."
            )
        self.fs = fs
        self.extended = extended
        self.implementation = implementation

        self.add_state("sum_stoi", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        stoi_batch = short_time_objective_intelligibility(
            preds, target, self.fs, self.extended, implementation=self.implementation
        )
        self.sum_stoi = self.sum_stoi + jnp.sum(stoi_batch)
        self.total = self.total + stoi_batch.size

    def compute(self) -> Array:
        return self.sum_stoi / jnp.asarray(self.total, dtype=self.sum_stoi.dtype)
