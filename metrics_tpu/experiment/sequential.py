"""Always-valid sequential significance testing from monoid evidence.

The decision engine runs at the serving ROOT on each history cut —
evidence arrives continuously, and "peek every minute" destroys a
fixed-horizon test's type-I guarantee. The machinery here is a
mixture-SPRT in the always-valid-inference tradition (Johari et al.,
"Peeking at A/B tests"; Howard et al., confidence sequences): the
likelihood ratio of a Gaussian null against a ``N(theta0, tau^2)``
mixture of alternatives is a martingale under the null, so by Ville's
inequality ``p_n = min_{m <= n} 1 / LR_m`` is a valid p-value at EVERY
cut simultaneously, and the matching confidence sequence covers the true
effect uniformly over time. All math is host-side numpy (vectorized —
the 1k-run null calibration in ``tests/experiment`` uses the same code
paths the root decision does).

Evidence enters as :class:`ArmStats` — ``(n, mean, var, halfwidth)`` —
built either from exact samples (:func:`arm_stats_from_samples`) or from
a mergeable sketch's bin masses (:func:`arm_stats_from_sketch`). The
``halfwidth`` is the sketch's rigorous error envelope on the mean, and
:class:`SequentialTest` folds it INTO the decision boundary: the
observed effect is shrunk toward the null by the combined envelope
before the likelihood ratio is formed (and the confidence sequence is
widened by it), so a sketch can never fabricate significance the exact
samples would not support — only delay it (pinned by
``tests/experiment/test_sequential.py``).
"""
import math
from typing import Any, Dict, NamedTuple, Optional, Union

import numpy as np

__all__ = [
    "ArmStats",
    "SequentialTest",
    "arm_stats_from_samples",
    "arm_stats_from_sketch",
    "mixture_lr",
]


class ArmStats(NamedTuple):
    """Sufficient evidence for one experiment arm.

    ``n`` observations with sample mean ``mean`` and variance ``var``;
    ``halfwidth`` is a rigorous bound on ``|mean - exact mean|`` (zero
    for exact-sum evidence, the envelope half-width for sketch-derived
    evidence — see :func:`arm_stats_from_sketch`).
    """

    n: float
    mean: float
    var: float
    halfwidth: float


def arm_stats_from_samples(samples: Any) -> ArmStats:
    """Exact evidence: mean/variance of raw samples, zero halfwidth."""
    arr = np.ravel(np.asarray(samples, dtype=np.float64))
    if arr.size == 0:
        return ArmStats(0.0, 0.0, 0.0, 0.0)
    return ArmStats(float(arr.size), float(arr.mean()), float(arr.var()), 0.0)


def arm_stats_from_sketch(sketch: Any, family: str = "mean") -> ArmStats:
    """Evidence from a mergeable sketch's bin masses.

    ``family="rate"`` reads a
    :class:`~metrics_tpu.streaming.sketches.ScoreLabelSketch`: the
    positive-label rate is a ratio of EXACT integer-valued count sums,
    so the halfwidth is zero and the variance is the exact Bernoulli
    ``p * (1 - p)``.

    ``family="mean"`` reads a
    :class:`~metrics_tpu.streaming.sketches.QuantileSketch`: the mean is
    estimated at the mass-weighted bin midpoints; the halfwidth is the
    mass-weighted half bin width (every sample provably lies inside its
    bin's [clipped] edges, so ``|est - exact| <= sum_b m_b * (hi_b -
    lo_b) / 2``); the variance is the CONSERVATIVE upper bound placing
    each bin's mass at its edge farthest from the mean — a larger
    variance can only weaken evidence at the decision boundary, which is
    the safe direction for the never-fabricate-significance contract.
    """
    from metrics_tpu.streaming.sketches import QuantileSketch, ScoreLabelSketch

    if family not in ("mean", "rate"):
        raise ValueError(f"family must be 'mean' or 'rate', got {family!r}")
    if family == "rate":
        if not isinstance(sketch, ScoreLabelSketch):
            raise ValueError(
                f"family='rate' needs a ScoreLabelSketch, got {type(sketch).__name__}"
            )
        pos = float(np.asarray(sketch.pos).sum())
        neg = float(np.asarray(sketch.neg).sum())
        n = pos + neg
        if n <= 0:
            return ArmStats(0.0, 0.0, 0.0, 0.0)
        p = pos / n
        return ArmStats(n, p, p * (1.0 - p), 0.0)
    if not isinstance(sketch, QuantileSketch):
        raise ValueError(f"family='mean' needs a QuantileSketch, got {type(sketch).__name__}")
    counts = np.asarray(sketch.counts, dtype=np.float64)
    n = float(counts.sum())
    if n <= 0:
        return ArmStats(0.0, 0.0, 0.0, 0.0)
    lower, upper = (np.asarray(e, dtype=np.float64) for e in sketch._bin_edges())
    masses = counts / n
    mid = (lower + upper) / 2.0
    mean = float((masses * mid).sum())
    halfwidth = float((masses * (upper - lower)).sum() / 2.0)
    far = np.maximum(np.abs(upper - mean), np.abs(lower - mean))
    var_ub = float((masses * far**2).sum())
    return ArmStats(n, mean, var_ub, halfwidth)


def mixture_lr(
    diff: Union[float, np.ndarray], v: Union[float, np.ndarray], tau: float
) -> np.ndarray:
    """mSPRT mixture likelihood ratio for an observed effect ``diff``
    with sampling variance ``v`` against the point null, mixing the
    alternative over ``N(0, tau^2)``:

        ``LR = sqrt(v / (v + tau^2)) * exp(diff^2 * tau^2 /
        (2 * v * (v + tau^2)))``

    Vectorized (the null calibration evaluates 1k runs x T cuts in one
    call); ``v <= 0`` (no evidence yet) yields LR = 1.
    """
    v = np.asarray(v, dtype=np.float64)
    diff = np.asarray(diff, dtype=np.float64)
    tau2 = float(tau) ** 2
    safe_v = np.where(v > 0, v, 1.0)
    with np.errstate(over="ignore"):
        # overflow to inf is the correct saturation: overwhelming evidence
        # drives LR -> inf and the always-valid p-value 1/max(LR) -> 0
        lr = np.sqrt(safe_v / (safe_v + tau2)) * np.exp(
            diff**2 * tau2 / (2.0 * safe_v * (safe_v + tau2))
        )
    return np.where(v > 0, lr, 1.0)


class SequentialTest:
    """mSPRT always-valid p-value + confidence sequence for a two-arm
    comparison, with the sketch error envelope folded into the boundary.

    Args:
        alpha: decision level — ship/stop when the always-valid p-value
            reaches ``alpha`` (type-I error over the WHOLE monitoring
            trajectory is at most ``alpha``, any stopping rule).
        tau: mixture scale of the alternative ``N(theta0, tau^2)`` —
            roughly the effect size the test is most sensitive to.
        theta0: the null effect (treatment mean minus control mean).
        min_samples: both arms must hold at least this many observations
            before a verdict may fire (the LR is computed regardless;
            this guards the normal approximation, not the validity).
        family: evidence family forwarded to
            :func:`arm_stats_from_sketch` by callers that extract from
            sketches (recorded here for the engine's report).

    :meth:`step` is a PURE function of ``(control, treatment, prev_p)``
    — the decision engine persists ``prev_p`` (the running minimum that
    makes the p-value always-valid) in its durable state, which is what
    makes decisions bitwise-reproducible from checkpoints.
    """

    def __init__(
        self,
        alpha: float = 0.05,
        tau: float = 0.1,
        theta0: float = 0.0,
        min_samples: int = 100,
        family: str = "mean",
    ) -> None:
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        if tau <= 0:
            raise ValueError(f"tau must be > 0, got {tau}")
        if family not in ("mean", "rate"):
            raise ValueError(f"family must be 'mean' or 'rate', got {family!r}")
        self.alpha = float(alpha)
        self.tau = float(tau)
        self.theta0 = float(theta0)
        self.min_samples = int(min_samples)
        self.family = family

    def config(self) -> Dict[str, Any]:
        return {
            "alpha": self.alpha,
            "tau": self.tau,
            "theta0": self.theta0,
            "min_samples": self.min_samples,
            "family": self.family,
        }

    def confidence_halfwidth(self, v: float) -> float:
        """Half-width of the always-valid confidence sequence at
        sampling variance ``v`` (Howard-style mixture bound):

            ``sqrt((v * (v + tau^2) / tau^2) * ln((v + tau^2) /
            (alpha^2 * v)))``

        The sequence ``diff ± halfwidth`` covers the true effect at
        every cut simultaneously with probability ``>= 1 - alpha``.
        """
        if v <= 0:
            return float("inf")
        tau2 = self.tau**2
        return math.sqrt((v * (v + tau2) / tau2) * math.log((v + tau2) / (self.alpha**2 * v)))

    def step(
        self, control: ArmStats, treatment: ArmStats, prev_p: float = 1.0
    ) -> Dict[str, Any]:
        """One evaluation: fold fresh arm evidence into the running
        always-valid p-value and emit a verdict.

        Returns a JSON-safe dict: ``verdict`` (``"ship"`` — treatment
        significantly above ``theta0``; ``"stop"`` — significantly
        below; ``"continue"``), the always-valid ``p_value`` (running
        min including ``prev_p``), the observed ``diff`` and its
        ``envelope`` (combined sketch halfwidths), the
        envelope-shrunk ``effective_diff`` the boundary actually saw,
        and the confidence sequence ``ci`` (envelope-widened).
        """
        n_c, n_t = float(control.n), float(treatment.n)
        diff = float(treatment.mean) - float(control.mean)
        envelope = float(control.halfwidth) + float(treatment.halfwidth)
        v = 0.0
        if n_c > 0 and n_t > 0:
            v = float(control.var) / n_c + float(treatment.var) / n_t
        # fold the envelope INTO the boundary: shrink the observed effect
        # toward the null by the combined halfwidth — any true effect the
        # sketch evidence is consistent with is at least this large, so
        # firing on the shrunk effect can never outrun exact evidence
        centered = diff - self.theta0
        effective = math.copysign(max(abs(centered) - envelope, 0.0), centered)
        lr = float(mixture_lr(effective, v, self.tau))
        p_value = min(float(prev_p), 1.0 / lr if lr > 0 else 1.0, 1.0)
        cs_halfwidth = self.confidence_halfwidth(v)
        ci = [diff - cs_halfwidth - envelope, diff + cs_halfwidth + envelope]
        verdict = "continue"
        if (
            min(n_c, n_t) >= self.min_samples
            and p_value <= self.alpha
            and effective != 0.0
        ):
            verdict = "ship" if effective > 0 else "stop"
        return {
            "verdict": verdict,
            "p_value": p_value,
            "lr": lr,
            "diff": diff,
            "effective_diff": effective,
            "envelope": envelope,
            "variance": v,
            "ci": ci,
            "n": [n_c, n_t],
            "alpha": self.alpha,
        }
