"""Online experimentation at the serving root: arms, tenants, decisions.

An :class:`Experiment` maps each arm of an A/B test onto its own
aggregator TENANT — arms inherit the platform's entire serving contract
for free (wire schema + dedup, elastic tree, chaos tolerance, history
rings, checkpoints, generation fencing) because they ARE ordinary
tenants. The :class:`DecisionEngine` then rides the history tier's cut
hook: on every interval cut it extracts per-arm evidence from the
just-retained cumulative snapshots (via the same capture-and-restore
state probing the alert rules use), folds it through the experiment's
:class:`~metrics_tpu.experiment.SequentialTest`, and fires SHIP / STOP
verdicts edge-triggered through the one-shot-warn + obs counter
machinery (``experiment.decisions{exp=,verdict=}``).

Durability and failover ride the existing seams: the engine's decision
state (always-valid p-value, verdict, evidence) serializes into the
aggregator's checkpoint manifest beside the history rings — a SIGKILLed
root resumes with bitwise-identical decisions — and evaluation is
GENERATION-FENCED: a cut whose arm snapshots straddle a failover
boundary is skipped (counted under ``experiment.fenced_evaluations``)
rather than compared across two histories, exactly the history tier's
delta-fencing stance.
"""
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence

from metrics_tpu.experiment.sequential import ArmStats, SequentialTest, arm_stats_from_sketch
from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import set_gauge as _obs_gauge
from metrics_tpu.serve.aggregator import ServeError

__all__ = ["ArmSpec", "DecisionEngine", "Experiment"]


class ArmSpec:
    """One experiment arm: a name and the metric-collection factory its
    tenant registers with (every arm of an experiment must use the SAME
    schema — the sequential test compares like evidence)."""

    def __init__(self, name: str, factory: Callable[[], Any]) -> None:
        if not str(name):
            raise ValueError("arm name must be non-empty")
        if not callable(factory):
            raise ValueError(f"arm {name!r}: factory must be a zero-arg callable")
        self.name = str(name)
        self.factory = factory


class Experiment:
    """A two-arm online experiment over per-arm aggregator tenants.

    Args:
        exp_id: experiment identity (tenant ids are
            ``"<exp_id>/<arm name>"``; the ``exp=`` obs label).
        arms: exactly two :class:`ArmSpec` — ``arms[0]`` is the CONTROL,
            ``arms[1]`` the treatment.
        metric: member name inside each arm's collection supplying the
            evidence. The member must expose a mergeable sketch state:
            a :class:`~metrics_tpu.streaming.sketches.QuantileSketch`
            (``family="mean"``) or
            :class:`~metrics_tpu.streaming.sketches.ScoreLabelSketch`
            (``family="rate"``) — :class:`StreamingRAGQuality`'s NDCG
            sketch, :class:`StreamingQuantile`, :class:`StreamingAUROC`
            all qualify.
        test: the :class:`~metrics_tpu.experiment.SequentialTest`
            (defaults to one at ``alpha=0.05``; its ``family`` selects
            the evidence extraction).
        higher_is_better: direction of goodness for the watched value
            (``None``: read the member metric's own ``higher_is_better``
            at evaluation time, defaulting True). A ``False`` direction
            negates the effect, so "ship" always means "treatment is
            significantly BETTER".
    """

    def __init__(
        self,
        exp_id: str,
        arms: Sequence[ArmSpec],
        metric: str,
        test: Optional[SequentialTest] = None,
        higher_is_better: Optional[bool] = None,
    ) -> None:
        if not str(exp_id):
            raise ValueError("exp_id must be non-empty")
        arms = list(arms)
        if len(arms) != 2:
            raise ValueError(f"experiment {exp_id!r} needs exactly 2 arms, got {len(arms)}")
        if arms[0].name == arms[1].name:
            raise ValueError(f"experiment {exp_id!r}: arm names must differ")
        self.exp_id = str(exp_id)
        self.arms = arms
        self.metric = str(metric)
        self.test = test if test is not None else SequentialTest()
        self.higher_is_better = higher_is_better

    @property
    def control(self) -> ArmSpec:
        return self.arms[0]

    @property
    def treatment(self) -> ArmSpec:
        return self.arms[1]

    def tenant_id(self, arm: ArmSpec) -> str:
        return f"{self.exp_id}/{arm.name}"

    def tenant_ids(self) -> List[str]:
        return [self.tenant_id(arm) for arm in self.arms]

    def register(self, aggregator: Any) -> List[str]:
        """Register one tenant per arm on ``aggregator``; returns the
        tenant ids. Idempotent-unfriendly by design — the aggregator
        refuses duplicate registration loudly, like any tenant."""
        for arm in self.arms:
            aggregator.register_tenant(self.tenant_id(arm), arm.factory)
        return self.tenant_ids()


def _fresh_record(exp: Experiment) -> Dict[str, Any]:
    return {
        "experiment": exp.exp_id,
        "verdict": "continue",
        "p_value": 1.0,
        "evaluations": 0,
        "fenced": 0,
        "evidence": None,
        "decision": None,
        "generation": None,
    }


class DecisionEngine:
    """Root-side experiment evaluator riding the history cut hook.

    Construct AFTER the aggregator (which must be armed with
    ``history=``) and after each experiment's :meth:`Experiment.register`;
    re-attach (same experiments) before :meth:`Aggregator.restore` so the
    saved decision state has somewhere to land. Evaluation order is
    deterministic (sorted experiment id), decisions are STICKY (a fired
    ship/stop is never re-litigated — re-run the experiment under a new
    id instead), and the whole evaluation is a pure function of durable
    state: retained history snapshots + the persisted always-valid
    p-value. That purity is what the kill-resume bitwise pin in
    ``tests/integrations/experiment_smoke.py`` checks.
    """

    def __init__(self, aggregator: Any, experiments: Sequence[Experiment] = ()) -> None:
        if aggregator.history is None:
            raise ServeError(
                f"aggregator {aggregator.name!r} has no history armed; the decision"
                " engine evaluates on interval cuts — construct the aggregator with"
                " history=HistoryConfig(...)"
            )
        self._aggregator = aggregator
        self._history = aggregator.history
        self._experiments: Dict[str, Experiment] = {}
        self._state: Dict[str, Dict[str, Any]] = {}
        self._warned: set = set()
        for exp in experiments:
            self.add(exp)
        self._history.add_cut_hook(self._on_cut)
        # the aggregator exposes the engine (endpoints, checkpoint seam)
        aggregator._experiment_engine = self

    # -- registry --------------------------------------------------------

    def add(self, experiment: Experiment) -> None:
        if experiment.exp_id in self._experiments:
            raise ServeError(f"experiment {experiment.exp_id!r} is already attached")
        self._experiments[experiment.exp_id] = experiment
        self._state[experiment.exp_id] = _fresh_record(experiment)
        if _obs_enabled():
            _obs_gauge("experiment.active", 1.0, exp=experiment.exp_id)

    def experiment_ids(self) -> List[str]:
        return sorted(self._experiments)

    # -- evaluation ------------------------------------------------------

    def _on_cut(self, history: Any, aggregator: Any) -> None:
        for exp_id in self.experiment_ids():
            try:
                self.evaluate(exp_id)
            except Exception as err:  # noqa: BLE001 — a decision bug must not kill cuts
                if exp_id not in self._warned:
                    self._warned.add(exp_id)
                    warnings.warn(
                        f"experiment {exp_id!r} evaluation failed:"
                        f" {type(err).__name__}: {err}",
                        stacklevel=2,
                    )

    def _arm_snapshot(self, tenant_id: str) -> Optional[Any]:
        th = self._history._tenants.get(tenant_id)
        return None if th is None else th.newest()

    def _extract_stats(self, exp: Experiment, tenant_id: str, snap: Any) -> Optional[ArmStats]:
        tenant = self._aggregator._tenant(tenant_id)

        def probe(view: Any) -> Optional[ArmStats]:
            member = dict(view.items()).get(exp.metric)
            if member is None:
                raise ServeError(
                    f"experiment {exp.exp_id!r}: metric {exp.metric!r} is not a"
                    f" member of tenant {tenant_id!r}'s collection"
                )
            sketch = self._evidence_sketch(member)
            if sketch is None:
                raise ServeError(
                    f"experiment {exp.exp_id!r}: metric {exp.metric!r} exposes no"
                    " QuantileSketch/ScoreLabelSketch state — sequential evidence"
                    " needs a mergeable sketch (or rate) family"
                )
            stats = arm_stats_from_sketch(sketch, exp.test.family)
            flip = exp.higher_is_better
            if flip is None:
                flip = getattr(member, "higher_is_better", True)
                flip = True if flip is None else bool(flip)
            if not flip:
                stats = ArmStats(stats.n, -stats.mean, stats.var, stats.halfwidth)
            return stats

        return self._history._with_loaded(tenant, snap.leaves, snap.consensus, probe)

    @staticmethod
    def _evidence_sketch(member: Any) -> Optional[Any]:
        from metrics_tpu.streaming.sketches import QuantileSketch, ScoreLabelSketch

        for attr in ("sketch", "ndcg_sketch"):
            candidate = getattr(member, attr, None)
            if isinstance(candidate, (QuantileSketch, ScoreLabelSketch)):
                return candidate
        return None

    def evaluate(self, exp_id: str) -> Dict[str, Any]:
        """Evaluate one experiment against the newest retained arm
        snapshots; returns (a copy of) the durable record. Pure in the
        durable state: same snapshots + same persisted p-value produce
        the same record, which is the checkpoint-reproducibility pin."""
        exp = self._experiments[exp_id]
        rec = self._state[exp_id]
        if rec["verdict"] != "continue":
            return dict(rec)  # sticky: decided experiments are frozen
        snap_c = self._arm_snapshot(exp.tenant_id(exp.control))
        snap_t = self._arm_snapshot(exp.tenant_id(exp.treatment))
        if snap_c is None or snap_t is None:
            return dict(rec)  # nothing retained yet for one arm
        if snap_c.generation != snap_t.generation or snap_c.generation != self._history.generation:
            # the arms' snapshots straddle a failover boundary: comparing
            # them would difference two histories — skip, loudly counted
            rec["fenced"] += 1
            if _obs_enabled():
                _obs_inc("experiment.fenced_evaluations", exp=exp_id)
            return dict(rec)
        stats_c = self._extract_stats(exp, exp.tenant_id(exp.control), snap_c)
        stats_t = self._extract_stats(exp, exp.tenant_id(exp.treatment), snap_t)
        result = exp.test.step(stats_c, stats_t, prev_p=rec["p_value"])
        rec["evaluations"] += 1
        rec["p_value"] = result["p_value"]
        rec["generation"] = snap_c.generation
        rec["evidence"] = dict(
            result,
            control={"tenant": exp.tenant_id(exp.control), "snapshot": snap_c.meta()},
            treatment={"tenant": exp.tenant_id(exp.treatment), "snapshot": snap_t.meta()},
        )
        if _obs_enabled():
            _obs_inc("experiment.evaluations", exp=exp_id)
        if result["verdict"] != "continue":
            rec["verdict"] = result["verdict"]
            rec["decision"] = {
                "verdict": result["verdict"],
                "p_value": result["p_value"],
                "diff": result["diff"],
                "ci": list(result["ci"]),
                "generation": snap_c.generation,
                "cut": {"control": snap_c.index, "treatment": snap_t.index},
                "evaluations": rec["evaluations"],
            }
            if _obs_enabled():
                _obs_inc("experiment.decisions", exp=exp_id, verdict=result["verdict"])
                _obs_gauge("experiment.active", 0.0, exp=exp_id)
            key = ("decision", exp_id)
            if key not in self._warned:
                self._warned.add(key)
                from metrics_tpu.utilities.prints import rank_zero_warn

                rank_zero_warn(
                    f"experiment {exp_id!r} DECIDED: {result['verdict'].upper()}"
                    f" (always-valid p={result['p_value']:.6f} <="
                    f" alpha={exp.test.alpha:g}, diff={result['diff']:+.6g},"
                    f" ci=[{result['ci'][0]:.6g}, {result['ci'][1]:.6g}])"
                    " — edge-triggered: counted once under"
                    " experiment.decisions and frozen until re-run under a"
                    " new experiment id"
                )
        return dict(rec)

    # -- reporting (GET /experiment/<id>) --------------------------------

    def report(self, exp_id: str) -> Dict[str, Any]:
        """The JSON answer for ``GET /experiment/<id>``."""
        exp = self._experiments.get(exp_id)
        if exp is None:
            raise KeyError(exp_id)
        if _obs_enabled():
            _obs_inc("experiment.queries", exp=exp_id)
        rec = self._state[exp_id]
        return {
            "experiment": exp.exp_id,
            "metric": exp.metric,
            "arms": {
                "control": exp.tenant_id(exp.control),
                "treatment": exp.tenant_id(exp.treatment),
            },
            "test": exp.test.config(),
            **{k: rec[k] for k in (
                "verdict", "p_value", "evaluations", "fenced", "evidence", "decision",
                "generation",
            )},
        }

    # -- durability (rides Aggregator.save/restore) ----------------------

    def state_for_checkpoint(self) -> Dict[str, Any]:
        """JSON-safe decision state for the checkpoint manifest (tiny:
        one record per experiment — no array tree needed)."""
        return {exp_id: dict(self._state[exp_id]) for exp_id in self.experiment_ids()}

    def load_checkpoint_state(self, meta: Dict[str, Any]) -> None:
        """Adopt the saved decision records wholesale (bitwise: the
        records are plain JSON and replace the fresh ones). Experiments
        the checkpoint does not name keep their fresh record; saved
        records for unattached experiments are ignored (the aggregator's
        re-register-before-restore stance)."""
        for exp_id, saved in (meta or {}).items():
            if exp_id not in self._experiments:
                continue
            self._state[exp_id] = dict(saved)
            if _obs_enabled():
                active = 1.0 if saved.get("verdict") == "continue" else 0.0
                _obs_gauge("experiment.active", active, exp=exp_id)
            if saved.get("verdict") != "continue":
                # the decision already warned on the node that made it;
                # a restored root must not re-announce (or re-count) it
                self._warned.add(("decision", exp_id))
