"""Online experimentation: per-arm tenants + always-valid sequential decisions.

The second new serving workload of ROADMAP open item 2: live A/B
experimentation over the metrics the platform already aggregates. The
design splits cleanly along the platform's existing seams:

* :class:`Experiment` / :class:`ArmSpec` — each arm is an ordinary
  aggregator TENANT (``"<exp_id>/<arm>"``), so arm evidence inherits the
  wire schema, dedup, elastic-tree aggregation, chaos tolerance, history
  retention, checkpoints and generation fencing without one new code
  path on the hot ingest/fold loop.
* :class:`SequentialTest` — an mSPRT-style always-valid p-value and
  confidence sequence (Johari et al.; Howard et al.), computed from
  sketch bin masses with the sketch's rigorous error envelope FOLDED
  INTO the decision boundary: a sketch can never fabricate significance
  that exact samples would not support, only delay it.
* :class:`DecisionEngine` — evaluated at the root on every history cut,
  edge-triggered ship/stop/continue through the one-shot-warn + obs
  counter machinery, durable in ft checkpoints, generation-fenced across
  failover, and served on ``GET /experiment/<id>``.

See ``docs/serving.md`` (experimentation section) for the worked flow.
"""
from metrics_tpu.experiment.experiment import ArmSpec, DecisionEngine, Experiment
from metrics_tpu.experiment.sequential import (
    ArmStats,
    SequentialTest,
    arm_stats_from_samples,
    arm_stats_from_sketch,
    mixture_lr,
)

__all__ = [
    "ArmSpec",
    "ArmStats",
    "DecisionEngine",
    "Experiment",
    "SequentialTest",
    "arm_stats_from_samples",
    "arm_stats_from_sketch",
    "mixture_lr",
]
