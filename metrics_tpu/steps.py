"""First-class pure-functional metric steps for jit / scan / shard_map.

SURVEY §7's design stance is ``state = init(); state = update(state, batch)
[jit, donated]; value = compute(state)``. The :class:`~metrics_tpu.metric.Metric`
class realizes that contract statefully (``state_pytree`` /
``load_state_pytree``); this module exposes it as pure functions so a metric
drops directly into ``jax.jit``, ``jax.lax.scan`` epochs, and
``jax.shard_map`` mesh programs:

    init, step, compute = make_step(Accuracy, num_classes=5)
    state = init()
    state, batch_value = jax.jit(step, donate_argnums=0)(state, preds, target)
    state, values = jax.lax.scan(lambda s, b: step(s, *b), state, batches)
    value = compute(state)

Under ``shard_map``, pass ``axis_name=`` and ``compute`` lowers each state's
declared ``dist_reduce_fx`` through
:func:`~metrics_tpu.utilities.distributed.sync_reduce_in_context`
(psum/pmin/pmax/replicated-gather over ICI) before the final math — the
mesh-collective analogue of the reference's gather-then-reduce sync
(``torchmetrics/metric.py:279-304``), with the ``process_group`` kwarg
(reference ``metric.py:137``) becoming the axis-name set.

Replacing the reference's double-update ``forward`` (``metric.py:248-264``):
``step`` returns ``(state', batch_value)`` from ONE traced program — XLA
shares the per-batch statistics between the accumulation and the
batch-local value, so nothing is computed twice.

Static-shape contract: every state must be an array or a fixed-capacity
buffer. Metrics whose states are unbounded Python lists (exact curve
metrics without ``sample_capacity``) are rejected with guidance, since a
growing pytree cannot be a ``scan`` carry.

Whole-collection fusion: :func:`make_collection_epoch` /
:func:`make_collection_step` lower an entire ``MetricCollection`` into one
traced program — members with provably identical update computations share
ONE update (the compute-group dedup extended from state to the update pass
itself), the input normalization/format-check pass runs once per
parameterization, and a fused ``compute`` evaluates every member's value
in a single further launch.
"""
from copy import deepcopy
from typing import Any, Callable, Dict, Optional, Tuple, Type, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.obs.profile import time_launch as _obs_time_launch
from metrics_tpu.obs.recompile import note_epoch_launch as _obs_epoch_launch
from metrics_tpu.obs.recompile import note_trace as _obs_note_trace
from metrics_tpu.obs.recompile import track_compiles as _obs_track_compiles
from metrics_tpu.obs.tracing import trace_span as _obs_span
from metrics_tpu.streaming.sketches import Sketch
from metrics_tpu.utilities.buffers import CapacityBuffer
from metrics_tpu.utilities.distributed import (
    hierarchical_reduce_in_context,
    replicate_typed,
    sync_buffer_in_context,
    sync_reduce_in_context,
    sync_sketch_in_context,
)

Array = jax.Array
State = Dict[str, Any]

# A state is merge-combinable when its batch contribution (accumulated from
# the default) folds into the carry with its own declared reduction — the
# exact property the DDP gather-reduce sync relies on (per-rank states
# accumulated from zero, merged by dist_reduce_fx). sum/max/min and sketch
# summaries (merge is their defining monoid) qualify; cat buffers, None and
# custom reductions don't.
_MERGE_OPS: Dict[str, Callable] = {
    "sum": lambda a, b: a + b,
    "max": jnp.maximum,
    "min": jnp.minimum,
    "sketch": lambda a, b: a.merge(b),
}


def _is_mergeable(metric: Metric) -> bool:
    return all(
        r in _MERGE_OPS and not isinstance(d, CapacityBuffer)
        for r, d in zip(metric._reductions.values(), metric._defaults.values())
    )

__all__ = [
    "make_collection_epoch",
    "make_collection_step",
    "make_epoch",
    "make_step",
    "make_stream_step",
    "overlap_epoch_sync",
    "prefetch_to_device",
]


def _metric_fingerprint(metric: Any) -> str:
    """Stable data-schema fingerprint for program cache keys (the serve
    tier's schema fingerprint; falls back to the type name for anything
    the wire schema walker cannot describe)."""
    try:
        from metrics_tpu.serve.wire import schema_fingerprint

        return schema_fingerprint(metric)
    except Exception:  # noqa: BLE001 — a key fallback, never a crash
        return f"type:{type(metric).__name__}"


def _engine_dispatch(raw_jitted: Callable, label: str, fingerprint: str, engine_obj: Any) -> Callable:
    """Route calls of a jitted program through an ExecutionEngine.

    Per distinct input signature the engine resolves ONE executable
    (memory -> persistent store -> AOT compile for
    :class:`~metrics_tpu.engine.AotEngine`) and later calls reuse it. The
    returned callable also exposes ``precompile(*args, **kwargs)`` — args
    may be ``ShapeDtypeStruct``s — so a warmup path can resolve programs
    before the first real batch arrives.
    """
    from metrics_tpu.engine.keys import ProgramKey, abstractify

    prepared: Dict[Any, Callable] = {}

    def _sig_of(args: tuple, kwargs: dict) -> Any:
        # cheap per-call lookup key (PyTreeDefs are hashable); the full
        # ProgramKey — json canonicalization, environment fields — is only
        # built on a miss, so the steady-state dispatch stays a flatten +
        # dict hit rather than a per-call key serialization
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        return treedef, tuple(
            (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") and hasattr(a, "dtype")
            else ("py", repr(a))
            for a in leaves
        )

    def resolve(*args: Any, **kwargs: Any) -> Callable:
        sig = _sig_of(args, kwargs)
        fn = prepared.get(sig)
        if fn is None:
            # a cached executable (engine memory or the persistent store)
            # skips tracing entirely, so trace-time side effects never run
            # against THIS factory's worker — in particular update-derived
            # aux attrs (the detected classification input mode) that
            # compute() relies on. One abstract eval_shape re-runs the
            # Python body on ShapeDtypeStructs before resolution: worker
            # state matches a traced process on every cache tier, still
            # zero backend compiles (a fresh compile pays one redundant
            # abstract trace, ms against its compile).
            from metrics_tpu.obs.recompile import suppress_note_trace

            aval_args, aval_kwargs = abstractify(args, kwargs)
            with suppress_note_trace():
                jax.eval_shape(raw_jitted, *aval_args, **aval_kwargs)
            key = ProgramKey.build(label, fingerprint, args, kwargs)
            fn = engine_obj.prepare(raw_jitted, key, *args, **kwargs)
            prepared[sig] = fn
        return fn

    def run(*args: Any, **kwargs: Any) -> Any:
        return resolve(*args, **kwargs)(*args, **kwargs)

    run.precompile = resolve
    return run


def _fresh_copy(state: State) -> State:
    """Copy leaves on the eager path so a donated init() can never delete
    arrays later traces embed as constants; a no-op under a trace (jnp.array
    on a concrete value would needlessly turn it into a tracer, and donation
    cannot reach trace-internal values).

    The copy pins each leaf's dtype explicitly, which also strips jax's
    weak-type flag: a weak-typed scalar default (``jnp.asarray(0)``) would
    otherwise make the SECOND jitted-epoch call retrace, because the first
    call's output carry comes back strong-typed."""
    if not isinstance(jnp.zeros(()), jax.core.Tracer):  # not under a trace
        return jax.tree_util.tree_map(
            lambda v: jnp.array(v, dtype=v.dtype) if hasattr(v, "dtype") else jnp.array(v), state
        )
    return state


def _is_array(a: Any) -> bool:
    return isinstance(a, (jnp.ndarray, jax.Array)) or hasattr(a, "__jax_array__")


def _split_update_leaves(args: tuple, kwargs: dict, dim: int):
    """Flatten (args, kwargs) into vmap leaves with per-leaf output axes."""
    keys = sorted(kwargs)
    leaves = list(args) + [kwargs[k] for k in keys]
    axes = tuple(dim if _is_array(a) else None for a in leaves)
    return keys, len(args), leaves, axes


def _stack_state(one: State, n: int) -> State:
    """Broadcast every leaf of a fresh state to a leading replicate axis."""
    return {name: jnp.broadcast_to(v[None], (n,) + jnp.shape(v)) for name, v in one.items()}


def make_step(
    metric: Union[Metric, Type[Metric], "MetricCollection"],  # noqa: F821
    *init_args: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]] = None,
    with_value: bool = True,
    sharded_state: bool = False,
    hierarchical_sync: bool = False,
    **init_kwargs: Any,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """Build pure ``(init, step, compute)`` functions from a metric.

    Args:
        metric: a :class:`Metric` subclass (constructed with
            ``*init_args, **init_kwargs``) or an existing instance (cloned;
            its accumulated state is not carried over). A
            :class:`MetricCollection` instance also works: the state becomes
            ``{metric_name: child_state}``, one traced program updates every
            member, and ``init_args``/``init_kwargs`` are not accepted
            (configure the collection before passing it).
        axis_name: mesh axis name(s) the state is sharded over. When given,
            ``compute`` reduces every state with its declared
            ``dist_reduce_fx`` via in-jit collectives before the final math —
            call it inside ``shard_map``/``pmap`` over that axis.
        with_value: when True (default), ``step`` also returns the
            batch-local metric value (the reference's ``forward`` result);
            when False, ``step`` returns ``(state', None)`` and skips that
            work.
        sharded_state: keep big states MESH-RESIDENT through ``compute``:
            instead of the replicated sync (psum all-reduce of sketch bins,
            materialized all-gather of sample buffers), the metric's
            registered gather-free kernel
            (:func:`metrics_tpu.utilities.sharding.register_sharded_compute`)
            reduce-scatters sketch bins / ring-passes buffer rows and
            finishes with scalar collectives — no device ever holds the
            full merged state. Built-ins cover ``StreamingAUROC`` /
            ``StreamingAveragePrecision`` / ``StreamingQuantile`` (sharded
            bins) and binary ``AUROC(sample_capacity=...)`` (resident
            rows). Metrics without a registered kernel whose states are
            all psum-family sync as usual (psum is already in-place);
            gather-state metrics without a kernel raise at build time.
        hierarchical_sync: with a MULTI-axis ``axis_name``, reduce each
            psum-family state one axis at a time in the given order
            (``axis_name[0]`` — pass the ICI/intra-slice axis — first, DCN
            second) instead of one flat collective, so the fast fabric
            combines first and the slow hop moves one already-reduced
            operand. Every per-axis collective is visible to the
            ``set_collective_seam`` hook and the ``sync.*`` counters in
            issue order. Gather-typed states keep the flat collective
            (concatenation order must not depend on the axis split).

    Returns:
        ``init() -> state``, ``step(state, *batch) -> (state', value)``,
        ``compute(state) -> value`` — all pure and trace-safe.

    Note:
        For a ``lax.scan`` INSIDE ``shard_map``, cast the initial carry to
        the sharded axis first — ``jax.lax.pcast(init(), ("dp",),
        to="varying")`` — since the scanned updates are device-varying while
        the fresh state is a replicated constant (``examples/sharded_eval.py``
        shows the pattern).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.steps import make_step
        >>> init, step, compute = make_step(Accuracy, num_classes=3)
        >>> state = init()
        >>> preds = jnp.asarray([[0, 1, 2, 2], [1, 1, 0, 2]])
        >>> target = jnp.asarray([[0, 1, 1, 2], [0, 1, 0, 2]])
        >>> state, values = jax.lax.scan(lambda s, b: step(s, *b), state, (preds, target))
        >>> values  # per-batch accuracies, one fused program per step
        Array([0.75, 0.75], dtype=float32)
        >>> compute(state)
        Array(0.75, dtype=float32)
    """
    from metrics_tpu.collections import MetricCollection

    if isinstance(metric, MetricCollection):
        if init_args or init_kwargs:
            raise TypeError("make_step(collection) takes no extra args; configure the collection itself")
        if sharded_state or hierarchical_sync:
            raise ValueError(
                "sharded_state/hierarchical_sync are per-metric knobs: build per-member steps"
                " (one make_step per sharded metric) instead of a fused collection step."
            )
        return _make_collection_step(metric, axis_name=axis_name, with_value=with_value)

    if isinstance(metric, Metric):
        template = metric.clone()
        template.reset()
    else:
        template = metric(*init_args, **init_kwargs)

    from metrics_tpu.wrappers.abstract import WrapperMetric
    from metrics_tpu.wrappers.bootstrapping import BootStrapper
    from metrics_tpu.wrappers.classwise import ClasswiseWrapper
    from metrics_tpu.wrappers.minmax import MinMaxMetric
    from metrics_tpu.wrappers.multioutput import MultioutputWrapper

    if (sharded_state or hierarchical_sync) and isinstance(template, WrapperMetric):
        raise ValueError(
            f"sharded_state/hierarchical_sync are not wired through {type(template).__name__}:"
            " build the step from the base metric and apply the wrapper semantics outside it."
        )

    if isinstance(template, BootStrapper):
        # the bootstrap replicate states are a fixed-shape stacked pytree —
        # exactly a scan carry; see _make_bootstrap_step
        return _make_bootstrap_step(template, axis_name=axis_name, with_value=with_value)
    if isinstance(template, ClasswiseWrapper):
        return _make_classwise_step(template, axis_name=axis_name, with_value=with_value)
    if isinstance(template, MinMaxMetric):
        return _make_minmax_step(template, axis_name=axis_name, with_value=with_value)
    if isinstance(template, MultioutputWrapper):
        return _make_multioutput_step(template, axis_name=axis_name, with_value=with_value)

    if isinstance(template, WrapperMetric):
        raise ValueError(
            f"{type(template).__name__} is a wrapper metric whose state is not a fixed-shape carry"
            " (snapshot lists / dynamic shapes). Build the step from the base metric and apply the"
            " wrapper semantics outside the step, or use the eager class API. (BootStrapper,"
            " ClasswiseWrapper, MinMaxMetric and MultioutputWrapper ARE supported.)"
        )

    for name, default in template._defaults.items():
        if isinstance(default, list):
            raise ValueError(
                f"State {name!r} of {type(template).__name__} is an unbounded list; a growing pytree cannot"
                " be a jitted-step carry. Construct the metric with `sample_capacity=` (fixed-capacity HBM"
                " buffer) or use the eager class API."
            )

    # one reusable worker (instead of a deepcopy per call): each use begins
    # with reset + load, so calls stay pure; only trace-time Python state is
    # shared, which is exactly what _capture_static wants propagated
    worker = deepcopy(template)

    def init() -> State:
        worker.reset()
        state = worker.state_pytree()
        # fresh buffers on the eager path (donation safety; see _fresh_copy —
        # the in-trace no-op also preserves CapacityBuffer's host-count mirror)
        return _fresh_copy(state)

    def _load(state: State) -> Metric:
        worker.reset()
        worker.load_state_pytree(state)
        worker._to_sync = False  # reductions, if any, happen in compute() below
        worker._computed = None
        return worker

    mergeable = _is_mergeable(template)
    obs_name = type(template).__name__
    # labels/tokens hoisted out of the per-call path: the step label keys the
    # aggregate counters; the token scopes the storm threshold to THIS factory
    _step_label, _compute_label = f"{obs_name}.step", f"{obs_name}.step_compute"
    _step_token, _compute_token = object(), object()

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        # trace-time Python only: counts (re)tracings of a jitted step /
        # eager calls, and names the traced ops for xprof. Disabled-mode HLO
        # is byte-identical (tests/bases/test_obs.py pins this).
        _obs_note_trace(_step_label, _step_token)
        with _obs_span(_step_label, category="step"):
            if mergeable:
                # ONE update on a fresh state; the carry merge is elementwise and
                # the batch-local value reuses the same batch statistics — no
                # double update even eagerly
                b = _load(init())
                b.update(*args, **kwargs)
                batch_state = b.state_pytree()
                new_state = {
                    name: _MERGE_OPS[template._reductions[name]](state[name], batch_state[name])
                    for name in batch_state
                }
                if not with_value:
                    return new_state, None
                b._update_count = 1
                return new_state, b.compute()
            m = _load(state)
            m.update(*args, **kwargs)
            new_state = m.state_pytree()
            if not with_value:
                return new_state, None
            b = _load(init())
            b.update(*args, **kwargs)
            b._update_count = 1
            return new_state, b.compute()

    # Gather-typed states (buffers, cat/None/callable reductions) ride a
    # 1x-payload varying-typed all_gather; invariant typing is restored on
    # the small FINAL value instead of the gathered buffer (a pmax identity
    # collective) so a 1M-sample buffer sync moves ~1x payload, not the
    # n_dev x of the replicated psum-of-scatter form.
    # sketch states sync leafwise through the psum family too — no gather
    _psum_reductions = ("sum", "mean", "max", "min", "sketch")
    has_gather_state = any(
        isinstance(d, CapacityBuffer) or r not in _psum_reductions
        for r, d in zip(template._reductions.values(), template._defaults.values())
    )

    # sharded-state compute: resolve the metric's gather-free kernel at
    # BUILD time so an unsupported combination fails here, not inside a
    # mesh trace. Metrics without a kernel whose states are all
    # psum-family still qualify (psum already reduces in place); a
    # gather-state metric without a kernel has no gather-free path.
    _sharded_fn = None
    if sharded_state:
        from metrics_tpu.utilities.sharding import get_sharded_compute

        if axis_name is None:
            raise ValueError("sharded_state=True needs axis_name= (the mesh axis the state lives on)")
        _sharded_fn = get_sharded_compute(type(template))
        if _sharded_fn is None and has_gather_state:
            raise ValueError(
                f"{type(template).__name__} has gather-typed states but no registered sharded"
                " compute — register one via"
                " metrics_tpu.utilities.sharding.register_sharded_compute, or drop"
                " sharded_state=True to use the replicated gather sync."
            )

    def compute(state: State) -> Any:
        _obs_note_trace(_compute_label, _compute_token)
        # span shares _compute_label ("X.step_compute") with the counter —
        # and stays distinguishable from the eager Metric.compute span
        with _obs_span(_compute_label, category="compute"):
            return _compute_impl(state)

    def _compute_impl(state: State) -> Any:
        if axis_name is not None and _sharded_fn is not None:
            # gather-free path: the kernel owns the mesh reduction (reduce-
            # scatter / ring / scalar psums); the worker only provides
            # static config (bins, q, detected input mode)
            m = _load(state)
            m._update_count = 1
            return _sharded_fn(m, state, axis_name)
        if axis_name is not None:
            _multi = isinstance(axis_name, (tuple, list)) and len(axis_name) > 1
            reduced: State = {}
            for name, value in state.items():
                if isinstance(value, CapacityBuffer):
                    # in-graph uneven cat-state gather (reference
                    # utilities/distributed.py:128-151): gather data + count
                    # per device, concat the filled prefixes
                    reduced[name] = sync_buffer_in_context(value, axis_name, typed="varying")
                elif isinstance(value, Sketch):
                    # leafwise psum/pmin/pmax == the sketch merge over the
                    # mesh (counts add, extremes extremize) — same payload
                    # shape as a sum state, no gather
                    reduced[name] = sync_sketch_in_context(
                        value, axis_name, hierarchical=hierarchical_sync and _multi
                    )
                elif hierarchical_sync and _multi:
                    # topology-ordered chain: axis_name[0] (ICI) first,
                    # later axes (DCN) combine the already-reduced operand
                    reduced[name] = hierarchical_reduce_in_context(
                        value, template._reductions[name], axis_name, typed="varying"
                    )
                else:
                    reduced[name] = sync_reduce_in_context(
                        value, template._reductions[name], axis_name, typed="varying"
                    )
            state = reduced
        m = _load(state)
        m._update_count = 1  # state arrived from outside; silence the unused-metric warning
        out = m.compute()
        if axis_name is not None and has_gather_state:
            out = jax.tree_util.tree_map(lambda v: replicate_typed(v, axis_name), out)
        return out

    # per-launch device timing (obs.configure(device_timing=True)): EAGER
    # step/compute calls block on their outputs and land in the
    # step.latency_ms{step=} histograms; under any trace the wrapper is
    # pass-through, so jitted/scanned/vmapped uses are untouched — wrap a
    # jitted step with obs.instrument() for tracked-launch timing there
    return init, _obs_time_launch(step, _step_label), _obs_time_launch(compute, _compute_label)


def _is_host_batch_leaf(a: Any) -> bool:
    """Array-like (device OR host numpy) with at least an epoch axis."""
    import numpy as np

    return (_is_array(a) or isinstance(a, np.ndarray)) and getattr(a, "ndim", 0) >= 1


def _run_prefetched(
    run: Callable,
    state: State,
    batches: tuple,
    kw_batches: dict,
    k: int,
    with_values: bool,
) -> Tuple[State, Any]:
    """Double-buffered chunked epoch fold (the ``prefetch=K`` driver).

    The epoch axis splits into chunks of ``k`` batches; the driver enqueues
    ``jax.device_put`` of chunk ``c + 1`` BEFORE dispatching the fold of
    chunk ``c``, so the host-to-device transfer streams while the previous
    launch executes (jax's async dispatch provides the overlap — the
    driver only orders the enqueues and never blocks between chunks).
    Chunks preserve batch order, so the chunked fold equals the monolithic
    one by the same merge-combination argument as the flat epoch (bitwise
    for integer-valued monoid states; float merge sums may reassociate by
    an ulp, exactly like flat-vs-vmap).
    """
    keys = sorted(kw_batches)
    n_pos = len(batches)
    leaves = list(batches) + [kw_batches[kk] for kk in keys]
    arr_idx = [i for i, a in enumerate(leaves) if _is_host_batch_leaf(a)]
    if not arr_idx:
        return run(state, *batches, **kw_batches)
    n_batches = leaves[arr_idx[0]].shape[0]
    if n_batches == 0:
        return run(state, *batches, **kw_batches)

    def _put_chunk(lo: int, hi: int) -> list:
        return [
            jax.device_put(a[lo:hi]) if i in arr_idx else a for i, a in enumerate(leaves)
        ]

    def _rebuild(chunk: list) -> Tuple[tuple, dict]:
        return tuple(chunk[:n_pos]), dict(zip(keys, chunk[n_pos:]))

    bounds = list(range(0, n_batches, k)) + [n_batches]
    values_acc: list = []
    nxt = _put_chunk(bounds[0], bounds[1])
    for lo, hi in zip(bounds, bounds[1:]):
        cur = nxt
        if hi < n_batches:
            # enqueue the NEXT transfer first: it streams while the fold
            # dispatched just below executes
            nxt = _put_chunk(hi, min(hi + k, n_batches))
        args_c, kwargs_c = _rebuild(cur)
        state, vals = run(state, *args_c, **kwargs_c)
        if with_values and vals is not None:
            values_acc.append(vals)
    if with_values and values_acc:
        values = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *values_acc
        )
        return state, values
    return state, None


# fold a stacked (B, *state) leaf down its leading axis with the state's own
# declared reduction — the epoch-axis analogue of _MERGE_OPS (a vmapped
# sketch state is a Sketch whose leaves carry the stacked axis)
_FOLD_OPS: Dict[str, Callable] = {
    "sum": lambda m: m.sum(axis=0),
    "max": lambda m: m.max(axis=0),
    "min": lambda m: m.min(axis=0),
    "sketch": lambda m: m.reduce_leading_axis(),
}


def make_epoch(
    metric: Union[Metric, Type[Metric], "MetricCollection"],  # noqa: F821
    *init_args: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]] = None,
    with_values: bool = False,
    jit_epoch: bool = True,
    engine: Any = None,
    sharded_state: bool = False,
    hierarchical_sync: bool = False,
    prefetch: Optional[int] = None,
    **init_kwargs: Any,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """Build ``(init, epoch, compute)``: a WHOLE epoch of batches per launch.

    ``epoch(state, *batches, **kw_batches)`` folds every batch of an epoch
    into the carry inside ONE compiled program: array inputs carry a leading
    epoch axis (``(num_batches, batch_size, ...)``), and the per-batch
    ``step`` of :func:`make_step` is rolled into the program instead of being
    dispatched once per batch — an eager loop of 16 ``step`` calls becomes
    one launch, which is where small-batch epochs lose most of their time on
    dispatch-latency-bound (tunneled) devices.

    How the batches are rolled depends on the metric's states:

    * **merge-combinable states** (every state sum/max/min-reducible — the
      same property the DDP gather-reduce sync relies on): the whole epoch
      collapses to ONE update over the flattened ``(num_batches *
      batch_size, ...)`` inputs, merged into the carry. XLA sees a single
      full-width kernel — no sequential per-batch chain at all. With
      ``with_values=True`` the per-batch contributions are instead computed
      under one ``jax.vmap`` (still one launch) so each batch's local value
      exists.
    * **anything else** ``make_step`` supports (running-moment states,
      wrappers): a ``jax.lax.scan`` of the step over the epoch
      axis — one launch, sequential inner kernels.
    * a :class:`MetricCollection` routes to :func:`make_collection_epoch`
      (whole-collection fusion: update dedup + shared input pass + one
      launch for every member).

    Args:
        metric: as :func:`make_step` (class, instance, or collection).
        axis_name: as :func:`make_step`; ``compute`` reduces over the mesh
            axis. Call ``epoch`` inside the same ``shard_map`` program.
        with_values: when True, ``epoch`` also returns the stacked per-batch
            metric values (``(num_batches, ...)``) — the scanned analogue of
            ``step``'s batch-local value; when False (default) it returns
            ``(state', None)`` and skips that work.
        jit_epoch: wrap ``epoch`` in ``jax.jit`` with the carry donated
            (default). Pass False when composing it inside an outer jit /
            ``shard_map`` yourself.
        engine: execution backend (see :mod:`metrics_tpu.engine`):
            ``None``/``"jit"`` keep today's jitted path; ``"eager"`` runs
            the epoch un-jitted (no compile ever — the reference's L1
            semantics); ``"aot"`` or an
            :class:`~metrics_tpu.engine.AotEngine` resolves one executable
            per input signature through the persistent program store —
            a warm store means the first epoch of a fresh process pays
            zero backend compiles. The returned ``epoch`` then also
            exposes ``precompile(state, *batches)`` (``ShapeDtypeStruct``
            leaves accepted) for ahead-of-traffic warmup.
        sharded_state / hierarchical_sync: as :func:`make_step` — the
            gather-free mesh-resident compute, and the ICI-first/DCN-second
            per-axis reduction chain.
        prefetch: double-buffered host-to-device streaming. ``prefetch=K``
            splits the epoch axis into chunks of ``K`` batches and, while
            the fold of chunk ``c`` is in flight on device, ``jax.device_put``
            of chunk ``c + 1`` streams concurrently — host-resident (numpy)
            epochs never stall a launch waiting for a transfer. Folding in
            chunks preserves batch order, so scan-path and count/sketch
            states (integer-valued monoids) stay BITWISE equal to the
            unchunked launch; merge-fold float sums may reassociate by an
            ulp exactly as the flat-vs-vmap paths already may. The chunked
            program traces once per distinct chunk shape (a ragged final
            chunk costs one extra trace).

    Exactly-once resume:
        ``epoch`` accepts two reserved keyword arguments, ``resume_from``
        (a :class:`~metrics_tpu.ft.ResumeCursor` from a restored
        :class:`~metrics_tpu.ft.BatchJournal`) and ``epoch_index`` (this
        epoch's absolute index). Batches the restored state already folded
        are sliced off host-side before the launch — a fully-folded epoch
        returns ``(state, None)`` without launching — so a preempted sweep
        resumed from a checkpoint never double-counts (the kill-and-resume
        tests pin ``compute()`` bitwise-equal to an uninterrupted run).
        The resumed epoch's trimmed shape costs one extra trace; later
        epochs reuse the full-shape program. With ``with_values=True`` the
        returned per-batch values cover only the freshly-folded batches.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.steps import make_epoch
        >>> init, epoch, compute = make_epoch(Accuracy, num_classes=3)
        >>> preds = jnp.asarray([[0, 1, 2, 2], [1, 1, 0, 2]])  # 2 batches
        >>> target = jnp.asarray([[0, 1, 1, 2], [0, 1, 0, 2]])
        >>> state, _ = epoch(init(), preds, target)  # ONE launch
        >>> compute(state)
        Array(0.75, dtype=float32)
    """
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.wrappers.abstract import WrapperMetric

    if prefetch is not None and (not isinstance(prefetch, int) or prefetch < 1):
        raise ValueError(f"`prefetch` must be a positive int (batches per chunk) or None, got {prefetch!r}")

    if isinstance(metric, MetricCollection):
        # whole-collection fusion: one launch per epoch for every member,
        # update dedup across compute-grouped members, shared input pass
        if init_args or init_kwargs:
            raise TypeError("make_epoch(collection) takes no extra args; configure the collection itself")
        if sharded_state or hierarchical_sync:
            raise ValueError(
                "sharded_state/hierarchical_sync are per-metric knobs: build per-member epochs"
                " (one make_epoch per sharded metric) instead of a fused collection epoch."
            )
        return make_collection_epoch(
            metric,
            axis_name=axis_name,
            with_values=with_values,
            jit_epoch=jit_epoch,
            engine=engine,
            prefetch=prefetch,
        )

    # construct a class argument ONCE and hand the instance to make_step
    # (which clones it), so ctor work is not duplicated
    if isinstance(metric, type) and issubclass(metric, Metric):
        metric = metric(*init_args, **init_kwargs)
        init_args, init_kwargs = (), {}

    mergeable = False
    reductions: Dict[str, str] = {}
    if isinstance(metric, Metric) and not isinstance(metric, WrapperMetric):
        mergeable = _is_mergeable(metric)
        reductions = dict(metric._reductions)

    init, step, compute = make_step(
        metric,
        *init_args,
        axis_name=axis_name,
        with_value=with_values,
        sharded_state=sharded_state,
        hierarchical_sync=hierarchical_sync,
        **init_kwargs,
    )

    def _split(batches: tuple, kw_batches: dict):
        keys = sorted(kw_batches)
        leaves = list(batches) + [kw_batches[k] for k in keys]
        return keys, len(batches), leaves

    def _rebuild(keys, n_pos, leaves):
        return tuple(leaves[:n_pos]), dict(zip(keys, leaves[n_pos:]))

    def _epoch_scan(state: State, *batches: Any, **kw_batches: Any) -> Tuple[State, Any]:
        keys, n_pos, leaves = _split(batches, kw_batches)
        scanned_idx = [i for i, a in enumerate(leaves) if _is_array(a)]
        static = {i: a for i, a in enumerate(leaves) if i not in scanned_idx}

        def body(s, xs):
            merged = [static[i] if i in static else xs[scanned_idx.index(i)] for i in range(len(leaves))]
            args_b, kwargs_b = _rebuild(keys, n_pos, merged)
            s2, value = step(s, *args_b, **kwargs_b)
            return s2, (value if with_values else None)

        return jax.lax.scan(body, state, tuple(leaves[i] for i in scanned_idx))

    def _epoch_vmap(state: State, *batches: Any, **kw_batches: Any) -> Tuple[State, Any]:
        # mergeable + per-batch values: every batch's contribution state is
        # accumulated from the default under one vmap, folded down the epoch
        # axis with its own declared reduction, and merged into the carry —
        # parallel inner kernels instead of a sequential scan chain
        keys, n_pos, leaves = _split(batches, kw_batches)
        axes = tuple(0 if _is_array(a) else None for a in leaves)

        def contrib(*flat):
            args_b, kwargs_b = _rebuild(keys, n_pos, list(flat))
            return step(init(), *args_b, **kwargs_b)

        batch_states, values = jax.vmap(contrib, in_axes=axes)(*leaves)
        new_state = {
            name: _MERGE_OPS[reductions[name]](state[name], _FOLD_OPS[reductions[name]](rows))
            for name, rows in batch_states.items()
        }
        return new_state, (values if with_values else None)

    def _epoch_flat(state: State, *batches: Any, **kw_batches: Any) -> Tuple[State, Any]:
        # mergeable, no values: ONE update over the flattened epoch. Valid by
        # the same invariant the DDP gather-reduce sync relies on — merging
        # per-batch (per-rank) updates equals one update over their
        # concatenation when every state folds with sum/max/min.
        keys, n_pos, leaves = _split(batches, kw_batches)
        flat = [
            a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]) if _is_array(a) else a
            for a in leaves
        ]
        args_b, kwargs_b = _rebuild(keys, n_pos, flat)
        new_state, _ = step(state, *args_b, **kwargs_b)
        return new_state, None

    obs_name = type(metric).__name__
    _epoch_label = f"{obs_name}.epoch"
    _epoch_token = object()

    # execution-engine resolution: "eager" forces the un-jitted path (no
    # compile ever); "aot"/an AotEngine routes the jitted program through
    # the persistent executable store; None/"jit" keep the default path
    from metrics_tpu.engine import EagerEngine, get_engine

    engine_obj = get_engine(engine)
    if isinstance(engine_obj, EagerEngine):
        jit_epoch, engine_obj = False, None

    def epoch(state: State, *batches: Any, **kw_batches: Any) -> Tuple[State, Any]:
        _obs_note_trace(_epoch_label, _epoch_token)
        with _obs_span(_epoch_label, category="epoch"):
            if not mergeable:
                return _epoch_scan(state, *batches, **kw_batches)
            if with_values:
                return _epoch_vmap(state, *batches, **kw_batches)
            _, _, leaves = _split(batches, kw_batches)
            if all(getattr(a, "ndim", 0) >= 2 for a in leaves if _is_array(a)):
                return _epoch_flat(state, *batches, **kw_batches)
            # an array leaf with only the epoch axis (per-batch scalars, e.g.
            # MeanMetric weights) has no sample axis to flatten into
            return _epoch_vmap(state, *batches, **kw_batches)

    if jit_epoch:
        raw_jitted = jax.jit(epoch, donate_argnums=0)
        if engine_obj is not None and engine_obj.name != "jit":
            jitted = _engine_dispatch(
                raw_jitted, _epoch_label, _metric_fingerprint(metric), engine_obj
            )
        else:
            jitted = _obs_track_compiles(raw_jitted, _epoch_label)

        def epoch(  # noqa: F811
            state: State,
            *batches: Any,
            resume_from: Any = None,
            epoch_index: Optional[int] = None,
            **kw_batches: Any,
        ) -> Tuple[State, Any]:
            if resume_from is not None:
                batches, kw_batches, done = _apply_resume(resume_from, epoch_index, batches, kw_batches)
                if done:  # every batch of this epoch is already in the state
                    return state, None
            # fused-epoch launch accounting from the EAGER entry's argument
            # shapes (host-side; the jitted program is untouched) — counted
            # AFTER resume trimming so batches_folded stays honest
            leaves = list(batches) + list(kw_batches.values())
            n_batches = next((a.shape[0] for a in leaves if getattr(a, "ndim", 0) >= 1), None)
            _obs_epoch_launch(_epoch_label, n_batches)
            if prefetch is not None:
                return _run_prefetched(jitted, state, batches, kw_batches, prefetch, with_values)
            return jitted(state, *batches, **kw_batches)

        # keep the jitted-callable surface usable through the accounting
        # wrapper (AOT lowering, cache control, introspection)
        epoch.__wrapped__ = raw_jitted
        for attr in ("lower", "eval_shape", "trace", "clear_cache"):
            if hasattr(raw_jitted, attr):
                setattr(epoch, attr, getattr(raw_jitted, attr))
        if hasattr(jitted, "precompile"):
            epoch.precompile = jitted.precompile
    else:
        # un-jitted epochs still get per-launch device timing at the eager
        # entry (trace-transparent when composed into an outer jit)
        _inner_epoch = _obs_time_launch(epoch, _epoch_label)

        def epoch(  # noqa: F811
            state: State,
            *batches: Any,
            resume_from: Any = None,
            epoch_index: Optional[int] = None,
            **kw_batches: Any,
        ) -> Tuple[State, Any]:
            if resume_from is not None:
                # host-side trim: the cursor must be concrete (slice sizes
                # are shapes), which it is when it comes from a restored
                # journal rather than a traced value
                batches, kw_batches, done = _apply_resume(resume_from, epoch_index, batches, kw_batches)
                if done:
                    return state, None
            if prefetch is not None:
                return _run_prefetched(_inner_epoch, state, batches, kw_batches, prefetch, with_values)
            return _inner_epoch(state, *batches, **kw_batches)

    return init, epoch, compute


def make_stream_step(
    metric: Any,
    *,
    axis_name: Optional[Union[str, Tuple[str, ...]]] = None,
    jit_step: bool = True,
    engine: Any = None,
    sharded_state: bool = False,
    hierarchical_sync: bool = False,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """Build ``(init, stream_step, compute)`` from a windowed/decayed metric:
    one launch folds a batch AND emits the current window value.

    The eager :class:`~metrics_tpu.streaming.WindowedMetric` /
    :class:`~metrics_tpu.streaming.DecayedMetric` API pays one dispatch for
    the fold and another for every ``compute()``; an always-on monitor
    wants both per batch. ``stream_step(state, *batch) -> (state', value)``
    rolls the batch contribution, the ring-slot fold (or decay), the
    automatic window rotation with shard expiry, and the refold-and-compute
    of the CURRENT window into one traced program — the streaming analogue
    of :func:`make_step`'s fused forward.

    Args:
        metric: a configured :class:`~metrics_tpu.streaming.WindowedMetric`
            (``updates_per_slot`` must be set — ring rotation must be
            expressible in-graph) or
            :class:`~metrics_tpu.streaming.DecayedMetric` instance. The
            wrapper's accumulated eager state is not carried over.
        axis_name: as :func:`make_step`; both the per-step window value and
            ``compute`` reduce the base state over the mesh axis — call
            ``stream_step`` inside the same ``shard_map`` program.
        jit_step: wrap ``stream_step`` in ``jax.jit`` with the carry
            donated (default). Pass False when composing into an outer jit.
        engine: execution backend as :func:`make_epoch` — ``"eager"``
            forces the un-jitted step, ``"aot"`` resolves the step through
            the persistent program store (``stream_step.precompile`` is
            then exposed for ahead-of-traffic warmup).
        sharded_state / hierarchical_sync: as :func:`make_step`, applied to
            the BASE metric's mesh sync — a windowed ``StreamingAUROC``'s
            per-step window value then computes from reduce-scattered bins
            with no replicated merge. For host-resident streams, feed the
            loop through :func:`prefetch_to_device` so the next batch's
            transfer overlaps the current launch.

    The carry is a plain state pytree (ring position and in-slot counter
    ride as traced int32 scalars), so a monitoring loop can checkpoint it
    with :class:`metrics_tpu.ft.CheckpointManager` and resume exactly-once
    through the journal watermark like any epoch state.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.steps import make_stream_step
        >>> from metrics_tpu.streaming import WindowedMetric
        >>> acc = Accuracy(num_classes=2, multiclass=True)  # static classes for jit
        >>> init, step, compute = make_stream_step(WindowedMetric(acc, window=2))
        >>> state = init()
        >>> state, v = step(state, jnp.asarray([1, 1]), jnp.asarray([1, 1]))
        >>> state, v = step(state, jnp.asarray([0, 0]), jnp.asarray([1, 1]))
        >>> float(v)  # window of the last 2 batches, one launch per step
        0.5
    """
    from metrics_tpu.streaming.windows import DecayedMetric, WindowedMetric

    if isinstance(metric, WindowedMetric):
        if metric.updates_per_slot is None:
            raise ValueError(
                "make_stream_step needs WindowedMetric(updates_per_slot=N): ring rotation"
                " must happen in-graph, and a host-side advance() cannot reach a jitted step."
            )
        make = _make_windowed_stream_step
    elif isinstance(metric, DecayedMetric):
        make = _make_decayed_stream_step
    else:
        raise ValueError(
            f"make_stream_step expects a WindowedMetric or DecayedMetric instance, got"
            f" {type(metric).__name__}. Wrap the base metric first (metrics_tpu.streaming)."
        )
    init, step, compute = make(
        metric, axis_name, sharded_state=sharded_state, hierarchical_sync=hierarchical_sync
    )

    obs_name = f"{type(metric).__name__}[{type(metric._worker).__name__}]"
    _step_label = f"{obs_name}.stream_step"
    _step_token = object()

    def traced_step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        _obs_note_trace(_step_label, _step_token)
        with _obs_span(_step_label, category="step"):
            return step(state, *args, **kwargs)

    from metrics_tpu.engine import EagerEngine, get_engine

    engine_obj = get_engine(engine)
    if isinstance(engine_obj, EagerEngine):
        jit_step, engine_obj = False, None

    _precompile = None
    if not jit_step:
        inner = _obs_time_launch(traced_step, _step_label)
    elif engine_obj is not None and engine_obj.name != "jit":
        inner = _engine_dispatch(
            jax.jit(traced_step, donate_argnums=0),
            _step_label,
            _metric_fingerprint(metric),
            engine_obj,
        )
        _precompile = inner.precompile
    else:
        inner = _obs_track_compiles(jax.jit(traced_step, donate_argnums=0), _step_label)

    if isinstance(metric, WindowedMetric):
        # host-side ring-expiry accounting at the EAGER entry (the
        # make_epoch launch-counter pattern: the jitted program is
        # untouched and in-graph hooks would only fire at trace time).
        # Mirrors the carried pos arithmetic, so it assumes the normal
        # monitoring-loop shape — one linear state thread per factory.
        ups_count, k_count = metric.updates_per_slot, metric.window
        worker_name = type(metric._worker).__name__
        calls = [0]

        def stream_step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
            from metrics_tpu.obs.registry import enabled as _obs_enabled
            from metrics_tpu.obs.registry import inc as _obs_inc

            if _obs_enabled():
                calls[0] += 1
                if calls[0] > 1 and (calls[0] - 1) % ups_count == 0:
                    rotation = (calls[0] - 1) // ups_count
                    if rotation >= k_count:  # the cleared shard had content
                        _obs_inc("stream.windows_expired", metric=worker_name)
            return inner(state, *args, **kwargs)

    else:
        stream_step = inner
    if _precompile is not None:
        stream_step.precompile = _precompile
    return init, stream_step, compute


def prefetch_to_device(batches: Any, size: int = 2) -> Any:
    """Generator: ``jax.device_put`` up to ``size`` batches AHEAD of the
    consumer — the streaming-loop arm of ``make_epoch(prefetch=K)``.

    Wrap any iterable of batches (tuples/dicts/pytrees of host numpy or
    device arrays) feeding a :func:`make_stream_step` (or hand-written)
    loop::

        for preds, target in prefetch_to_device(batch_stream, size=2):
            state, value = stream_step(state, preds, target)

    While the current ``stream_step`` launch executes, the next batch's
    host-to-device transfer is already streaming (jax's async dispatch —
    ``device_put`` returns immediately), so the input pipeline never
    stalls a launch. ``size`` bounds the transfers in flight (device
    memory held ahead of consumption).
    """
    # validate EAGERLY (this outer function is not a generator), so a bad
    # `size` raises at the call site, not at the first iteration
    if not isinstance(size, int) or size < 1:
        raise ValueError(f"`size` must be a positive int, got {size!r}")

    def _generate() -> Any:
        import collections

        def _put(batch: Any) -> Any:
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a) if _is_host_batch_leaf(a) else a, batch
            )

        queue: Any = collections.deque()
        for batch in batches:
            queue.append(_put(batch))
            if len(queue) >= size:
                yield queue.popleft()
        while queue:
            yield queue.popleft()

    return _generate()


def overlap_epoch_sync(
    epoch: Callable,
    sync: Callable,
    state: State,
    chunks: Any,
) -> Tuple[State, list]:
    """Fold chunks while each previous chunk's sync collective is in flight.

    The async arm of the topology-aware sync: ``sync`` (a compiled mesh
    reduction — typically the ``compute`` of a ``make_epoch(...,
    axis_name=..., hierarchical_sync=True)`` factory wrapped in the
    caller's ``shard_map``/pjit program, or any pure jitted
    state-to-snapshot function) is ISSUED on chunk ``N``'s folded state and
    NOT waited on; the fold of chunk ``N + 1`` dispatches immediately
    after, so the collective for batch ``N`` rides the fabric while the
    device folds batch ``N + 1`` (jax async dispatch — the driver never
    blocks). Folding is pure, so reading state ``N`` while state ``N + 1``
    is being produced is race-free by construction.

    Args:
        epoch: ``epoch(state, *chunk) -> (state', _)`` from
            :func:`make_epoch` (or any pure fold).
        sync: ``sync(state) -> snapshot`` — the reduction to overlap.
        state: initial carry.
        chunks: iterable of per-chunk ``*batches`` tuples.

    Returns:
        ``(final_state, snapshots)`` — one un-blocked snapshot per chunk
        (jax arrays are futures; block when consuming, e.g.
        ``jax.block_until_ready(snapshots[-1])``).

    Note:
        Safe with a donated epoch carry: the snapshot's collective is
        ENQUEUED before the donating fold of the next chunk, so on the
        device stream it reads state ``N`` before the fold that reuses its
        buffers executes.
    """
    snapshots: list = []
    for chunk in chunks:
        if not isinstance(chunk, tuple):
            chunk = (chunk,)
        state, _ = epoch(state, *chunk)
        # issue the collective for THIS chunk's state; the next loop
        # iteration's fold dispatches without waiting on it
        snapshots.append(sync(state))
    return state, snapshots


def _windowed_fold(reductions: Dict[str, str], slots: State) -> State:
    return {name: _FOLD_OPS[red](slots[name]) for name, red in reductions.items()}


def _make_windowed_stream_step(
    metric: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    sharded_state: bool = False,
    hierarchical_sync: bool = False,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """WindowedMetric as a pure step: the carry is ``{"slots": ring of K
    state shards, "pos", "in_slot"}``; each step merges the batch
    contribution into the current shard, rotates + expires in-graph when
    the shard fills, and emits the base compute over the refolded window —
    bitwise the eager wrapper's update-then-compute sequence."""
    k = metric.window
    ups = metric.updates_per_slot
    reductions = dict(metric._base_reductions)
    base_init, base_step, base_compute = make_step(
        metric._worker,
        axis_name=axis_name,
        with_value=False,
        sharded_state=sharded_state,
        hierarchical_sync=hierarchical_sync,
    )

    def _stack_slots(one: State) -> State:
        return {
            name: one[name].stack(k) if red == "sketch" else jnp.broadcast_to(
                one[name][None], (k,) + jnp.shape(one[name])
            )
            for name, red in reductions.items()
        }

    def init() -> State:
        return {
            "slots": _stack_slots(base_init()),
            "pos": jnp.asarray(0, jnp.int32),
            "in_slot": jnp.asarray(0, jnp.int32),
        }

    def _set_row(stacked: Any, red: str, pos: Array, row: Any) -> Any:
        if red == "sketch":
            return stacked.set_slot(pos, row)
        return jax.lax.dynamic_update_index_in_dim(stacked, row.astype(stacked.dtype), pos, 0)

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        contrib, _ = base_step(base_init(), *args, **kwargs)  # mergeable: state IS the contribution
        pos, in_slot = state["pos"], state["in_slot"]
        # lazy rotation BEFORE the fold (the eager wrapper's order): when
        # the current shard is full, the ring advances and the oldest shard
        # expires to the state default, then the batch folds into the fresh
        # current shard — the emitted value always covers the newest batch
        wrap = in_slot >= ups
        new_pos = jnp.where(wrap, (pos + 1) % k, pos)
        defaults = base_init()
        slots: State = {}
        for name, red in reductions.items():
            stacked = state["slots"][name]
            cleared = _set_row(stacked, red, new_pos, defaults[name])
            expired = jax.tree_util.tree_map(
                lambda c, s: jnp.where(wrap, c, s), cleared, stacked
            )
            if red == "sketch":
                slots[name] = expired.merge_into_slot(new_pos, contrib[name])
            else:
                row = jax.lax.dynamic_index_in_dim(expired, new_pos, keepdims=False)
                slots[name] = _set_row(expired, red, new_pos, _MERGE_OPS[red](row, contrib[name]))
        new_in_slot = jnp.where(wrap, 1, in_slot + 1)
        value = base_compute(_windowed_fold(reductions, slots))
        return {"slots": slots, "pos": new_pos, "in_slot": new_in_slot}, value

    def compute(state: State) -> Any:
        return base_compute(_windowed_fold(reductions, state["slots"]))

    return init, step, compute


def _make_decayed_stream_step(
    metric: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    sharded_state: bool = False,
    hierarchical_sync: bool = False,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """DecayedMetric as a pure step: the carry is the base state (int sum
    states lifted to f32 — decayed counts are fractional); each step scales
    by the half-life decay, merges the batch contribution, and emits the
    base compute of the decayed state."""
    decay = metric.decay
    reductions = dict(metric._base_reductions)
    base_init, base_step, base_compute = make_step(
        metric._worker,
        axis_name=axis_name,
        with_value=False,
        sharded_state=sharded_state,
        hierarchical_sync=hierarchical_sync,
    )

    def _lift(state: State) -> State:
        return {
            name: state[name]
            if red == "sketch" or jnp.issubdtype(state[name].dtype, jnp.floating)
            else state[name].astype(jnp.float32)
            for name, red in reductions.items()
        }

    def init() -> State:
        return _lift(base_init())

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        contrib, _ = base_step(base_init(), *args, **kwargs)
        new_state: State = {}
        for name, red in reductions.items():
            acc = state[name]
            if red == "sketch":
                new_state[name] = acc.scale_sum_leaves(jnp.asarray(decay, jnp.float32)).merge(contrib[name])
            else:
                new_state[name] = acc * jnp.asarray(decay, acc.dtype) + contrib[name].astype(acc.dtype)
        return new_state, base_compute(new_state)

    def compute(state: State) -> Any:
        return base_compute(state)

    return init, step, compute


def _apply_resume(resume_from: Any, epoch_index: Optional[int], batches: tuple, kw_batches: dict):
    """Slice already-folded leading batches off the epoch inputs (host-side;
    see :mod:`metrics_tpu.ft.journal` for the cursor semantics)."""
    from metrics_tpu.ft.journal import trim_epoch_batches

    if epoch_index is None:
        raise ValueError("epoch(resume_from=...) also needs epoch_index= (this epoch's absolute index)")
    keys = sorted(kw_batches)
    n_pos = len(batches)
    leaves = list(batches) + [kw_batches[k] for k in keys]
    trimmed, _n_skipped, done = trim_epoch_batches(resume_from, epoch_index, leaves)
    return tuple(trimmed[:n_pos]), dict(zip(keys, trimmed[n_pos:])), done


def _make_bootstrap_step(
    wrapper: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    with_value: bool,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """Pure step functions over a :class:`~metrics_tpu.wrappers.BootStrapper`.

    The carry is ``{"key": jax PRNG key, "boot": stacked replicate states}``
    — the bootstrap axis lives INSIDE the carry, so the whole wrapper rides
    ``jax.lax.scan`` / ``shard_map`` as one traced program (the reference's
    N deep copies, ``torchmetrics/wrappers/bootstrapping.py:48``, become a
    vmapped axis). Each ``step`` splits the carried key and draws the
    resample matrix with ``jax.random`` (multinomial: a ``(B, N)`` index
    gather; poisson: per-sample weight multipliers) — trace-safe, unlike the
    eager wrapper's host-side numpy generator, so the two paths draw from
    different streams: parity with the eager wrapper is distributional, not
    bitwise. The key derives from the wrapper's ``seed`` (0 when unseeded).

    ``compute`` returns the same statistics dict as the eager wrapper
    (mean/std/quantile/raw over the replicate axis); under ``axis_name``
    each replicate leaf reduces with the base metric's declared reduction
    first.
    """
    if not wrapper._vmap:
        raise ValueError(
            "This BootStrapper fell back to the per-copy eager path (base metric not step-compatible, or"
            " poisson without sample-weight support), so its state is not a fixed-shape carry. Use a"
            " step-compatible base metric (fixed-shape sum/min/max states), or the eager wrapper API."
        )
    import numpy as np

    base_init, base_step, base_compute = wrapper._init, wrapper._step, wrapper._compute_one
    n_boot = wrapper.num_bootstraps
    strategy = wrapper.sampling_strategy
    reductions = {n: wrapper.base_metric._reductions[n] for n in wrapper._state_names}
    # an unseeded wrapper must stay nondeterministic across factories (the
    # eager path's default_rng(None) semantics): entropy-seed the key then,
    # never a fixed constant — parallel unseeded runs need independent draws
    seed = int(np.random.SeedSequence().generate_state(1)[0]) if wrapper._seed is None else wrapper._seed
    stats = {"mean": wrapper.mean, "std": wrapper.std, "quantile": wrapper.quantile, "raw": wrapper.raw}

    def _stacked_init() -> State:
        return _stack_state(base_init(), n_boot)

    def init() -> State:
        # PRNGKey and broadcast_to both allocate fresh unaliased buffers;
        # no donation-safety copy needed
        return {"key": jax.random.PRNGKey(seed), "boot": _stacked_init()}

    def _apply(boot: State, sub: Array, args: tuple, kwargs: dict) -> State:
        from metrics_tpu.wrappers.bootstrapping import _apply_resample

        leaves = list(args) + [kwargs[k] for k in sorted(kwargs)]
        size = next((a.shape[0] for a in leaves if getattr(a, "ndim", 0) >= 1), None)
        if size is None:
            raise ValueError(
                "None of the input contained tensors with a batch dimension, so could not determine"
                " the sampling size"
            )
        if strategy == "multinomial":
            matrix = jax.random.randint(sub, (n_boot, size), 0, size)
        else:
            matrix = jax.random.poisson(sub, 1.0, (n_boot, size)).astype(jnp.float32)
        return _apply_resample(base_step, boot, matrix, strategy, args, kwargs)

    def _statistics(vals: Array) -> Dict[str, Array]:
        out: Dict[str, Array] = {}
        if stats["mean"]:
            out["mean"] = vals.mean(axis=0)
        if stats["std"]:
            out["std"] = vals.std(axis=0, ddof=1)
        if stats["quantile"] is not None:
            out["quantile"] = jnp.quantile(vals, jnp.asarray(stats["quantile"]), axis=0)
        if stats["raw"]:
            out["raw"] = vals
        return out

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        key, sub = jax.random.split(state["key"])
        boot = _apply(state["boot"], sub, args, kwargs)
        new_state = {"key": key, "boot": boot}
        if not with_value:
            return new_state, None
        # batch-local statistics: the same resample applied to a fresh state
        # (XLA CSE shares the gathered batches between the two updates)
        batch_boot = _apply(_stacked_init(), sub, args, kwargs)
        return new_state, _statistics(jnp.asarray(jax.vmap(base_compute)(batch_boot)))

    def compute(state: State) -> Dict[str, Array]:
        boot = state["boot"]
        if axis_name is not None:
            boot = {n: sync_reduce_in_context(v, reductions[n], axis_name) for n, v in boot.items()}
        return _statistics(jnp.asarray(jax.vmap(base_compute)(boot)))

    return init, step, compute


def _make_classwise_step(
    wrapper: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    with_value: bool,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """ClasswiseWrapper as a pure step: the carry IS the base metric's state;
    only the compute output is relabeled into ``{name_label: scalar}``."""
    base_init, base_step, base_compute = make_step(wrapper.metric, axis_name=axis_name, with_value=with_value)
    _convert = wrapper._convert  # the wrapper's own labeling (zip-truncating, pure)

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        new_state, value = base_step(state, *args, **kwargs)
        return new_state, (_convert(jnp.asarray(value)) if with_value else None)

    def compute(state: State) -> Dict[str, Array]:
        return _convert(jnp.asarray(base_compute(state)))

    return base_init, step, compute


def _make_minmax_step(
    wrapper: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    with_value: bool,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """MinMaxMetric as a pure step.

    The carry is ``{"base": base_state, "min_val", "max_val"}``. Each step
    folds the batch and advances min/max with the post-update RUNNING value
    — equivalent to the eager wrapper when ``compute()`` follows every
    ``update()`` (the tracker's canonical usage). Under ``axis_name`` the
    running value is the SYNCED one (the base compute inside the step emits
    its reductions — a per-step collective over the scalar states; the true
    global trajectory, so avoid wrapping buffer-state metrics whose sync is
    a full gather).
    """
    base_init, base_step, base_compute = make_step(
        wrapper._base_metric, axis_name=axis_name, with_value=with_value
    )

    def init() -> State:
        return {
            "base": base_init(),
            "min_val": jnp.asarray(jnp.inf),
            "max_val": jnp.asarray(-jnp.inf),
        }

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        new_base, value = base_step(state["base"], *args, **kwargs)
        running = jnp.asarray(base_compute(new_base), dtype=jnp.float32)
        if running.size != 1:  # static under trace: raises at trace time, like the eager wrapper
            raise RuntimeError(
                f"Returned value from base metric should be a scalar, but got shape {running.shape}"
            )
        running = running.reshape(())
        new_state = {
            "base": new_base,
            "min_val": jnp.minimum(state["min_val"], running),
            "max_val": jnp.maximum(state["max_val"], running),
        }
        return new_state, value

    def compute(state: State) -> Dict[str, Array]:
        return {
            "raw": base_compute(state["base"]),
            "min": state["min_val"],
            "max": state["max_val"],
        }

    return init, step, compute


def _make_multioutput_step(
    wrapper: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    with_value: bool,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """MultioutputWrapper as a pure step: the reference's N deep copies
    become one stacked state pytree with a leading output axis, and every
    step is a single ``jax.vmap`` over the sliced ``output_dim`` of the
    array inputs (reference ``wrappers/multioutput.py:23``).

    ``remove_nans=True`` (NaN-row dropping, reference ``multioutput.py:11``)
    is expressed with STATIC shapes as masked merge-combination: every row's
    contribution state is accumulated from the default (an inner ``vmap``),
    NaN rows are replaced by the default — the identity element of their
    declared reduction — and the batch folds into the carry with each
    state's own ``dist_reduce_fx``. That is exactly the DDP gather-reduce
    equivalence the sync protocol already relies on, so it is available for
    the same metrics: all states sum/max/min-reducible.
    """
    if wrapper.remove_nans:
        # a nested wrapper base has NO states of its own (empty _defaults),
        # which would make the mergeability check vacuously true
        if (
            not wrapper.metrics[0]._defaults
            or not _is_mergeable(wrapper.metrics[0])
            or any(isinstance(d, Sketch) for d in wrapper.metrics[0]._defaults.values())
        ):
            raise ValueError(
                "MultioutputWrapper(remove_nans=True) as a step needs every base-metric state to be"
                " sum/max/min-reducible (NaN rows are masked to the reduction identity and"
                " merge-folded). This base metric has cat/mean/custom/sketch states; construct the"
                " wrapper with remove_nans=False (inputs must be NaN-free) or use the eager class API."
            )
        return _make_multioutput_nanmask_step(wrapper, axis_name=axis_name, with_value=with_value)
    if any(isinstance(d, (CapacityBuffer, Sketch)) for d in wrapper.metrics[0]._defaults.values()):
        raise ValueError(
            "MultioutputWrapper over a sample-buffer or sketch base metric is not a stackable"
            " step carry (these states cannot broadcast over the output axis here). Use the"
            " eager class API, or one make_step per output."
        )
    n_out = len(wrapper.metrics)
    dim = wrapper.output_dim
    squeeze = wrapper.squeeze_outputs
    base_init, base_step, base_compute = make_step(
        wrapper.metrics[0], axis_name=axis_name, with_value=with_value
    )

    def init() -> State:
        return _stack_state(base_init(), n_out)  # broadcast_to: fresh unaliased buffers

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        keys, n_pos, leaves, axes = _split_update_leaves(args, kwargs, dim)

        def one(s, *flat):
            flat = [jnp.expand_dims(a, dim) if (_is_array(a) and not squeeze) else a for a in flat]
            return base_step(s, *flat[:n_pos], **dict(zip(keys, flat[n_pos:])))

        new_state, values = jax.vmap(one, in_axes=(0,) + axes)(state, *leaves)
        return new_state, (values if with_value else None)

    def compute(state: State) -> Array:
        return jax.vmap(base_compute)(state)

    return init, step, compute


def _make_multioutput_nanmask_step(
    wrapper: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    with_value: bool,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """``MultioutputWrapper(remove_nans=True)`` with static shapes.

    Per output, each row's contribution state is accumulated from the
    default via an inner ``vmap``; rows flagged by ``_get_nan_indices``
    (reference ``wrappers/multioutput.py:11``) are masked back to the
    default — the identity of their declared reduction — and the whole
    batch folds into the carry with each state's ``dist_reduce_fx``. For
    sum/max/min states this equals dropping the rows exactly (up to float
    reassociation), by the same argument that makes the DDP gather-reduce
    sync equal to a single global update.
    """
    from metrics_tpu.wrappers.multioutput import _get_nan_indices

    n_out = len(wrapper.metrics)
    dim = wrapper.output_dim
    squeeze = wrapper.squeeze_outputs
    base = wrapper.metrics[0]
    reductions = dict(base._reductions)
    row_fold = {"sum": lambda m: m.sum(axis=0), "max": lambda m: m.max(axis=0), "min": lambda m: m.min(axis=0)}
    base_init, base_step, base_compute_local = make_step(base, axis_name=None, with_value=False)
    if axis_name is None:
        base_compute_synced = base_compute_local
    else:
        _, _, base_compute_synced = make_step(base, axis_name=axis_name, with_value=False)

    def init() -> State:
        return _stack_state(base_init(), n_out)

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        keys, n_pos, leaves, axes = _split_update_leaves(args, kwargs, dim)

        def one(s, *flat):
            flat = [jnp.expand_dims(a, dim) if (_is_array(a) and not squeeze) else a for a in flat]
            arrays = [a for a in flat if _is_array(a)]
            drop = _get_nan_indices(*arrays)  # (B,) True -> row removed
            row_axes = tuple(0 if _is_array(a) else None for a in flat)

            def row_contrib(*row):
                row = tuple(jnp.expand_dims(a, 0) if _is_array(a) else a for a in row)
                rs, _ = base_step(base_init(), *row[:n_pos], **dict(zip(keys, row[n_pos:])))
                return rs

            row_states = jax.vmap(row_contrib, in_axes=row_axes)(*flat)  # leaves: (B, *state)
            defaults = base_init()
            batch_state: State = {}
            for name, rows in row_states.items():
                keep = (~drop).reshape((-1,) + (1,) * (rows.ndim - 1))
                masked = jnp.where(keep, rows, defaults[name][None])
                batch_state[name] = row_fold[reductions[name]](masked)
            new_s = {
                name: _MERGE_OPS[reductions[name]](s[name], batch_state[name]) for name in batch_state
            }
            if not with_value:
                return new_s, None
            return new_s, base_compute_local(batch_state)

        new_state, values = jax.vmap(one, in_axes=(0,) + axes)(state, *leaves)
        return new_state, (values if with_value else None)

    def compute(state: State) -> Any:
        return jax.vmap(base_compute_synced)(state)

    return init, step, compute


def _collection_fusion_plan(
    collection: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    with_value: bool,
) -> Dict[str, Any]:
    """Shared machinery of the fused collection step/epoch factories.

    Builds, per member, the pure sub-functions it will run with, and an
    UPDATE-GROUP resolver: members whose batch-contribution computation is
    provably identical (same state names/reductions/defaults, same filtered
    kwargs, and the same traced jaxpr + embedded constants on the call's
    input shapes) share ONE update per traced program. Unlike the eager
    compute-group heuristic (state equality after the first batch, which a
    coincidental batch can fool), jaxpr equality is sound: identical
    programs on identical inputs produce identical states by construction.

    Members that cannot ride the contribution-merge formulation (wrappers,
    cat/buffer/``mean``/custom states, metrics with update-derived aux
    attrs) fall back to their own :func:`make_step` sub-functions inside
    the same traced body — still one launch, just no shared update.
    """
    import numpy as np

    from metrics_tpu.utilities.data import _flatten_dict
    from metrics_tpu.wrappers.abstract import WrapperMetric

    template = collection.clone()
    template.reset()
    # base (un-prefixed) names key the state; outputs go through the same
    # flatten + prefix/postfix naming as the eager collection's compute
    # (collections.py:260-267), so dict-valued members splice identically
    children = {name: m for name, m in template.items(keep_base=True, copy_state=False)}

    groupable: Dict[str, bool] = {}
    subs: Dict[str, Tuple] = {}  # solo members: full (init, step, compute)
    local_subs: Dict[str, Tuple] = {}  # groupable: axis_name-free, value-free
    synced_compute: Dict[str, Callable] = {}
    state_keys: Dict[str, Any] = {}
    for name, m in children.items():
        is_groupable = (
            isinstance(m, Metric)
            and not isinstance(m, WrapperMetric)
            and bool(m._defaults)
            and _is_mergeable(m)
            # update-derived Python attrs (e.g. a detected input mode) are
            # only set on the worker whose update actually runs; members
            # relying on them must run their own update
            and not type(m)._aux_attrs
        )
        groupable[name] = is_groupable
        if is_groupable:
            local_subs[name] = make_step(m, axis_name=None, with_value=False)
            synced_compute[name] = (
                local_subs[name][2]
                if axis_name is None
                else make_step(m, axis_name=axis_name, with_value=False)[2]
            )
            # the grouping key's data part: state names, reductions and the
            # default VALUES (two identical update programs starting from
            # different defaults produce different contributions — defaults
            # ride the jaxpr as consts, invisible to its pretty-print)
            state_keys[name] = tuple(
                (
                    sname,
                    str(m._reductions[sname]),
                    tuple(
                        (str(leaf.dtype), tuple(leaf.shape), np.asarray(leaf).tobytes())
                        for leaf in jax.tree_util.tree_leaves(m._defaults[sname])
                    ),
                )
                for sname in m._defaults
            )
        else:
            subs[name] = make_step(m, axis_name=axis_name, with_value=with_value)

    def _named(res: Dict[str, Any]) -> Dict[str, Any]:
        return {template._set_name(k): v for k, v in _flatten_dict(res).items()}

    def init() -> State:
        return {
            name: (local_subs[name][0]() if groupable[name] else subs[name][0]())
            for name in children
        }

    group_cache: Dict[Any, list] = {}

    def _leaf_sig(a: Any) -> Any:
        if _is_array(a):
            return (tuple(a.shape), str(a.dtype))
        return ("py", repr(a))

    def resolve_groups(args: tuple, kwargs: dict) -> list:
        """``[(representative, [member names])]`` for these input shapes."""
        sig = (
            tuple(_leaf_sig(a) for a in args),
            tuple(sorted((k, _leaf_sig(v)) for k, v in kwargs.items())),
        )
        cached = group_cache.get(sig)
        if cached is not None:
            return cached
        av_args = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype) if _is_array(a) else a for a in args
        )
        av_kwargs = {
            k: jax.ShapeDtypeStruct(v.shape, v.dtype) if _is_array(v) else v
            for k, v in kwargs.items()
        }
        keyed: Dict[Any, list] = {}
        order: list = []
        from metrics_tpu.obs.recompile import suppress_note_trace

        for name, m in children.items():
            key: Any = ("solo", name)
            if groupable[name]:
                fk = tuple(sorted(m._filter_kwargs(**av_kwargs)))
                li, ls, _ = local_subs[name]

                def contrib(*leaves, _li=li, _ls=ls, _n=len(av_args), _keys=fk):
                    s, _ = _ls(_li(), *leaves[:_n], **dict(zip(_keys, leaves[_n:])))
                    return s

                try:
                    # abstract probe: traces, never executes; its retrace is
                    # bookkeeping, not shape drift, so it must not count
                    with suppress_note_trace():
                        jaxpr = jax.make_jaxpr(contrib)(
                            *av_args, *[av_kwargs[k] for k in fk]
                        )
                    consts = jaxpr.consts
                    if sum(np.asarray(c).nbytes for c in consts) <= 1 << 20:
                        key = (
                            "jaxpr",
                            fk,
                            state_keys[name],
                            str(jaxpr),
                            tuple(np.asarray(c).tobytes() for c in consts),
                        )
                except Exception:
                    pass  # un-probeable member stays solo
            entry = keyed.get(key)
            if entry is None:
                keyed[key] = entry = []
                order.append(entry)
            entry.append(name)
        groups = [(members[0], members) for members in order]
        group_cache[sig] = groups
        return groups

    return {
        "template": template,
        "children": children,
        "groupable": groupable,
        "subs": subs,
        "local_subs": local_subs,
        "synced_compute": synced_compute,
        "named": _named,
        "init": init,
        "resolve_groups": resolve_groups,
        "label": f"MetricCollection[{len(children)}]",
    }


def _make_collection_step(
    collection: Any,
    axis_name: Optional[Union[str, Tuple[str, ...]]],
    with_value: bool,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """Pure step functions over a whole :class:`MetricCollection`, with
    update dedup and a shared input-normalization pass.

    The state is ``{metric_name: child_state}``; one ``step`` updates every
    member inside the same traced program. Members grouped by the fusion
    plan (see :func:`_collection_fusion_plan`) share ONE batch-contribution
    computation — the traced-program extension of the eager compute-group
    dedup from state sharing to update sharing — and the whole body runs
    under :func:`~metrics_tpu.utilities.checks.shared_input_format_scope`,
    so the input format/normalization pass executes once per distinct
    parameterization instead of once per member.
    """
    from metrics_tpu.obs.recompile import note_collection_fusion as _obs_collection
    from metrics_tpu.utilities.checks import shared_input_format_scope

    plan = _collection_fusion_plan(collection, axis_name, with_value)
    children, groupable = plan["children"], plan["groupable"]
    subs, local_subs, synced_compute = plan["subs"], plan["local_subs"], plan["synced_compute"]
    _named, resolve_groups = plan["named"], plan["resolve_groups"]
    label = plan["label"]
    _step_label, _compute_label = f"{label}.collection_step", f"{label}.collection_compute"
    _step_token, _compute_token = object(), object()

    def step(state: State, *args: Any, **kwargs: Any) -> Tuple[State, Any]:
        _obs_note_trace(_step_label, _step_token)
        with _obs_span(_step_label, category="step"):
            groups = resolve_groups(args, kwargs)
            _obs_collection(_step_label, len(children), len(groups))
            new_state: State = {}
            values: Dict[str, Any] = {}
            with shared_input_format_scope():
                for rep, members in groups:
                    m_rep = children[rep]
                    if not groupable[rep]:
                        _, sub_step, _ = subs[rep]
                        new_state[rep], values[rep] = sub_step(
                            state[rep], *args, **m_rep._filter_kwargs(**kwargs)
                        )
                        continue
                    li, ls, _ = local_subs[rep]
                    batch_state, _ = ls(li(), *args, **m_rep._filter_kwargs(**kwargs))
                    for name in members:
                        reds = children[name]._reductions
                        new_state[name] = {
                            k: _MERGE_OPS[reds[k]](state[name][k], batch_state[k])
                            for k in batch_state
                        }
                        if with_value:
                            values[name] = local_subs[name][2](batch_state)
            return new_state, (_named(values) if with_value else None)

    def compute(state: State) -> Dict[str, Any]:
        _obs_note_trace(_compute_label, _compute_token)
        with _obs_span(_compute_label, category="compute"):
            return _named(
                {
                    name: (
                        synced_compute[name](state[name])
                        if groupable[name]
                        else subs[name][2](state[name])
                    )
                    for name in children
                }
            )

    return plan["init"], step, compute


def make_collection_step(
    collection: "MetricCollection",  # noqa: F821
    *,
    axis_name: Optional[Union[str, Tuple[str, ...]]] = None,
    with_value: bool = True,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """Build fused pure ``(init, step, compute)`` functions from a whole
    :class:`~metrics_tpu.collections.MetricCollection`.

    One ``step(state, *batch)`` updates every member inside a single traced
    program, with two fusions the per-member eager loop cannot express:

    * **Update dedup** — members whose batch-contribution computation is
      provably identical (same states/reductions/defaults and the same
      traced program on these input shapes) share ONE update; the eager
      compute-group machinery dedupes *state*, this extends the dedup to
      the *update pass itself*, and the jaxpr-equality test cannot be
      fooled by a coincidental first batch the way the eager state-equality
      heuristic can.
    * **Shared input normalization** — the body runs under
      :func:`~metrics_tpu.utilities.checks.shared_input_format_scope`, so
      the classification input format/check pass executes once per distinct
      parameterization and is reused by every member that shares it.

    Args:
        collection: a configured ``MetricCollection`` (cloned; accumulated
            state is not carried over).
        axis_name: as :func:`make_step`; ``compute`` reduces every member
            state with its declared ``dist_reduce_fx`` over the mesh axis.
        with_value: when True (default), ``step`` also returns the
            batch-local values dict (the eager ``forward`` result).

    Returns:
        ``init() -> {member: state}``, ``step(state, *batch) ->
        (state', values)``, ``compute(state) -> {name: value}`` — all pure
        and trace-safe; member kwargs are filtered per update signature
        like the eager collection.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MetricCollection, Precision, Recall
        >>> from metrics_tpu.steps import make_collection_step
        >>> coll = MetricCollection([Precision(num_classes=3, average='macro'),
        ...                          Recall(num_classes=3, average='macro')])
        >>> init, step, compute = make_collection_step(coll, with_value=False)
        >>> state, _ = step(init(), jnp.asarray([0, 1, 2, 2]), jnp.asarray([0, 1, 1, 2]))
        >>> sorted(compute(state))
        ['Precision', 'Recall']
    """
    from metrics_tpu.collections import MetricCollection

    if not isinstance(collection, MetricCollection):
        raise TypeError(
            f"make_collection_step expects a MetricCollection, got {type(collection).__name__};"
            " use make_step for a single metric."
        )
    return _make_collection_step(collection, axis_name=axis_name, with_value=with_value)


def make_collection_epoch(
    collection: "MetricCollection",  # noqa: F821
    *,
    axis_name: Optional[Union[str, Tuple[str, ...]]] = None,
    with_values: bool = False,
    jit_epoch: bool = True,
    engine: Any = None,
    prefetch: Optional[int] = None,
) -> Tuple[Callable[[], State], Callable[..., Tuple[State, Any]], Callable[[State], Any]]:
    """Build ``(init, epoch, compute)`` folding a WHOLE collection's epoch in
    ONE jitted launch.

    The production eval-loop shape is dozens of metrics over the same
    predictions: a 12-metric collection driven eagerly pays 12 jitted
    launches, 12 input normalization passes and 12 state folds per batch.
    ``epoch(state, *batches)`` (inputs carry a leading
    ``(num_batches, batch, ...)`` epoch axis, like :func:`make_epoch`)
    instead lowers the entire collection into one compiled program:

    * members grouped by the fusion plan (identical contribution programs —
      see :func:`make_collection_step`) share ONE update computation;
    * across groups the input flatten + format/normalization pass runs
      exactly once and is reused by every group's fold
      (:func:`~metrics_tpu.utilities.checks.shared_input_format_scope`);
    * merge-combinable members collapse to one full-width update over the
      flattened ``(num_batches * batch, ...)`` inputs, merged into the
      carry by each state's declared ``dist_reduce_fx`` (the
      ``_MERGE_OPS``/``_FOLD_OPS`` registries — sum/max/min/sketch and
      reductions added via
      :func:`metrics_tpu.metric.register_state_reduction`);
    * anything else (wrappers, cat/buffer/``mean`` states) rides a
      ``lax.scan`` over the epoch axis INSIDE the same program;
    * the returned ``compute`` evaluates the whole collection from the
      folded states in one further jitted launch (``axis_name=None``; under
      a mesh axis it stays an open function to call inside the same
      ``shard_map`` program).

    The carry is donated across folds (``donate_argnums=0``), so epoch N+1
    reuses epoch N's state buffers.

    Args:
        collection: a configured ``MetricCollection`` (cloned).
        axis_name: as :func:`make_epoch`; ``compute`` reduces member states
            over the mesh axis — call ``epoch`` inside the same
            ``shard_map`` program (with ``jit_epoch=False``).
        with_values: when True, ``epoch`` also returns the per-batch values
            dict (each value stacked over the epoch axis).
        jit_epoch: wrap ``epoch`` in ``jax.jit`` with the carry donated
            (default); pass False when composing into an outer jit.
        engine: execution backend as :func:`make_epoch` — ``"eager"``
            forces the un-jitted path, ``"aot"`` resolves the fused epoch
            (and the fused compute) through the persistent program store;
            ``epoch.precompile`` is then exposed for warmup.
        prefetch: double-buffered host-to-device streaming as
            :func:`make_epoch` — chunk ``c + 1``'s ``jax.device_put``
            overlaps chunk ``c``'s in-flight fused fold.

    Exactly-once resume:
        ``epoch`` accepts the same reserved ``resume_from=`` /
        ``epoch_index=`` keywords as :func:`make_epoch`; already-folded
        leading batches are trimmed host-side before the launch, so a
        preempted sweep resumed from a
        :class:`~metrics_tpu.ft.BatchJournal` cursor never double-counts.

    Observability:
        with ``obs`` enabled, each fused fold is ONE tracked launch
        (``epoch.launches`` / ``runs`` under the
        ``step=MetricCollection[N].collection_epoch`` label), and the
        ``collection.members`` / ``collection.update_groups`` gauges record
        how many update computations the fusion actually pays for; with
        ``obs.configure(cost_analysis=True)`` the program's FLOPs/bytes
        land under the same per-collection label.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MetricCollection, Precision
        >>> from metrics_tpu.steps import make_collection_epoch
        >>> coll = MetricCollection([Accuracy(num_classes=3),
        ...                          Precision(num_classes=3, average='macro')])
        >>> init, epoch, compute = make_collection_epoch(coll)
        >>> preds = jnp.asarray([[0, 1, 2, 2], [1, 1, 0, 2]])  # 2 batches
        >>> target = jnp.asarray([[0, 1, 1, 2], [0, 1, 0, 2]])
        >>> state, _ = epoch(init(), preds, target)  # ONE launch
        >>> float(compute(state)['Accuracy'])
        0.75
    """
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.obs.recompile import note_collection_fusion as _obs_collection
    from metrics_tpu.utilities.checks import shared_input_format_scope

    if not isinstance(collection, MetricCollection):
        raise TypeError(
            f"make_collection_epoch expects a MetricCollection, got {type(collection).__name__};"
            " use make_epoch for a single metric."
        )
    if prefetch is not None and (not isinstance(prefetch, int) or prefetch < 1):
        raise ValueError(f"`prefetch` must be a positive int (batches per chunk) or None, got {prefetch!r}")

    plan = _collection_fusion_plan(collection, axis_name, with_values)
    children, groupable = plan["children"], plan["groupable"]
    subs, local_subs, synced_compute = plan["subs"], plan["local_subs"], plan["synced_compute"]
    _named, resolve_groups = plan["named"], plan["resolve_groups"]
    label = plan["label"]
    _epoch_label = f"{label}.collection_epoch"
    _compute_label = f"{label}.collection_compute"
    _epoch_token, _compute_token = object(), object()

    from metrics_tpu.engine import EagerEngine, get_engine

    engine_obj = get_engine(engine)
    if isinstance(engine_obj, EagerEngine):
        jit_epoch, engine_obj = False, None
    _collection_fingerprint = _metric_fingerprint(plan["template"]) if engine_obj is not None else ""

    def _flatten_leaf(a: Any) -> Any:
        return a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]) if _is_array(a) else a

    def _group_fold_flat(state, rep, members, flat_args, flat_kwargs, new_state):
        """Merge-combinable group, no values: ONE update over the flattened
        epoch, merged into every member's carry (valid by the same invariant
        the DDP gather-reduce sync relies on)."""
        m_rep = children[rep]
        li, ls, _ = local_subs[rep]
        batch_state, _ = ls(li(), *flat_args, **m_rep._filter_kwargs(**flat_kwargs))
        for name in members:
            reds = children[name]._reductions
            new_state[name] = {
                k: _MERGE_OPS[reds[k]](state[name][k], batch_state[k]) for k in batch_state
            }
        return None

    def _group_fold_vmap(state, rep, members, args, kwargs, new_state, values):
        """Merge-combinable group with values (or inputs without a sample
        axis): per-batch contributions under one vmap, folded down the
        epoch axis by each state's declared reduction."""
        m_rep = children[rep]
        li, ls, _ = local_subs[rep]
        fk = sorted(m_rep._filter_kwargs(**kwargs))
        leaves = list(args) + [kwargs[k] for k in fk]
        axes = tuple(0 if _is_array(a) else None for a in leaves)
        n_pos = len(args)

        def contrib(*flat):
            s, _ = ls(li(), *flat[:n_pos], **dict(zip(fk, flat[n_pos:])))
            return s

        batch_states = jax.vmap(contrib, in_axes=axes)(*leaves)
        for name in members:
            reds = children[name]._reductions
            new_state[name] = {
                k: _MERGE_OPS[reds[k]](state[name][k], _FOLD_OPS[reds[k]](rows))
                for k, rows in batch_states.items()
            }
            if values is not None:
                values[name] = jax.vmap(local_subs[name][2])(batch_states)

    def _solo_fold_scan(state, name, args, kwargs, new_state, values):
        """Non-mergeable member: its own sub-step over the epoch axis,
        inside the same traced program — first batch unrolled (so a
        CapacityBuffer carry allocates its data buffer, fixing the pytree
        structure the scan requires to be static), remaining batches
        scanned."""
        m = children[name]
        _, sub_step, _ = subs[name]
        fk = sorted(m._filter_kwargs(**kwargs))
        leaves = list(args) + [kwargs[k] for k in fk]
        n_pos = len(args)
        scanned_idx = [i for i, a in enumerate(leaves) if _is_array(a)]
        static = {i: a for i, a in enumerate(leaves) if i not in scanned_idx}

        def _at(batch_index):
            return [
                static[i] if i in static else leaves[i][batch_index] for i in range(len(leaves))
            ]

        first = _at(0)
        s1, v1 = sub_step(state[name], *first[:n_pos], **dict(zip(fk, first[n_pos:])))
        n_batches = leaves[scanned_idx[0]].shape[0] if scanned_idx else 1
        if n_batches <= 1:
            new_state[name] = s1
            if values is not None:
                values[name] = jax.tree_util.tree_map(lambda v: v[None], v1)
            return

        def body(s, xs):
            merged = [
                static[i] if i in static else xs[scanned_idx.index(i)] for i in range(len(leaves))
            ]
            s2, value = sub_step(s, *merged[:n_pos], **dict(zip(fk, merged[n_pos:])))
            return s2, (value if values is not None else None)

        new_state[name], vals = jax.lax.scan(
            body, s1, tuple(leaves[i][1:] for i in scanned_idx)
        )
        if values is not None:
            values[name] = jax.tree_util.tree_map(
                lambda a, b: jnp.concatenate([a[None], b], axis=0), v1, vals
            )

    def epoch_body(state: State, *batches: Any, **kw_batches: Any) -> Tuple[State, Any]:
        _obs_note_trace(_epoch_label, _epoch_token)
        with _obs_span(_epoch_label, category="epoch"):
            leaves = list(batches) + list(kw_batches.values())
            flatable = all(getattr(a, "ndim", 0) >= 2 for a in leaves if _is_array(a))
            if flatable and not with_values:
                # flat path: group on the flattened shapes the contributions
                # actually run with
                flat_args = tuple(_flatten_leaf(a) for a in batches)
                flat_kwargs = {k: _flatten_leaf(v) for k, v in kw_batches.items()}
                groups = resolve_groups(flat_args, flat_kwargs)
            else:
                # vmap path: group on one batch slice — the shapes the
                # vmapped per-batch contributions see (the slice is dead
                # code under the trace; XLA DCEs it)
                flat_args, flat_kwargs = batches, kw_batches
                groups = resolve_groups(
                    tuple(a[0] if _is_array(a) and getattr(a, "ndim", 0) >= 1 else a for a in batches),
                    {
                        k: (v[0] if _is_array(v) and getattr(v, "ndim", 0) >= 1 else v)
                        for k, v in kw_batches.items()
                    },
                )
            _obs_collection(_epoch_label, len(children), len(groups))
            new_state: State = {}
            values: Optional[Dict[str, Any]] = {} if with_values else None
            with shared_input_format_scope():
                for rep, members in groups:
                    if not groupable[rep]:
                        _solo_fold_scan(state, rep, batches, kw_batches, new_state, values)
                    elif not with_values and flatable:
                        _group_fold_flat(state, rep, members, flat_args, flat_kwargs, new_state)
                    else:
                        _group_fold_vmap(state, rep, members, batches, kw_batches, new_state, values)
            return new_state, (_named(values) if with_values else None)

    def compute_body(state: State) -> Dict[str, Any]:
        _obs_note_trace(_compute_label, _compute_token)
        with _obs_span(_compute_label, category="compute"):
            return _named(
                {
                    name: (
                        synced_compute[name](state[name])
                        if groupable[name]
                        else subs[name][2](state[name])
                    )
                    for name in children
                }
            )

    if jit_epoch:
        raw_jitted = jax.jit(epoch_body, donate_argnums=0)
        if engine_obj is not None and engine_obj.name != "jit":
            jitted = _engine_dispatch(raw_jitted, _epoch_label, _collection_fingerprint, engine_obj)
        else:
            jitted = _obs_track_compiles(raw_jitted, _epoch_label)

        def epoch(
            state: State,
            *batches: Any,
            resume_from: Any = None,
            epoch_index: Optional[int] = None,
            **kw_batches: Any,
        ) -> Tuple[State, Any]:
            if resume_from is not None:
                batches, kw_batches, done = _apply_resume(resume_from, epoch_index, batches, kw_batches)
                if done:
                    return state, None
            leaves = list(batches) + list(kw_batches.values())
            n_batches = next((a.shape[0] for a in leaves if getattr(a, "ndim", 0) >= 1), None)
            _obs_epoch_launch(_epoch_label, n_batches)
            if prefetch is not None:
                return _run_prefetched(jitted, state, batches, kw_batches, prefetch, with_values)
            return jitted(state, *batches, **kw_batches)

        epoch.__wrapped__ = raw_jitted
        for attr in ("lower", "eval_shape", "trace", "clear_cache"):
            if hasattr(raw_jitted, attr):
                setattr(epoch, attr, getattr(raw_jitted, attr))
        if hasattr(jitted, "precompile"):
            epoch.precompile = jitted.precompile
    else:
        _inner_epoch = _obs_time_launch(epoch_body, _epoch_label)

        def epoch(  # noqa: F811
            state: State,
            *batches: Any,
            resume_from: Any = None,
            epoch_index: Optional[int] = None,
            **kw_batches: Any,
        ) -> Tuple[State, Any]:
            if resume_from is not None:
                batches, kw_batches, done = _apply_resume(resume_from, epoch_index, batches, kw_batches)
                if done:
                    return state, None
            if prefetch is not None:
                return _run_prefetched(_inner_epoch, state, batches, kw_batches, prefetch, with_values)
            return _inner_epoch(state, *batches, **kw_batches)

    # dynamic-count states (CapacityBuffer, cat lists) need concrete fill
    # counts at compute time — their compute cannot be jitted blind
    jit_computable = all(
        not any(isinstance(d, (CapacityBuffer, list)) for d in m._defaults.values())
        for m in children.values()
    )
    if jit_epoch and axis_name is None and jit_computable:
        # fused whole-collection compute: one further launch for every
        # member's final value (per-member eager computes would be N
        # launches). Not donated: callers keep folding after a mid-sweep
        # compute. XLA may fuse/reassociate float ops inside a member's
        # compute differently than the eager op-by-op dispatch, so float
        # values can differ from the eager path by an ulp; folded STATES
        # are bitwise-identical.
        if engine_obj is not None and engine_obj.name != "jit":
            compute = _engine_dispatch(
                jax.jit(compute_body), _compute_label, _collection_fingerprint, engine_obj
            )
        else:
            compute = _obs_track_compiles(jax.jit(compute_body), _compute_label)
    else:
        # under a mesh axis the collectives must trace inside the caller's
        # shard_map program (and buffer-state members need eager counts),
        # so the function stays open
        compute = compute_body

    return plan["init"], epoch, compute
