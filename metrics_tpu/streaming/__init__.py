"""``metrics_tpu.streaming`` — always-on online monitoring over endless streams.

The epoch lifecycle (``update``/``compute``/``reset``) assumes a finite
pass over a dataset; serving-time monitoring streams forever. This
subsystem supplies the three missing pieces (see ``docs/streaming.md``):

1. **Sketch states** (:mod:`~metrics_tpu.streaming.sketches`) — fixed-size,
   jit-safe, pytree-registered summaries whose merge is associative and
   commutative: :class:`QuantileSketch` and :class:`ScoreLabelSketch` back
   the bounded-memory :class:`StreamingAUROC` /
   :class:`StreamingAveragePrecision` / :class:`StreamingQuantile` metrics,
   each with a documented, computable error bound vs the exact
   sample-keeping path.
2. **Windowed and decayed wrappers** (:mod:`~metrics_tpu.streaming.windows`)
   — :class:`WindowedMetric` (ring of expirable state shards) and
   :class:`DecayedMetric` (half-life EWMA fold); drive them one launch per
   batch with :func:`metrics_tpu.steps.make_stream_step`.
3. **Drift monitors** (:mod:`~metrics_tpu.streaming.drift`) — PSI / KL / JS
   divergence of the live sketch against a frozen reference, with
   threshold alerts surfaced through ``metrics_tpu.obs`` counters.

Sketch-state metrics checkpoint through
:class:`metrics_tpu.ft.CheckpointManager` (manifest round-trip,
exactly-once resume via the journal watermark) like any other metric.
"""
from typing import Any

# sketches.py has no dependency on metric.py, so it loads eagerly (metric.py
# itself imports Sketch for the "sketch" reduction registry); everything
# depending on Metric loads lazily through __getattr__ to keep this package
# importable mid-way through metrics_tpu.metric's own import.
from metrics_tpu.streaming.distinct import DistinctCountSketch  # noqa: F401
from metrics_tpu.streaming.heavy import (  # noqa: F401
    CoOccurrenceSketch,
    HeavyHitterSketch,
)
from metrics_tpu.streaming.sketches import (  # noqa: F401
    QuantileSketch,
    ScoreLabelSketch,
    Sketch,
    merge_all,
    sketch_from_pack_tree,
)

__all__ = [
    "ChurnUndefinedError",
    "CoOccurrenceSketch",
    "DecayedMetric",
    "DistinctCountSketch",
    "DriftMonitor",
    "HeavyHitterSketch",
    "QuantileSketch",
    "ScoreLabelSketch",
    "Sketch",
    "StreamingAUROC",
    "StreamingAveragePrecision",
    "StreamingConfusion",
    "StreamingDistinctCount",
    "StreamingQuantile",
    "StreamingTopK",
    "WindowedMetric",
    "js_divergence",
    "kl_divergence",
    "merge_all",
    "population_stability_index",
    "sketch_from_pack_tree",
]

_LAZY = {
    "ChurnUndefinedError": "metrics_tpu.streaming.metrics",
    "StreamingAUROC": "metrics_tpu.streaming.metrics",
    "StreamingAveragePrecision": "metrics_tpu.streaming.metrics",
    "StreamingConfusion": "metrics_tpu.streaming.metrics",
    "StreamingDistinctCount": "metrics_tpu.streaming.metrics",
    "StreamingQuantile": "metrics_tpu.streaming.metrics",
    "StreamingTopK": "metrics_tpu.streaming.metrics",
    "WindowedMetric": "metrics_tpu.streaming.windows",
    "DecayedMetric": "metrics_tpu.streaming.windows",
    "DriftMonitor": "metrics_tpu.streaming.drift",
    "js_divergence": "metrics_tpu.streaming.drift",
    "kl_divergence": "metrics_tpu.streaming.drift",
    "population_stability_index": "metrics_tpu.streaming.drift",
}


def __getattr__(name: str) -> Any:
    if name in _LAZY:
        import importlib

        value = getattr(importlib.import_module(_LAZY[name]), name)
        globals()[name] = value  # cache: subsequent lookups skip __getattr__
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__() -> list:
    return sorted(set(globals()) | set(_LAZY))
