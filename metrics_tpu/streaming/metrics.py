"""Bounded-memory streaming metrics backed by mergeable sketch states.

The exact ``AUROC``/``AveragePrecision`` classes pay O(N) HBM (or host
memory) per epoch because their cat states keep every sample. These
classes keep a fixed-size :mod:`~metrics_tpu.streaming.sketches` summary
instead — a few KB of device state for an endless stream — and expose the
**documented error bound** alongside every value (``error_bound()``,
``bounds()``), so callers can trade memory for a *known* accuracy.

They are ordinary :class:`~metrics_tpu.metric.Metric` subclasses: they ride
``MetricCollection``, ``make_step``/``make_epoch`` (the sketch state is a
fixed-shape scan carry and merges under the ``"sketch"`` reduction),
``shard_map`` mesh sync (leafwise psum/pmin/pmax), and
:class:`metrics_tpu.ft.CheckpointManager` (manifest round-trip, exactly-once
resume) without special cases.
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.streaming.distinct import DistinctCountSketch
from metrics_tpu.streaming.heavy import CoOccurrenceSketch, HeavyHitterSketch
from metrics_tpu.streaming.sketches import QuantileSketch, ScoreLabelSketch

Array = jax.Array

__all__ = [
    "ChurnUndefinedError",
    "StreamingAUROC",
    "StreamingAveragePrecision",
    "StreamingConfusion",
    "StreamingDistinctCount",
    "StreamingQuantile",
    "StreamingTopK",
]


class ChurnUndefinedError(ValueError):
    """Top-k membership is AMBIGUOUS: the rigorous count envelopes of the
    k-th and (k+1)-th heaviest candidates overlap, so the set boundary —
    and therefore any entered/exited churn verdict — cannot be certified.
    The bounded-answers stance: refuse loudly rather than report churn
    that a heavier sketch (or the exact stream) could contradict. Widen
    ``capacity``/``depth``, or lower ``k``."""


class StreamingAUROC(Metric):
    """AUROC over an unbounded stream in ``8 * num_bins`` bytes of state.

    Binary scores in ``[0, 1]`` fold into a
    :class:`~metrics_tpu.streaming.sketches.ScoreLabelSketch`;
    :meth:`compute` returns the midpoint of the attainable AUROC interval
    and :meth:`error_bound` its half-width
    (``sum_b P_b * N_b / (2 * P * N)`` — ``|compute() - exact| <= bound``
    for the exact AUROC of the same stream, pinned at 1M samples by
    ``tests/streaming/test_streaming_metrics.py``). The default 2048 bins
    hold ~16 KB of device state; the bound shrinks as scores spread over
    more bins.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import StreamingAUROC
        >>> m = StreamingAUROC(num_bins=128)
        >>> m.update(jnp.asarray([0.1, 0.9, 0.3, 0.8]), jnp.asarray([0, 1, 0, 1]))
        >>> float(m.compute())
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, num_bins: int = 2048, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_bins = int(num_bins)
        self.add_state("sketch", default=ScoreLabelSketch(num_bins), dist_reduce_fx="sketch")

    def update(self, preds: Array, target: Array) -> None:
        self.sketch = self.sketch.fold(preds, target)

    def compute(self) -> Array:
        return self.sketch.auroc()

    def bounds(self) -> Tuple[Array, Array]:
        """Rigorous (lower, upper) interval containing the exact AUROC."""
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            return self.sketch.auroc_bounds()

    def error_bound(self) -> Array:
        """Half-width of :meth:`bounds` — the guaranteed accuracy of
        :meth:`compute` vs the exact AUROC of the folded stream."""
        lo, hi = self.bounds()
        return (hi - lo) / 2.0


class StreamingAveragePrecision(Metric):
    """Average precision over an unbounded stream, bounded memory.

    Same contract as :class:`StreamingAUROC`: binary scores fold into a
    :class:`~metrics_tpu.streaming.sketches.ScoreLabelSketch`, ``compute``
    returns the midpoint of the attainable AP interval over all within-bin
    orderings, and :meth:`error_bound` its half-width.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import StreamingAveragePrecision
        >>> m = StreamingAveragePrecision(num_bins=128)
        >>> m.update(jnp.asarray([0.1, 0.9, 0.3, 0.8]), jnp.asarray([0, 1, 0, 1]))
        >>> float(m.compute())
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, num_bins: int = 2048, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_bins = int(num_bins)
        self.add_state("sketch", default=ScoreLabelSketch(num_bins), dist_reduce_fx="sketch")

    def update(self, preds: Array, target: Array) -> None:
        self.sketch = self.sketch.fold(preds, target)

    def compute(self) -> Array:
        return self.sketch.average_precision()

    def bounds(self) -> Tuple[Array, Array]:
        """Rigorous (lower, upper) interval containing the exact AP."""
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            return self.sketch.average_precision_bounds()

    def error_bound(self) -> Array:
        """Half-width of :meth:`bounds`."""
        lo, hi = self.bounds()
        return (hi - lo) / 2.0


class StreamingQuantile(Metric):
    """Quantile(s) of an unbounded stream in fixed device memory.

    Values fold into a
    :class:`~metrics_tpu.streaming.sketches.QuantileSketch` over
    ``[lo, hi]`` with exact min/max tracking; :meth:`compute` returns the
    envelope-midpoint quantile(s) for ``q`` and :meth:`error_bound` the
    per-query half-width of the rigorous envelope — ``|compute() - exact|
    <= error_bound()`` always, at most ``(hi - lo) / (2 * num_bins)`` for
    in-range data.

    Args:
        q: quantile (scalar) or quantiles (sequence) to report.
        num_bins: histogram resolution (state is ``4 * (num_bins + 2)``
            bytes plus two scalars).
        lo / hi: expected data range; mass outside it lands in unbounded
            edge bins whose envelope is the exact running min/max.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import StreamingQuantile
        >>> m = StreamingQuantile(q=0.5, num_bins=100, lo=0.0, hi=1.0)
        >>> m.update(jnp.linspace(0.0, 1.0, 1001))
        >>> float(jnp.round(m.compute(), 3))  # exact median 0.5, bound 0.005
        0.505
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        q: Union[float, Sequence[float]] = 0.5,
        num_bins: int = 1024,
        lo: float = 0.0,
        hi: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.q = tuple(float(x) for x in jnp.atleast_1d(jnp.asarray(q)).tolist())
        self._scalar_q = jnp.ndim(q) == 0
        self.add_state("sketch", default=QuantileSketch(num_bins, lo, hi), dist_reduce_fx="sketch")

    def update(self, values: Array, weights: Optional[Array] = None) -> None:
        self.sketch = self.sketch.fold(values, weights)

    def compute(self) -> Array:
        out = self.sketch.quantile(jnp.asarray(self.q))
        return out[0] if self._scalar_q else out

    def bounds(self) -> Tuple[Array, Array]:
        """Rigorous per-query (lower, upper) envelope for the quantiles."""
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            lo, hi = self.sketch.quantile_bounds(jnp.asarray(self.q))
        if self._scalar_q:
            return lo[0], hi[0]
        return lo, hi

    def error_bound(self) -> Array:
        """Per-query half-width of :meth:`bounds`."""
        lo, hi = self.bounds()
        return (hi - lo) / 2.0


class StreamingTopK(Metric):
    """The ``k`` most frequent ids of an unbounded stream, fixed memory.

    Integer ids (error classes, labels, user cohorts — anything hashable
    to ``[0, 2^id_bits)``) fold into a
    :class:`~metrics_tpu.streaming.heavy.HeavyHitterSketch`;
    :meth:`compute` returns ``(ids, counts)`` for the ``k`` heaviest
    (SpaceSaving reporting contract: counts never underestimate, empty
    slots carry ``id=-1``) and :meth:`error_bound` the rigorous per-item
    overestimate envelope — the true count of reported item ``i`` lies in
    ``[counts[i] - error_bound()[i], counts[i]]``, always. Default state
    is ~100 KB regardless of stream length or id cardinality.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import StreamingTopK
        >>> m = StreamingTopK(k=2, capacity=64, id_bits=16)
        >>> m.update(jnp.asarray([7, 7, 7, 9, 9, 3]))
        >>> ids, counts = m.compute()
        >>> [int(i) for i in ids], [float(c) for c in counts]
        ([7, 9], [3.0, 2.0])
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        k: int = 10,
        capacity: int = 256,
        depth: int = 4,
        id_bits: int = 24,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if k < 1:
            raise ValueError(f"`k` must be >= 1, got {k}")
        self.k = int(k)
        self.add_state(
            "sketch", default=HeavyHitterSketch(capacity, depth, id_bits), dist_reduce_fx="sketch"
        )

    def update(self, ids: Array, weights: Optional[Array] = None) -> None:
        self.sketch = self.sketch.fold(ids, weights)

    def compute(self) -> Tuple[Array, Array]:
        ids, counts, _over = self.sketch.topk(self.k)
        return ids, counts

    def bounds(self) -> Tuple[Array, Array]:
        """Per-item rigorous ``(lower, upper)`` count envelope for the
        reported top-``k`` (``upper`` is the reported count)."""
        _obs_inc("stream.hh_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            _ids, counts, over = self.sketch.topk(self.k)
        return counts - over, counts

    def error_bound(self) -> Array:
        """Per-item overestimate envelope of the reported counts."""
        lo, hi = self.bounds()
        return hi - lo

    def certified_topk(self) -> np.ndarray:
        """The top-``k`` id set with CERTIFIED membership boundary.

        The set is certain when the k-th heaviest candidate's rigorous
        LOWER count bound strictly exceeds every possible competitor's
        UPPER bound — the (k+1)-th reported candidate AND any id the
        sketch could not decode at all. Undecoded ids are bounded by the
        RESIDUAL mass ``total - sum(candidate lower bounds)``: distinct
        ids partition the stream mass, so no unreported id can hold more
        than what the decoded candidates leave unaccounted. Raises
        :class:`ChurnUndefinedError` when the envelopes overlap (a
        saturated sketch leaves most mass undecodable, so the residual
        refuses loudly rather than certifying a fabricated boundary).
        """
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            depth, width = self.sketch.counts.shape[:2]
            n_cand = max(self.k + 1, int(depth) * int(width))
            ids, counts, over = self.sketch.topk(n_cand)
            total = float(np.asarray(self.sketch.counts)[0].sum())
        ids = np.asarray(ids)
        counts = np.asarray(counts)
        over = np.asarray(over)
        valid = ids >= 0
        unreported_ub = max(total - float((counts - over)[valid].sum()), 0.0)
        member = ids[: self.k]
        member = member[member >= 0]
        next_upper = unreported_ub
        if member.size == self.k and valid.size > self.k and valid[self.k]:
            next_upper = max(next_upper, float(counts[self.k]))
        kth_lower = (
            float(counts[self.k - 1] - over[self.k - 1]) if member.size == self.k else 0.0
        )
        # a short member list is exact only when NO mass is unaccounted;
        # a full one must clear every competitor strictly
        certified = next_upper == 0.0 or (member.size == self.k and kth_lower > next_upper)
        if not certified:
            raise ChurnUndefinedError(
                f"top-{self.k} membership is ambiguous: the k-th candidate's"
                f" lower count bound {kth_lower:g} does not exceed the best"
                f" competitor's upper bound {next_upper:g} (reported (k+1)-th"
                " candidate or residual undecoded mass) — entered/exited churn"
                " cannot be certified. Widen the sketch (capacity/depth) or"
                " lower k."
            )
        return member

    def churn(self, newer: "StreamingTopK") -> Dict[str, List[int]]:
        """Top-k membership churn from this state (interval ``a``) to
        ``newer`` (interval ``b``): ``StreamingTopK.churn(a, b)`` answers
        which ids ``entered``/``exited``/``stayed`` in the certified
        top-``k`` between two history snapshots of the same stream (the
        ``/query?mode=delta`` enrichment reads retained ring snapshots
        through this path). Refuses with :class:`ChurnUndefinedError`
        when EITHER side's membership boundary is ambiguous — a churn
        verdict built on an uncertain set would fabricate arrivals."""
        if not isinstance(newer, StreamingTopK):
            raise ValueError(
                f"churn compares two StreamingTopK states, got {type(newer).__name__}"
            )
        if newer.k != self.k:
            raise ValueError(f"churn needs matching k: {self.k} vs {newer.k}")
        _obs_inc("stream.churn_queries")
        old_ids = {int(i) for i in self.certified_topk()}
        new_ids = {int(i) for i in newer.certified_topk()}
        return {
            "entered": sorted(new_ids - old_ids),
            "exited": sorted(old_ids - new_ids),
            "stayed": sorted(new_ids & old_ids),
        }


class StreamingDistinctCount(Metric):
    """Distinct ids over an unbounded stream in ``4 * 2^precision`` bytes.

    "Unique users per tenant per window" at millions-of-users scale: ids
    fold into a :class:`~metrics_tpu.streaming.distinct.
    DistinctCountSketch` (HyperLogLog; merge is an exact idempotent
    bitwise max, so duplicate shipping and any fold order are harmless);
    :meth:`compute` returns the corrected cardinality estimate and
    :meth:`error_bound` the absolute 2-sigma envelope
    ``2 * 1.04 / sqrt(2^precision) * estimate`` (~3.2% at the default
    ``precision=12``). Note the registers are NOT invertible — interval
    deltas over history snapshots refuse (use a windowed instance for
    per-window uniques).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import StreamingDistinctCount
        >>> m = StreamingDistinctCount(precision=12)
        >>> m.update(jnp.arange(10_000))
        >>> abs(float(m.compute()) - 10_000) < float(m.error_bound())
        True
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, precision: int = 12, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.precision = int(precision)
        self.add_state("sketch", default=DistinctCountSketch(precision), dist_reduce_fx="sketch")

    def update(self, ids: Array) -> None:
        self.sketch = self.sketch.fold(ids)

    def compute(self) -> Array:
        return self.sketch.estimate()

    def bounds(self) -> Tuple[Array, Array]:
        """2-sigma ``(lower, upper)`` envelope around the estimate."""
        _obs_inc("stream.distinct_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            return self.sketch.bounds()

    def error_bound(self) -> Array:
        """Absolute half-width of :meth:`bounds` (2-sigma)."""
        lo, hi = self.bounds()
        return (hi - lo) / 2.0


class StreamingConfusion(Metric):
    """Confusion/co-occurrence structure for label spaces beyond the
    C<=128 exact tile, in fixed memory.

    ``(target, prediction)`` pairs fold into a
    :class:`~metrics_tpu.streaming.heavy.CoOccurrenceSketch` — hashed
    cell binning with an exact sum merge plus EXACT per-axis marginals.
    :meth:`compute` returns ``(rows, cols, counts)`` for the ``k``
    heaviest cells (counts never underestimate; empty slots ``-1``) and
    :meth:`error_bound` the per-cell collision envelope;
    :meth:`cell_bounds` answers arbitrary cells. A 10k x 10k label space
    costs the same device bytes as 100 x 100.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import StreamingConfusion
        >>> m = StreamingConfusion(num_rows=1000, k=1, capacity=64)
        >>> m.update(jnp.asarray([3, 3, 7]), jnp.asarray([3, 3, 9]))
        >>> rows, cols, counts = m.compute()  # k=1: squeezed to scalars
        >>> int(rows), int(cols), float(counts)
        (3, 3, 2.0)
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_rows: int,
        num_cols: Optional[int] = None,
        k: int = 16,
        capacity: int = 256,
        depth: int = 4,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if k < 1:
            raise ValueError(f"`k` must be >= 1, got {k}")
        self.k = int(k)
        self.add_state(
            "sketch",
            default=CoOccurrenceSketch(num_rows, num_cols, capacity, depth),
            dist_reduce_fx="sketch",
        )

    def update(self, target: Array, preds: Array, weights: Optional[Array] = None) -> None:
        self.sketch = self.sketch.fold(target, preds, weights)

    def compute(self) -> Tuple[Array, Array, Array]:
        rows, cols, counts, _over = self.sketch.top_cells(self.k)
        return rows, cols, counts

    def bounds(self) -> Tuple[Array, Array]:
        """Per-cell rigorous ``(lower, upper)`` envelope for the reported
        top-``k`` cells (``upper`` is the reported count)."""
        _obs_inc("stream.cooccur_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            _r, _c, counts, over = self.sketch.top_cells(self.k)
        return counts - over, counts

    def error_bound(self) -> Array:
        """Per-cell collision envelope of the reported counts."""
        lo, hi = self.bounds()
        return hi - lo

    def cell_bounds(self, target: Array, preds: Array) -> Tuple[Array, Array]:
        """Rigorous ``(lower, upper)`` count envelope for arbitrary
        queried ``(target, prediction)`` cells."""
        _obs_inc("stream.cooccur_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            return self.sketch.cell_bounds(target, preds)


# ---------------------------------------------------------------------------
# Sharded (gather-free) computes — make_step(..., sharded_state=True)
# ---------------------------------------------------------------------------
# Registered here, beside the classes: the kernels reduce-scatter the
# merged sketch bins over the mesh axis (each device keeps its 1/n bin
# slice resident — no full merged replica ever exists) and finish with
# segment-local math plus scalar collectives. See
# metrics_tpu/utilities/sharding.py for the kernel contracts.
from metrics_tpu.utilities.sharding import (  # noqa: E402
    register_sharded_compute as _register_sharded_compute,
    sharded_sketch_auroc as _sharded_sketch_auroc,
    sharded_sketch_average_precision as _sharded_sketch_ap,
    sharded_sketch_cooccur_top_cells as _sharded_cooccur_top_cells,
    sharded_sketch_distinct as _sharded_sketch_distinct,
    sharded_sketch_quantile as _sharded_sketch_quantile,
    sharded_sketch_topk as _sharded_sketch_topk,
)


def _streaming_auroc_sharded(worker: StreamingAUROC, state: dict, axis_name: Any) -> Array:
    lo, hi = _sharded_sketch_auroc(state["sketch"], axis_name)
    return (lo + hi) / 2.0


def _streaming_ap_sharded(worker: StreamingAveragePrecision, state: dict, axis_name: Any) -> Array:
    lo, hi = _sharded_sketch_ap(state["sketch"], axis_name)
    return (lo + hi) / 2.0


def _streaming_quantile_sharded(worker: StreamingQuantile, state: dict, axis_name: Any) -> Array:
    out = _sharded_sketch_quantile(state["sketch"], jnp.asarray(worker.q), axis_name)
    return out[0] if worker._scalar_q else out


def _streaming_topk_sharded(
    worker: StreamingTopK, state: dict, axis_name: Any
) -> Tuple[Array, Array]:
    ids, counts, _over = _sharded_sketch_topk(state["sketch"], worker.k, axis_name)
    return ids, counts


def _streaming_distinct_sharded(
    worker: StreamingDistinctCount, state: dict, axis_name: Any
) -> Array:
    return _sharded_sketch_distinct(state["sketch"], axis_name)


def _streaming_confusion_sharded(
    worker: StreamingConfusion, state: dict, axis_name: Any
) -> Tuple[Array, Array, Array]:
    rows, cols, counts, _over = _sharded_cooccur_top_cells(state["sketch"], worker.k, axis_name)
    return rows, cols, counts


_register_sharded_compute(StreamingAUROC, _streaming_auroc_sharded)
_register_sharded_compute(StreamingAveragePrecision, _streaming_ap_sharded)
_register_sharded_compute(StreamingQuantile, _streaming_quantile_sharded)
_register_sharded_compute(StreamingTopK, _streaming_topk_sharded)
_register_sharded_compute(StreamingDistinctCount, _streaming_distinct_sharded)
_register_sharded_compute(StreamingConfusion, _streaming_confusion_sharded)
