"""Deterministic, jit-safe hashing primitives for the sketch families.

Every hashed sketch in :mod:`metrics_tpu.streaming` needs the same three
ingredients, and they must be DETERMINISTIC — fixed constants, no PRNG
keys — so that two processes (a client and the root re-folding its
payload, a preemption-resume replay, a mesh permutation) bucket every id
identically and the merge algebra stays bitwise:

* :func:`fmix32` — the murmur3 32-bit finalizer, a full-avalanche
  bijection on ``uint32``. All index/signature derivation starts here.
* :func:`row_hash` / :func:`bucket_index` — per-row keyed hashes for
  depth-``D`` bucketed sketches (:class:`~metrics_tpu.streaming.heavy.
  HeavyHitterSketch`, :class:`~metrics_tpu.streaming.heavy.
  CoOccurrenceSketch`): row ``r`` xors a fixed odd seed into the id
  before finalizing, so rows are pairwise-independent-in-practice but
  reproducible everywhere.
* :func:`bit_planes` / :func:`pack_bits` — the id<->bit-plane codec for
  the linear id-recovery trick (majority vote over exact per-bit mass
  sums, see ``streaming/heavy.py``).

Everything here is pure ``jnp`` integer arithmetic on ``uint32`` (wraps
mod 2^32 by dtype), valid inside ``jit``/``scan``/``vmap``/``shard_map``.
"""
import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

__all__ = [
    "ROW_SEEDS",
    "bit_planes",
    "bucket_index",
    "fmix32",
    "leading_rho",
    "pack_bits",
    "register_index",
    "row_hash",
]

# fixed per-row xor seeds: fmix32(golden-ratio odd multiples) computed once
# in plain Python — depth is capped by this table's length (raise it by
# extending the table; NEVER reorder, existing states depend on it)
_GOLDEN = 0x9E3779B9


def _py_fmix32(x: int) -> int:
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    x ^= x >> 16
    return x


ROW_SEEDS = tuple(_py_fmix32(_GOLDEN * (r + 1)) for r in range(16))


def fmix32(x: Array) -> Array:
    """Murmur3 32-bit finalizer: a deterministic full-avalanche bijection
    on ``uint32`` values (pure jnp, jit-safe)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    return x


def row_hash(ids: Array, row: int) -> Array:
    """The row-``row`` keyed hash of ``ids``: ``fmix32(id ^ seed_row)``."""
    if not 0 <= row < len(ROW_SEEDS):
        raise ValueError(f"row {row} outside the fixed seed table (depth <= {len(ROW_SEEDS)})")
    return fmix32(ids.astype(jnp.uint32) ^ jnp.uint32(ROW_SEEDS[row]))


def bucket_index(ids: Array, row: int, width: int) -> Array:
    """Deterministic bucket of each id in row ``row`` of a width-``width``
    table (int32, in ``[0, width)``)."""
    return (row_hash(ids, row) % jnp.uint32(width)).astype(jnp.int32)


def bit_planes(ids: Array, num_bits: int) -> Array:
    """``float32[..., num_bits]`` bit decomposition of integer ids (LSB
    first) — the per-update votes the linear id-recovery sums."""
    shifts = jnp.arange(num_bits, dtype=jnp.uint32)
    return ((ids.astype(jnp.uint32)[..., None] >> shifts) & jnp.uint32(1)).astype(jnp.float32)


def pack_bits(bits: Array) -> Array:
    """Inverse of :func:`bit_planes`: pack a ``bool/float[..., B]`` plane
    stack (LSB first) back into ``uint32`` ids."""
    num_bits = bits.shape[-1]
    shifts = jnp.arange(num_bits, dtype=jnp.uint32)
    return (bits.astype(jnp.uint32) << shifts).sum(axis=-1).astype(jnp.uint32)


def leading_rho(hashes: Array, precision_bits: int) -> Array:
    """HLL rank: position of the leftmost 1-bit in the ``32 - p`` hash
    bits BELOW the register-index bits, counted from 1; ``32 - p + 1``
    when they are all zero. ``int32``, in ``[1, 33 - p]``."""
    p = int(precision_bits)
    tail_bits = 32 - p
    tail = hashes.astype(jnp.uint32) & jnp.uint32((1 << tail_bits) - 1)
    shifted = tail << jnp.uint32(p)  # tail promoted to the high bits
    rho = lax.clz(shifted).astype(jnp.int32) + 1
    return jnp.where(tail == 0, jnp.int32(tail_bits + 1), rho)


def register_index(hashes: Array, precision_bits: int) -> Array:
    """HLL register index: the TOP ``p`` hash bits (int32, ``[0, 2^p)``)."""
    return (hashes.astype(jnp.uint32) >> jnp.uint32(32 - int(precision_bits))).astype(jnp.int32)
