"""Time-windowed and exponentially-decayed metric wrappers.

The epoch-oriented ``update/compute/reset`` lifecycle answers "what is the
metric over everything since the last reset" — always-on monitoring needs
"what is the metric over the last hour" (:class:`WindowedMetric`) and
"what is the metric now, with the past fading" (:class:`DecayedMetric`).
Both wrap any **merge-combinable** metric (every state sum/max/min- or
sketch-reducible — the property ``make_epoch``'s fused path and the DDP
gather-reduce sync already rely on) and stay ordinary
:class:`~metrics_tpu.metric.Metric` subclasses: ``MetricCollection``
membership, mesh sync (per-slot elementwise), and
:class:`metrics_tpu.ft.CheckpointManager` round-trips (the ring position
rides ``_aux_attrs``) all work unchanged.

* :class:`WindowedMetric` — a ring of ``window`` state shards. Each
  ``update`` folds into the current shard; :meth:`~WindowedMetric.advance`
  (or every ``updates_per_slot`` updates) rotates the ring and **expires**
  the oldest shard by resetting it to the state default — the
  expire-and-refold that an accumulated monoid state cannot express
  (you cannot subtract a max). ``compute`` refolds the live shards and
  runs the base metric's math.
* :class:`DecayedMetric` — exponential time decay applied *inside* the
  fold: ``state <- decay * state + batch_state`` with
  ``decay = 0.5 ** (1 / half_life)``, so every value is a half-life-
  weighted EWMA of the stream. Requires sum-combinable states (counts are
  linear; a max cannot fade).

For the jit/scan-native path — fold a batch and emit the current window
value in ONE launch — see :func:`metrics_tpu.steps.make_stream_step`.
"""
from copy import deepcopy
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.streaming.sketches import Sketch
from metrics_tpu.utilities.buffers import CapacityBuffer
from metrics_tpu.utilities.data import coerce_foreign_tensors

Array = jax.Array

__all__ = ["DecayedMetric", "WindowedMetric"]

_WINDOW_REDUCTIONS = ("sum", "max", "min", "sketch")
_DECAY_REDUCTIONS = ("sum", "sketch")


def _check_streamable(metric: Metric, allowed: Tuple[str, ...], wrapper: str) -> Dict[str, str]:
    """Validate the base metric's states are combinable under ``allowed``
    reductions; returns ``{state_name: reduction}``."""
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.wrappers.abstract import WrapperMetric

    if isinstance(metric, MetricCollection):
        raise ValueError(f"{wrapper} wraps a single Metric; wrap each collection member instead")
    if isinstance(metric, WrapperMetric):
        raise ValueError(f"{wrapper} cannot wrap wrapper metrics; wrap the base metric directly")
    if not isinstance(metric, Metric):
        raise ValueError(f"{wrapper} expects a Metric instance, got {type(metric).__name__}")
    if not metric._defaults:
        raise ValueError(f"{wrapper} base metric {type(metric).__name__} declares no states")
    reductions: Dict[str, str] = {}
    for name, red in metric._reductions.items():
        default = metric._defaults[name]
        if isinstance(default, (list, CapacityBuffer)) or red not in allowed:
            raise ValueError(
                f"{wrapper} needs every state of {type(metric).__name__} to be"
                f" {'/'.join(allowed)}-combinable, but state {name!r} has"
                f" dist_reduce_fx={red!r} (default type {type(default).__name__})."
                " Sample-buffer and cat-list states cannot be expired or decayed;"
                " use a sketch-backed streaming metric (metrics_tpu.streaming) as the base."
            )
        reductions[name] = red
    return reductions


def _merge_state(red: str, acc: Any, new: Any) -> Any:
    # the steps.py registry is THE definition of merge-combination; the
    # eager wrappers and the jitted make_stream_step path must share it or
    # their pinned bitwise parity could silently diverge
    from metrics_tpu.steps import _MERGE_OPS

    return _MERGE_OPS[red](acc, new)


def _fold_axis0(red: str, value: Any) -> Any:
    from metrics_tpu.steps import _FOLD_OPS

    return _FOLD_OPS[red](value)


class _StreamWrapper(Metric):
    """Shared plumbing: a worker clone of the base metric builds batch
    contributions and runs ``compute`` over the refolded state."""

    def __init__(self, base_metric: Metric, allowed: Tuple[str, ...], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._base_reductions = _check_streamable(base_metric, allowed, type(self).__name__)
        template = base_metric.clone()
        template.reset()
        self._worker = template

    def _batch_state(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        w = self._worker
        w.reset()
        w.update(*args, **kwargs)
        return w.state_pytree()

    def _compute_from(self, state: Dict[str, Any]) -> Any:
        w = self._worker
        w.reset()
        w.load_state_pytree(state)
        # our own compute wrapper already synced THIS metric's states
        # across processes (per-slot / decayed elementwise) — the base math
        # must not re-sync
        w._to_sync = False
        w._computed = None
        w._update_count = max(1, self._update_count)
        return w.compute()

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Fold the batch AND return its batch-local base-metric value."""
        args = coerce_foreign_tensors(args)
        kwargs = coerce_foreign_tensors(kwargs)
        self.update(*args, **kwargs)
        w = self._worker
        w.reset()
        w.update(*args, **kwargs)
        w._to_sync = self.dist_sync_on_step
        w._computed = None
        w._update_count = 1
        self._forward_cache = w.compute()
        return self._forward_cache


class WindowedMetric(_StreamWrapper):
    """Sliding-window metric: a ring of ``window`` expirable state shards.

    Args:
        base_metric: any merge-combinable metric (all states
            sum/max/min/sketch-reducible) — e.g. ``Accuracy``,
            ``MeanSquaredError``, ``StreamingAUROC``.
        window: number of ring shards ``K``. ``compute()`` covers the
            current shard plus the ``K - 1`` most recent expired-into ones.
        updates_per_slot: rotate the ring automatically after this many
            updates per shard (the window then spans between
            ``(K-1)*u + 1`` and ``K*u`` most recent updates). ``None``
            disables auto-rotation; call :meth:`advance` at your own
            boundaries (e.g. wall-clock minutes).

    Every rotation that clears a previously-written shard bumps the
    ``stream.windows_expired`` obs counter.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.streaming import WindowedMetric
        >>> w = WindowedMetric(Accuracy(), window=2, updates_per_slot=1)
        >>> w.update(jnp.asarray([1, 1, 1, 1]), jnp.asarray([1, 1, 1, 1]))
        >>> w.update(jnp.asarray([0, 0, 0, 0]), jnp.asarray([1, 1, 1, 1]))
        >>> float(w.compute())  # both shards in the window
        0.5
        >>> w.update(jnp.asarray([0, 0, 0, 0]), jnp.asarray([1, 1, 1, 1]))
        >>> float(w.compute())  # the all-correct shard has expired
        0.0
    """

    full_state_update = False
    _aux_attrs = ("_pos", "_in_slot", "_slot_filled")

    def __init__(
        self,
        base_metric: Metric,
        window: int,
        updates_per_slot: Optional[int] = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__(base_metric, _WINDOW_REDUCTIONS, **kwargs)
        if window < 1:
            raise ValueError(f"`window` must be positive, got {window}")
        if updates_per_slot is not None and updates_per_slot < 1:
            raise ValueError(f"`updates_per_slot` must be positive or None, got {updates_per_slot}")
        self.window = int(window)
        self.updates_per_slot = None if updates_per_slot is None else int(updates_per_slot)
        self._pos = 0
        self._in_slot = 0
        self._slot_filled = [0] * self.window
        for name, red in self._base_reductions.items():
            default = self._worker._defaults[name]
            if isinstance(default, Sketch):
                stacked = default.stack(self.window)
            else:
                stacked = jnp.broadcast_to(default[None], (self.window,) + jnp.shape(default))
            self.add_state(name, default=stacked, dist_reduce_fx=red)
        self._slot_defaults = {name: deepcopy(self._worker._defaults[name]) for name in self._base_reductions}

    def update(self, *args: Any, **kwargs: Any) -> None:
        # rotate LAZILY before the fold (not eagerly after it): the window
        # right after N updates then spans exactly the most recent
        # min(N, window * updates_per_slot) of them, with no empty current
        # shard diluting it
        if self.updates_per_slot is not None and self._in_slot >= self.updates_per_slot:
            self.advance()
        batch = self._batch_state(*args, **kwargs)
        pos = self._pos
        for name, red in self._base_reductions.items():
            stacked = getattr(self, name)
            if red == "sketch":
                setattr(self, name, stacked.merge_into_slot(pos, batch[name]))
            else:
                merged = _merge_state(red, stacked[pos], batch[name])
                setattr(self, name, stacked.at[pos].set(merged.astype(stacked.dtype)))
        self._slot_filled[pos] = 1
        self._in_slot += 1

    def advance(self) -> None:
        """Rotate the ring: the oldest shard is expired (reset to the state
        default) and becomes the new current shard."""
        next_pos = (self._pos + 1) % self.window
        if self._slot_filled[next_pos] and _obs_enabled():
            _obs_inc("stream.windows_expired", metric=type(self._worker).__name__)
        for name, red in self._base_reductions.items():
            stacked = getattr(self, name)
            default = self._slot_defaults[name]
            if red == "sketch":
                setattr(self, name, stacked.set_slot(next_pos, default))
            else:
                setattr(self, name, stacked.at[next_pos].set(default.astype(stacked.dtype)))
        self._slot_filled[next_pos] = 0
        self._pos = next_pos
        self._in_slot = 0
        self._computed = None

    def compute(self) -> Any:
        folded = {
            name: _fold_axis0(red, getattr(self, name)) for name, red in self._base_reductions.items()
        }
        return self._compute_from(folded)

    def _reset_impl(self) -> None:
        super()._reset_impl()
        self._pos = 0
        self._in_slot = 0
        self._slot_filled = [0] * self.window

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}({type(self._worker).__name__}, window={self.window},"
            f" updates_per_slot={self.updates_per_slot})"
        )


class DecayedMetric(_StreamWrapper):
    """Exponentially-decayed metric: the past fades with a half-life.

    Each update scales the accumulated state by
    ``decay = 0.5 ** (1 / half_life)`` before merging the batch
    contribution, so a batch folded ``half_life`` updates ago carries half
    the weight of the current one — an EWMA over the stream with an
    effective window of ``1 / (1 - decay)`` updates. Requires
    sum-combinable states (counts and sketch counts are linear under
    scaling; a max cannot fade — :class:`WindowedMetric` covers those).
    Sketch min/max leaves are left undecayed: they remain all-time
    extremes, which only the unbounded edge bins of a quantile envelope
    ever consult.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.streaming import DecayedMetric
        >>> d = DecayedMetric(Accuracy(), half_life=1.0)
        >>> d.update(jnp.asarray([0, 0, 0, 0]), jnp.asarray([1, 1, 1, 1]))
        >>> d.update(jnp.asarray([1, 1, 1, 1]), jnp.asarray([1, 1, 1, 1]))
        >>> float(jnp.round(d.compute(), 4))  # recent all-correct weighs 2x
        0.6667
    """

    full_state_update = False

    def __init__(self, base_metric: Metric, half_life: float, **kwargs: Any) -> None:
        super().__init__(base_metric, _DECAY_REDUCTIONS, **kwargs)
        if not half_life > 0:
            raise ValueError(f"`half_life` must be positive, got {half_life}")
        self.half_life = float(half_life)
        self.decay = float(0.5 ** (1.0 / self.half_life))
        for name, red in self._base_reductions.items():
            default = deepcopy(self._worker._defaults[name])
            if not isinstance(default, Sketch) and not jnp.issubdtype(default.dtype, jnp.floating):
                # decayed counts are fractional; int states go float up front
                # (strict-promotion clean: no int*float mixing in update)
                default = default.astype(jnp.float32)
            self.add_state(name, default=default, dist_reduce_fx=red)

    @property
    def effective_window(self) -> float:
        """Total weight of an infinite stream: ``1 / (1 - decay)`` updates."""
        return 1.0 / (1.0 - self.decay)

    def update(self, *args: Any, **kwargs: Any) -> None:
        batch = self._batch_state(*args, **kwargs)
        for name, red in self._base_reductions.items():
            acc = getattr(self, name)
            if red == "sketch":
                setattr(self, name, acc.scale_sum_leaves(jnp.asarray(self.decay, jnp.float32)).merge(batch[name]))
            else:
                decay = jnp.asarray(self.decay, acc.dtype)
                setattr(self, name, acc * decay + batch[name].astype(acc.dtype))

    def compute(self) -> Any:
        return self._compute_from({name: getattr(self, name) for name in self._base_reductions})

    def __repr__(self) -> str:
        return f"{type(self).__name__}({type(self._worker).__name__}, half_life={self.half_life})"
