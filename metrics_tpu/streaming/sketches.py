"""Mergeable, bounded-memory sketch states for always-on online monitoring.

The exact curve metrics answer unbounded streams with host offload
(``compute_on_cpu``) or a capped HBM buffer (``CapacityBuffer``) — both
keep *samples*, so memory is O(N) or the tail is lost. A **sketch** keeps a
fixed-size *summary* instead: device state is a few KB regardless of how
many samples streamed through, and accuracy degrades gracefully with a
documented, *computable* error bound.

Two sketches, one contract:

* :class:`QuantileSketch` — a bounded-memory rank/quantile summary in the
  KLL tradition (fixed space, documented rank error), realized as a
  fixed-resolution binned histogram plus exact min/max tracking. Where KLL
  buys adaptivity with randomized compaction, this design buys an **exactly
  associative and commutative merge** (counts add, extremes min/max) — the
  property that lets states fold under ``lax.scan``, merge order-invariantly
  across mesh shards, and replay-merge bitwise after a preemption resume.
* :class:`ScoreLabelSketch` — per-bin positive/negative label histograms
  over scores in [0, 1], the sufficient statistic for binned ROC / PR
  analysis. Backs :class:`~metrics_tpu.streaming.metrics.StreamingAUROC`
  and :class:`~metrics_tpu.streaming.metrics.StreamingAveragePrecision`
  with envelope bounds: the sketch knows which *bin* every sample landed
  in but not the within-bin order, so it computes the attainable interval
  over all orderings and returns its midpoint — the half-width IS the
  error bound (``tests/streaming`` pins it at 1M samples).

Every sketch is a **registered jax pytree with static aux config**: it is a
valid ``jit``/``scan``/``vmap`` carry, its leaves ride ``shard_map``
collectives (each leaf declares sum/min/max), and it serializes through
``metrics_tpu.utilities.checkpoint`` / :class:`metrics_tpu.ft.CheckpointManager`
unchanged. Merges are closed under the sketch algebra:

    ``merge`` is associative + commutative; a fresh sketch is the identity.

which is exactly the contract ``dist_reduce_fx="sketch"`` states rely on
(see ``metrics_tpu.metric.Metric.add_state``).
"""
import functools
import json
from typing import Any, Dict, Optional, Sequence, Tuple, Type, Union

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = [
    "QuantileSketch",
    "ScoreLabelSketch",
    "Sketch",
    "delta_envelope_leaf",
    "sketch_from_pack_tree",
]

# class registry for checkpoint round-trips (utilities/checkpoint._unpack)
_SKETCH_REGISTRY: Dict[str, Type["Sketch"]] = {}


class Sketch:
    """Base class: static-config, array-leaf summaries with a monoid merge.

    Subclasses declare

    * ``_leaf_fields`` — ordered ``(name, reduction)`` pairs; ``reduction``
      in ``{"sum", "min", "max"}`` is both the merge op of :meth:`merge`
      and the mesh collective the state syncs with
      (:func:`metrics_tpu.utilities.distributed.sync_sketch_in_context`).
    * ``_config_fields`` — static Python aux (bin counts, ranges); two
      sketches merge only when their configs are equal.
    * ``_shard_dims`` — the declarative sharding spec: ``{leaf_name: dim}``
      naming which dimension of a leaf distributes over a mesh axis.
      Consumed by :func:`metrics_tpu.utilities.sharding.state_named_shardings`
      (the pjit layout) and
      :func:`~metrics_tpu.utilities.sharding.shard_sketch_in_context` (the
      reduce-scatter sync that leaves each device holding its bin slice
      instead of a full merged replica). Leaves absent from the mapping
      (extremes, scalars) stay replicated.
    * ``_delta_envelope_leaves`` — the names of min/max leaves that are
      cumulative ENVELOPE bounds (a quantile sketch's running
      ``minv``/``maxv``): over a history interval delta they may be
      carried from the newer snapshot and stay a valid bound for the
      interval. min/max leaves NOT named here (HLL max-registers, whose
      carried value would silently answer "uniques ever" to a "uniques
      this interval" query) make interval deltas refuse with
      :class:`~metrics_tpu.serve.history.DeltaUndefinedError` — consult
      via :func:`delta_envelope_leaf`.

    The flatten/unflatten protocol intentionally accepts leaves of any
    shape: ``vmap``/``make_epoch`` stack a leading batch axis onto every
    leaf and fold it back down with :meth:`reduce_leading_axis`.
    """

    _leaf_fields: Tuple[Tuple[str, str], ...] = ()
    _config_fields: Tuple[str, ...] = ()
    _shard_dims: Dict[str, int] = {}
    _delta_envelope_leaves: Tuple[str, ...] = ()

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        _SKETCH_REGISTRY[cls.__name__] = cls
        jax.tree_util.register_pytree_node_class(cls)

    # -- pytree protocol -------------------------------------------------

    def tree_flatten(self) -> Tuple[tuple, tuple]:
        children = tuple(getattr(self, name) for name, _ in self._leaf_fields)
        aux = tuple(getattr(self, name) for name in self._config_fields)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux: tuple, children: tuple) -> "Sketch":
        new = cls.__new__(cls)
        for name, value in zip(cls._config_fields, aux):
            object.__setattr__(new, name, value)
        for (name, _), child in zip(cls._leaf_fields, children):
            object.__setattr__(new, name, child)
        return new

    # -- config / identity ----------------------------------------------

    def config(self) -> Dict[str, Any]:
        """The static configuration (merge compatibility key)."""
        return {name: getattr(self, name) for name in self._config_fields}

    def _check_mergeable(self, other: "Sketch") -> None:
        if type(other) is not type(self):
            raise ValueError(f"cannot merge {type(self).__name__} with {type(other).__name__}")
        if other.config() != self.config():
            raise ValueError(
                f"cannot merge {type(self).__name__} sketches with different configs:"
                f" {self.config()} vs {other.config()}"
            )

    def _replace_leaves(self, **leaves: Any) -> "Sketch":
        children = tuple(leaves.get(name, getattr(self, name)) for name, _ in self._leaf_fields)
        return type(self).tree_unflatten(tuple(getattr(self, n) for n in self._config_fields), children)

    # -- merge algebra ---------------------------------------------------

    def merge(self, other: "Sketch") -> "Sketch":
        """Combine two summaries; associative, commutative, identity = a
        fresh sketch of the same config. Jit-safe (pure leaf arithmetic)."""
        self._check_mergeable(other)
        out = {}
        for name, red in self._leaf_fields:
            a, b = getattr(self, name), getattr(other, name)
            if red == "sum":
                out[name] = a + b
            elif red == "min":
                out[name] = jnp.minimum(a, b)
            else:
                out[name] = jnp.maximum(a, b)
        return self._replace_leaves(**out)

    def stack(self, k: int) -> "Sketch":
        """Broadcast every leaf to a leading replicate axis of size ``k``
        (a ring of ``k`` identity slots — see ``streaming/windows.py``)."""
        return self._replace_leaves(
            **{
                name: jnp.broadcast_to(getattr(self, name)[None], (k,) + jnp.shape(getattr(self, name)))
                for name, _ in self._leaf_fields
            }
        )

    def reduce_leading_axis(self) -> "Sketch":
        """Fold a stacked sketch (leaves ``(k, *shape)``) back down axis 0
        with each leaf's declared reduction — the merge of all ``k`` slots."""
        out = {}
        for name, red in self._leaf_fields:
            leaf = getattr(self, name)
            out[name] = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[red](leaf, axis=0)
        return self._replace_leaves(**out)

    def slot(self, index: Union[int, Array]) -> "Sketch":
        """Row ``index`` of a stacked sketch (dynamic index allowed)."""
        return self._replace_leaves(
            **{
                name: jax.lax.dynamic_index_in_dim(getattr(self, name), index, keepdims=False)
                for name, _ in self._leaf_fields
            }
        )

    def set_slot(self, index: Union[int, Array], row: "Sketch") -> "Sketch":
        """A stacked sketch with row ``index`` replaced by ``row``."""
        self._check_mergeable(row)
        return self._replace_leaves(
            **{
                name: jax.lax.dynamic_update_index_in_dim(
                    getattr(self, name), getattr(row, name).astype(getattr(self, name).dtype), index, 0
                )
                for name, _ in self._leaf_fields
            }
        )

    def merge_into_slot(self, index: Union[int, Array], batch: "Sketch") -> "Sketch":
        """Merge ``batch`` into row ``index`` of a stacked sketch."""
        return self.set_slot(index, self.slot(index).merge(batch))

    def scale_sum_leaves(self, factor: Union[float, Array]) -> "Sketch":
        """Exponential decay primitive: scale every ``sum`` leaf by
        ``factor`` (counts are linear, so a decayed sketch is still a valid
        weighted summary); ``min``/``max`` leaves pass through untouched —
        they remain all-time extremes (see ``DecayedMetric``)."""
        out = {}
        for name, red in self._leaf_fields:
            leaf = getattr(self, name)
            out[name] = leaf * factor if red == "sum" else leaf
        return self._replace_leaves(**out)

    # -- introspection ---------------------------------------------------

    @property
    def nbytes(self) -> int:
        """Device bytes of the summary (shape/dtype metadata only)."""
        total = 0
        for name, _ in self._leaf_fields:
            leaf = getattr(self, name)
            total += int(jnp.size(leaf)) * jnp.asarray(leaf).dtype.itemsize if hasattr(leaf, "dtype") else 0
        return total

    def bin_masses(self) -> Array:
        """Normalized per-bin probability masses (drift-monitor input)."""
        raise NotImplementedError

    # -- checkpoint packing (utilities/checkpoint._pack/_unpack) ---------

    def to_pack_tree(self) -> Dict[str, Any]:
        packed: Dict[str, Any] = {
            "__sketch_meta": jnp.frombuffer(
                json.dumps({"class": type(self).__name__, "config": self.config()}).encode(),
                dtype=jnp.uint8,
            )
        }
        for name, _ in self._leaf_fields:
            packed[f"__sketch_leaf_{name}"] = getattr(self, name)
        return packed

    def __repr__(self) -> str:
        cfg = ", ".join(f"{k}={v}" for k, v in self.config().items())
        return f"{type(self).__name__}({cfg})"


def sketch_from_pack_tree(tree: Dict[str, Any]) -> Sketch:
    """Rebuild a sketch from :meth:`Sketch.to_pack_tree` output (checkpoint
    restore path; leaves may arrive as numpy arrays from orbax)."""
    import numpy as np

    meta = json.loads(bytes(np.asarray(tree["__sketch_meta"]).astype(np.uint8)).decode())
    cls = _SKETCH_REGISTRY[meta["class"]]
    new = cls(**meta["config"])
    leaves = {
        name: jnp.asarray(tree[f"__sketch_leaf_{name}"]).astype(getattr(new, name).dtype)
        for name, _ in cls._leaf_fields
    }
    return new._replace_leaves(**leaves)


class QuantileSketch(Sketch):
    """Bounded-memory quantile summary with an exactly-mergeable state.

    A fixed grid of ``num_bins`` equal-width bins over ``[lo, hi]`` plus an
    underflow and an overflow bin and exact min/max tracking — ``4 *
    (num_bins + 2) + 8`` bytes of device state no matter how many
    samples fold through. KLL-style in its guarantee (fixed space, bounded
    rank error); unlike randomized KLL compaction the merge is **bitwise
    associative and commutative** (integer-valued count sums + extreme
    min/max), so fold order — scan carries, mesh shards, windowed-slot
    refolds, preemption-resume replays — can never change the state.

    Error bound (documented + computable): a quantile query returns the
    MIDPOINT of the [clipped] edges of the bin holding the target rank —
    the true value lies within those edges, so :meth:`quantile_bounds`'
    half-width bounds the value error; it is at most
    ``(hi - lo) / (2 * num_bins)`` for data inside ``[lo, hi]``. Mass
    outside the range is tracked in the unbounded under/overflow bins
    whose edges are the exact running min/max.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import QuantileSketch
        >>> sk = QuantileSketch(num_bins=100, lo=0.0, hi=1.0)
        >>> sk = sk.fold(jnp.linspace(0.0, 1.0, 1001))
        >>> float(jnp.round(sk.quantile(0.5), 3))  # exact median 0.5, bound 0.005
        0.505
    """

    _leaf_fields = (("counts", "sum"), ("minv", "min"), ("maxv", "max"))
    _config_fields = ("num_bins", "lo", "hi")
    _delta_envelope_leaves = ("minv", "maxv")
    # bins distribute over the mesh; the exact min/max scalars replicate
    _shard_dims = {"counts": 0}

    def __init__(self, num_bins: int = 1024, lo: float = 0.0, hi: float = 1.0) -> None:
        if num_bins < 1:
            raise ValueError(f"`num_bins` must be positive, got {num_bins}")
        if not hi > lo:
            raise ValueError(f"need hi > lo, got [{lo}, {hi}]")
        self.num_bins = int(num_bins)
        self.lo = float(lo)
        self.hi = float(hi)
        self.counts = jnp.zeros(self.num_bins + 2, dtype=jnp.float32)
        self.minv = jnp.asarray(jnp.inf, dtype=jnp.float32)
        self.maxv = jnp.asarray(-jnp.inf, dtype=jnp.float32)

    # -- accumulation ----------------------------------------------------

    def fold(self, values: Array, weights: Optional[Array] = None) -> "QuantileSketch":
        """A new sketch with ``values`` (optionally ``weights``-weighted)
        folded in. Pure and jit-safe: one scatter-add plus two extremes."""
        values = jnp.ravel(jnp.asarray(values)).astype(jnp.float32)
        width = (self.hi - self.lo) / self.num_bins
        idx = jnp.floor((values - self.lo) / width).astype(jnp.int32)
        # bin 0 = underflow (-inf, lo); 1..num_bins = grid; num_bins+1 = overflow [hi, inf)
        idx = jnp.clip(idx + 1, 0, self.num_bins + 1)
        w = (
            jnp.ones_like(values)
            if weights is None
            else jnp.ravel(jnp.asarray(weights)).astype(jnp.float32)
        )
        counts = self.counts.at[idx].add(w)
        return self._replace_leaves(
            counts=counts,
            minv=jnp.minimum(self.minv, values.min(initial=jnp.inf)),
            maxv=jnp.maximum(self.maxv, values.max(initial=-jnp.inf)),
        )

    # -- queries ---------------------------------------------------------

    @property
    def count(self) -> Array:
        """Total folded weight."""
        return self.counts.sum()

    def _bin_edges(self) -> Tuple[Array, Array]:
        """Per-bin (lower, upper) value edges, clipped to the observed
        [min, max] so empty range never widens the envelope."""
        width = (self.hi - self.lo) / self.num_bins
        grid = self.lo + width * jnp.arange(self.num_bins + 1, dtype=jnp.float32)
        lower = jnp.concatenate([jnp.asarray([-jnp.inf], jnp.float32), grid])
        upper = jnp.concatenate([grid, jnp.asarray([jnp.inf], jnp.float32)])
        lower = jnp.clip(lower, self.minv, self.maxv)
        upper = jnp.clip(upper, self.minv, self.maxv)
        return lower, upper

    def quantile_bounds(self, q: Union[float, Sequence[float], Array]) -> Tuple[Array, Array]:
        """Rigorous (lower, upper) envelope for quantile(s) ``q``: the
        [clipped] edges of the bin holding the target rank. The true
        quantile of the folded stream lies inside; half the width is the
        value error of :meth:`quantile`."""
        q = jnp.atleast_1d(jnp.asarray(q, jnp.float32))
        lower, upper = self._bin_edges()
        cum = jnp.cumsum(self.counts)
        total = cum[-1]
        rank = jnp.clip(q, 0.0, 1.0) * total
        # first bin whose cumulative mass reaches the rank AND is non-empty
        idx = jnp.searchsorted(cum, jnp.maximum(rank, jnp.finfo(jnp.float32).tiny), side="left")
        idx = jnp.clip(idx, 0, self.num_bins + 1)
        lo, hi = lower[idx], upper[idx]
        # the extremes are tracked EXACTLY: q=0/q=1 envelopes collapse to a point
        lo = jnp.where(q <= 0.0, self.minv, jnp.where(q >= 1.0, self.maxv, lo))
        hi = jnp.where(q <= 0.0, self.minv, jnp.where(q >= 1.0, self.maxv, hi))
        return lo, hi

    def quantile(self, q: Union[float, Sequence[float], Array]) -> Array:
        """Approximate quantile(s): the MIDPOINT of the rigorous envelope
        (scalar in -> scalar out). Midpoint, not rank interpolation: the
        exact quantile can sit anywhere inside its bin regardless of the
        rank's position within the bin's mass (all that mass may be one
        repeated value at an edge), so only the midpoint honors the
        ``|quantile(q) - exact| <= half-width`` contract of
        :meth:`quantile_bounds`."""
        q_arr = jnp.atleast_1d(jnp.asarray(q, jnp.float32))
        lower, upper = self.quantile_bounds(q_arr)
        total = self.counts.sum()
        out = jnp.where(total > 0, (lower + upper) / 2.0, jnp.nan)
        return out[0] if jnp.ndim(q) == 0 else out

    def bin_masses(self) -> Array:
        """Normalized per-bin masses (``num_bins + 2`` incl. under/overflow)."""
        total = self.counts.sum()
        return self.counts / jnp.maximum(total, 1.0)


class ScoreLabelSketch(Sketch):
    """Per-bin positive/negative score histograms: the binned sufficient
    statistic for ROC / PR curve metrics over scores in ``[0, 1]``.

    State is two ``(num_bins,)`` count vectors (positives / negatives per
    score bin) — ``8 * num_bins`` bytes regardless of stream length; the
    default 2048 bins is 16 KB. Counts are integer-valued float32 (exact
    to 2^24), so merges are bitwise associative/commutative and mesh
    merges are plain ``psum``.

    Accumulation reuses the fused threshold-binning kernel
    (:func:`metrics_tpu.ops.binned_counts.binned_counts` — one HBM read of
    preds/target on TPU) when the backend and bin count suit it, and an
    O(N) scatter-add bincount elsewhere; both produce identical counts.

    Curve values come with **envelope bounds**: scores are ordered across
    bins but unordered within one, so the sketch computes the attainable
    interval over every within-bin ordering and returns its midpoint
    (:meth:`auroc`, :meth:`average_precision`); the half-width — e.g.
    ``sum_b P_b * N_b / (2 * P * N)`` for AUROC — is the documented error
    bound (:meth:`auroc_bounds`, :meth:`average_precision_bounds`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import ScoreLabelSketch
        >>> sk = ScoreLabelSketch(num_bins=64)
        >>> sk = sk.fold(jnp.asarray([0.1, 0.8, 0.4, 0.9]), jnp.asarray([0, 1, 0, 1]))
        >>> float(sk.auroc())
        1.0
    """

    _leaf_fields = (("pos", "sum"), ("neg", "sum"))
    _config_fields = ("num_bins",)
    # both label histograms distribute bin-wise over the mesh
    _shard_dims = {"pos": 0, "neg": 0}

    def __init__(self, num_bins: int = 2048) -> None:
        if num_bins < 2:
            raise ValueError(f"`num_bins` must be >= 2, got {num_bins}")
        self.num_bins = int(num_bins)
        self.pos = jnp.zeros(self.num_bins, dtype=jnp.float32)
        self.neg = jnp.zeros(self.num_bins, dtype=jnp.float32)

    # -- accumulation ----------------------------------------------------

    def fold(self, preds: Array, target: Array) -> "ScoreLabelSketch":
        """A new sketch with a batch of ``(score in [0,1], binary label)``
        pairs folded in (scores are clipped into range). Pure, jit-safe."""
        preds = jnp.ravel(jnp.asarray(preds)).astype(jnp.float32)
        target = jnp.ravel(jnp.asarray(target)).astype(jnp.int32) == 1
        if jax.default_backend() == "tpu" and self.num_bins <= 256:
            pos_hist, neg_hist = self._hists_via_kernel(preds, target)
        else:
            pos_hist, neg_hist = self._hists_via_bincount(preds, target)
        return self._replace_leaves(pos=self.pos + pos_hist, neg=self.neg + neg_hist)

    def _hists_via_bincount(self, preds: Array, target: Array) -> Tuple[Array, Array]:
        # bin by searchsorted against the SAME float32 `k/T` thresholds the
        # kernel arm compares with — `int(v * T)` truncation disagrees with
        # `v >= k/T` on boundary scores whenever k/T is inexact in f32
        # (e.g. T=100, v=float32(0.53)), and the two arms must produce
        # identical counts or a TPU-folded and a CPU-folded sketch of the
        # same stream would diverge (pinned by test_fold_arms_agree)
        thresholds = jnp.arange(self.num_bins, dtype=jnp.float32) / self.num_bins
        idx = jnp.clip(
            jnp.searchsorted(thresholds, preds, side="right").astype(jnp.int32) - 1,
            0,
            self.num_bins - 1,
        )
        t = target.astype(jnp.float32)
        pos_hist = jnp.zeros(self.num_bins, jnp.float32).at[idx].add(t)
        neg_hist = jnp.zeros(self.num_bins, jnp.float32).at[idx].add(1.0 - t)
        return pos_hist, neg_hist

    def _hists_via_kernel(self, preds: Array, target: Array) -> Tuple[Array, Array]:
        # one HBM read of preds/target through the fused pallas threshold
        # kernel; the cumulative->per-bin translation lives beside the
        # kernel (bin k = [k/T, (k+1)/T), last bin closed at 1.0 — matching
        # the bincount clip)
        from metrics_tpu.ops.binned_counts import binned_label_histograms

        return binned_label_histograms(preds, target.astype(jnp.int32), self.num_bins)

    # -- queries ---------------------------------------------------------

    @property
    def count(self) -> Array:
        return self.pos.sum() + self.neg.sum()

    def curve_counts(self) -> Tuple[Array, Array]:
        """Cumulative ``(TP, FP)`` at each bin's lower edge, descending
        through score bins — the binned ROC curve's support points."""
        tp = jnp.cumsum(self.pos[::-1])[::-1]
        fp = jnp.cumsum(self.neg[::-1])[::-1]
        return tp, fp

    def auroc_bounds(self) -> Tuple[Array, Array]:
        """Rigorous (lower, upper) AUROC envelope over every within-bin
        ordering: a (pos, neg) pair in different bins is ordered identically
        under all of them; a same-bin pair contributes anywhere in [0, 1]."""
        p_total = self.pos.sum()
        n_total = self.neg.sum()
        pn = jnp.maximum(p_total * n_total, 1.0)
        # positives strictly above each bin
        pos_above = jnp.concatenate([jnp.cumsum(self.pos[::-1])[::-1][1:], jnp.zeros((1,), jnp.float32)])
        cross = (self.neg * pos_above).sum()  # pairs ordered correctly in every interleaving
        same = (self.neg * self.pos).sum()  # same-bin pairs: [0, 1] each
        lo = jnp.where(p_total * n_total > 0, cross / pn, jnp.nan)
        hi = jnp.where(p_total * n_total > 0, (cross + same) / pn, jnp.nan)
        return lo, hi

    def auroc(self) -> Array:
        """Binned AUROC: the envelope midpoint (== trapezoidal area under
        the binned ROC curve; same-bin pairs count 1/2, the tie
        convention of exact AUROC)."""
        lo, hi = self.auroc_bounds()
        return (lo + hi) / 2.0

    def auroc_error_bound(self) -> Array:
        """``sum_b P_b * N_b / (2 * P * N)`` — the half-width of
        :meth:`auroc_bounds`; ``|auroc() - exact| <= this`` always."""
        lo, hi = self.auroc_bounds()
        return (hi - lo) / 2.0

    def average_precision_bounds(self) -> Tuple[Array, Array]:
        """Rigorous (lower, upper) envelope for average precision.

        Within bin ``b`` (``p`` positives, ``n`` negatives, ``Pa``/``Na``
        positives/negatives in strictly-higher bins), the ``j``-th bin
        positive's precision is a concave increasing function of ``j``
        bounded by the all-positives-first and all-negatives-first
        orderings; Jensen (upper) and the chord inequality (lower) turn
        the per-positive sums into closed forms. Exact AP of the stream —
        any within-bin ordering — lies inside the interval.
        """
        p, n = self.pos, self.neg
        p_total = jnp.maximum(p.sum(), 1.0)
        pos_above = jnp.concatenate([jnp.cumsum(p[::-1])[::-1][1:], jnp.zeros((1,), jnp.float32)])
        neg_above = jnp.concatenate([jnp.cumsum(n[::-1])[::-1][1:], jnp.zeros((1,), jnp.float32)])
        has = p > 0
        safe_p = jnp.where(has, p, 1.0)
        # upper: positives first; f(j) = (Pa+j)/(Pa+Na+j) concave increasing,
        # so sum_{j=1..p} f(j) <= p * f((p+1)/2)
        j_mid = (safe_p + 1.0) / 2.0
        upper_terms = safe_p * (pos_above + j_mid) / jnp.maximum(pos_above + neg_above + j_mid, 1.0)
        # lower: negatives first; g(j) = (Pa+j)/(Pa+Na+n+j) concave increasing,
        # so sum_{j=1..p} g(j) >= p * (g(1) + g(p)) / 2
        denom0 = jnp.maximum(pos_above + neg_above + n + 1.0, 1.0)
        denom1 = jnp.maximum(pos_above + neg_above + n + safe_p, 1.0)
        lower_terms = safe_p * ((pos_above + 1.0) / denom0 + (pos_above + safe_p) / denom1) / 2.0
        zero = jnp.zeros((), jnp.float32)
        hi = jnp.where(has, upper_terms, zero).sum() / p_total
        lo = jnp.where(has, lower_terms, zero).sum() / p_total
        nanless = self.pos.sum() > 0
        return (
            jnp.where(nanless, jnp.clip(lo, 0.0, 1.0), jnp.nan),
            jnp.where(nanless, jnp.clip(hi, 0.0, 1.0), jnp.nan),
        )

    def average_precision(self) -> Array:
        """Binned average precision: the envelope midpoint."""
        lo, hi = self.average_precision_bounds()
        return (lo + hi) / 2.0

    def average_precision_error_bound(self) -> Array:
        """Half-width of :meth:`average_precision_bounds` —
        ``|average_precision() - exact| <= this`` always."""
        lo, hi = self.average_precision_bounds()
        return (hi - lo) / 2.0

    def bin_masses(self) -> Array:
        """Normalized per-bin (pos + neg) score masses (drift input)."""
        total = self.count
        return (self.pos + self.neg) / jnp.maximum(total, 1.0)

    def label_masses(self) -> Tuple[Array, Array]:
        """Per-class normalized masses ``(pos_masses, neg_masses)`` —
        class-conditional drift inputs."""
        return (
            self.pos / jnp.maximum(self.pos.sum(), 1.0),
            self.neg / jnp.maximum(self.neg.sum(), 1.0),
        )


def merge_all(sketches: Sequence[Sketch]) -> Sketch:
    """Left fold of :meth:`Sketch.merge` over a non-empty sequence (order
    irrelevant by the merge algebra)."""
    if not sketches:
        raise ValueError("merge_all needs at least one sketch")
    return functools.reduce(lambda a, b: a.merge(b), sketches)


def delta_envelope_leaf(leaf_name: str) -> bool:
    """Whether a min/max sketch leaf named ``leaf_name`` is a cumulative
    ENVELOPE bound — carryable through history interval deltas — according
    to every registered sketch class's ``_delta_envelope_leaves``.

    The history tier's delta algebra sees spec paths
    (``__sketch_leaf_<name>``), not sketch classes, so the answer is
    resolved by leaf NAME across the registry. Registration guards the
    ambiguity: if one class declares a min/max leaf name an envelope and
    another uses the same name for a non-invertible extreme (an HLL
    register array), this raises rather than guess — rename the leaf.
    """
    envelope = False
    plain = False
    for cls in _SKETCH_REGISTRY.values():
        for name, red in cls._leaf_fields:
            if name != leaf_name or red not in ("min", "max"):
                continue
            if name in cls._delta_envelope_leaves:
                envelope = True
            else:
                plain = True
    if envelope and plain:
        raise ValueError(
            f"sketch leaf name {leaf_name!r} is declared a delta-envelope"
            " bound by one registered sketch class and a plain extreme by"
            " another — leaf names must be unambiguous for the history"
            " delta algebra; rename one of them"
        )
    return envelope
