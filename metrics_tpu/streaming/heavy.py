"""Heavy-hitter and co-occurrence sketches: exact-monoid frequency summaries.

**Why these are linear sketches and not textbook SpaceSaving.** Classic
SpaceSaving (Metwally et al.) decides *at update time* which counter to
evict — so two summaries merged in different orders hold different
states, and only the error BOUND survives reordering (Agarwal et al.,
"Mergeable Summaries"). That is not good enough here: this platform's
entire distribution story — ``lax.scan`` epoch folds, stacked pow-2
serve-tree folds, mesh reduce-scatter, history rollups, elastic
rebalance replays — assumes every sketch leaf merges by an exact
leafwise ``sum``/``min``/``max`` monoid, pinned BITWISE across fold
orders. So, exactly as :class:`~metrics_tpu.streaming.sketches.
QuantileSketch` chose fixed bins over randomized KLL compaction,
:class:`HeavyHitterSketch` chooses determinism over update-time
eviction: update and merge are LOSSLESS LINEAR projections (exact
integer-valued sums — a true commutative monoid, fold order can never
change state), and the SpaceSaving-style condensation to fixed-capacity
``(id, count, overestimate)`` arrays happens only at **compute time**,
where nothing merges afterwards.

**The linear id-recovery trick.** Each of ``depth`` rows hashes an id
into one of ``capacity`` buckets and adds its weight to the bucket's
total (a count-min row) AND to one exact per-bit mass sum for every set
bit of the id (``bitsums[r, b, j] = total weight in bucket b whose id
has bit j set``). All leaves are sums, so the merge is exact. At query
time a bucket dominated by one id reproduces that id by per-bit majority
vote, and the bit sums yield *deterministic, rigorous* per-item bounds:

* upper: ``f(x) <= min_r min_j side_j(x)`` where ``side_j(x)`` is the
  bucket mass agreeing with ``x``'s bit ``j`` (every unit of ``x``'s
  mass agrees with ``x`` at every bit);
* lower: ``f(x) >= counts[r,b] - sum_j minority_j(x)`` (every OTHER id
  in the bucket disagrees with ``x`` in at least one bit, so its mass is
  counted in at least one minority term).

``estimate() = upper`` keeps SpaceSaving's reporting contract — never an
underestimate, with a per-item overestimate envelope ``upper - lower``
(``tests/streaming/test_sketch_families.py`` pins both sides at 1M
samples).

:class:`CoOccurrenceSketch` is the same machinery over packed
``(row, col)`` pair ids — confusion/co-occurrence structure for label
spaces far beyond the C<=128 pallas tile — plus EXACT per-axis marginal
counts that tighten the upper bound (a cell can never exceed its row or
column total).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.streaming.hashing import ROW_SEEDS, bit_planes, bucket_index, pack_bits
from metrics_tpu.streaming.sketches import Sketch

Array = jax.Array

__all__ = ["CoOccurrenceSketch", "HeavyHitterSketch"]


# ---------------------------------------------------------------------------
# shared linear-decode core (pure jnp; used by both sketches AND by the
# sharded mesh kernels in utilities/sharding.py)
# ---------------------------------------------------------------------------


def _fold_linear(
    counts: Array, bitsums: Array, ids: Array, weights: Optional[Array], width: int
) -> Tuple[Array, Array]:
    """Scatter a batch of (id, weight) pairs into every row of the
    count/bit-plane arrays. Pure and jit-safe; exact integer-valued f32
    sums, so folds commute bitwise with merges."""
    ids = jnp.ravel(jnp.asarray(ids)).astype(jnp.uint32)
    w = (
        jnp.ones(ids.shape, jnp.float32)
        if weights is None
        else jnp.ravel(jnp.asarray(weights)).astype(jnp.float32)
    )
    depth, _w, num_bits = bitsums.shape
    bits = bit_planes(ids, num_bits)  # [N, B]
    votes = w[:, None] * bits
    for r in range(depth):
        b = bucket_index(ids, r, width)
        counts = counts.at[r, b].add(w)
        bitsums = bitsums.at[r, b, :].add(votes)
    return counts, bitsums


def _decode_candidates(counts: Array, bitsums: Array, width: int) -> Tuple[Array, Array]:
    """Majority-decode every cell of every row into a candidate id.

    Returns ``(ids uint32[D, W], valid bool[D, W])`` — a cell is a valid
    candidate only when it holds mass and its decoded id hashes back to
    that very cell (the self-consistency check that rejects cells whose
    majority vote is collision noise)."""
    depth, w = counts.shape
    maj = (2.0 * bitsums) > counts[..., None]  # strict: zero mass decodes id 0 invalidly
    ids = pack_bits(maj)  # [D, W]
    cols = jnp.arange(w, dtype=jnp.int32)[None, :]
    valid = counts > 0
    for r in range(depth):
        home = bucket_index(ids[r], r, width)[None, :] == cols
        valid = valid.at[r].set(valid[r] & home[0])
    return ids, valid


def _candidate_bounds(
    counts: Array, bitsums: Array, ids: Array, width: int
) -> Tuple[Array, Array]:
    """Rigorous per-id ``(lower, upper)`` frequency bounds for a flat id
    vector, from full (merged) count/bit-plane arrays.

    ``upper``: for every row and bit, the bucket mass AGREEING with the
    id's bit is >= its true count — take the min. ``lower``: the bucket
    total minus the sum of per-bit DISAGREEING masses — every colliding
    id disagrees somewhere, so the subtraction can only overshoot.
    """
    depth, _w, num_bits = bitsums.shape
    bits = bit_planes(ids, num_bits)  # [M, B]
    uppers, lowers = [], []
    for r in range(depth):
        b = bucket_index(ids, r, width)  # [M]
        c = counts[r, b]  # [M]
        bs = bitsums[r, b, :]  # [M, B]
        agree = jnp.where(bits > 0, bs, c[:, None] - bs)
        uppers.append(jnp.minimum(agree.min(axis=-1), c))
        lowers.append(c - (c[:, None] - agree).sum(axis=-1))
    upper = jnp.stack(uppers).min(axis=0)
    lower = jnp.clip(jnp.stack(lowers).max(axis=0), 0.0, None)
    return jnp.minimum(lower, upper), upper


def _rank_candidates(
    ids: Array, valid: Array, lower: Array, upper: Array, k: int
) -> Tuple[Array, Array, Array]:
    """Deterministic top-``k`` selection over a flat candidate set.

    Duplicates (the same id decoded from several rows) collapse to one
    entry; ordering is by (estimate desc, id asc) — a total order, so the
    reported arrays are identical regardless of candidate enumeration
    order (the compute-time face of merge determinism). Returns
    ``(ids int32[k], estimates f32[k], overestimates f32[k])`` with empty
    slots as ``id=-1, estimate=0, overestimate=0``.
    """
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    flat_valid = valid.reshape(-1)
    flat_up = jnp.where(flat_valid, upper.reshape(-1), -jnp.inf)
    flat_lo = lower.reshape(-1)
    # collapse duplicates: sort by (id, valid-first) and keep the first of
    # each id run (equal ids carry equal bounds — same merged arrays, same
    # arithmetic). Valid-first matters: an unrelated cell can spuriously
    # decode the same bit pattern yet fail its home-bucket check, and it
    # must not shadow the genuine occurrence.
    order = jnp.lexsort((~flat_valid, flat_ids))
    sid = flat_ids[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    keep = first & flat_valid[order]
    up = jnp.where(keep, flat_up[order], -jnp.inf)
    lo = flat_lo[order]
    # (estimate desc, id asc): lexsort's last key is primary
    rank = jnp.lexsort((sid, -up))
    top = rank[:k]
    got = up[top] > -jnp.inf
    return (
        jnp.where(got, sid[top], -1).astype(jnp.int32),
        jnp.where(got, up[top], 0.0).astype(jnp.float32),
        jnp.where(got, up[top] - lo[top], 0.0).astype(jnp.float32),
    )


class HeavyHitterSketch(Sketch):
    """Deterministic heavy-hitter summary with an exact (bitwise) monoid
    merge and compute-time SpaceSaving condensation.

    State: ``depth`` count-min rows of ``capacity`` buckets
    (``counts[D, W]``) plus exact per-bit id-mass sums
    (``bitsums[D, W, id_bits]``) — ``4 * D * W * (1 + id_bits)`` bytes,
    fixed, regardless of stream length or cardinality. Every leaf is an
    integer-valued f32 sum, so ``merge`` is associative + commutative
    BITWISE with the fresh sketch as identity — fold order, shard count,
    and mesh permutation can never change state (see module docstring for
    why update-time eviction was rejected).

    :meth:`topk` materializes the classic fixed-capacity
    ``(id, count, overestimate)`` arrays at query time: counts NEVER
    underestimate, and each item's rigorous overestimate envelope comes
    from the exact bit-plane bounds. Ids must be non-negative and below
    ``2 ** id_bits`` (larger ids alias by truncation — raise ``id_bits``
    for wider id spaces).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import HeavyHitterSketch
        >>> sk = HeavyHitterSketch(capacity=64, depth=4, id_bits=16)
        >>> sk = sk.fold(jnp.asarray([7, 7, 7, 9, 9, 3]))
        >>> ids, counts, over = sk.topk(2)
        >>> [int(i) for i in ids], [float(c) for c in counts]
        ([7, 9], [3.0, 2.0])
    """

    _leaf_fields = (("counts", "sum"), ("bitsums", "sum"))
    _config_fields = ("capacity", "depth", "id_bits")
    # buckets distribute over the mesh lane-wise (dim 1 of every row)
    _shard_dims = {"counts": 1, "bitsums": 1}

    def __init__(self, capacity: int = 256, depth: int = 4, id_bits: int = 24) -> None:
        if capacity < 2:
            raise ValueError(f"`capacity` must be >= 2, got {capacity}")
        if not 1 <= depth <= len(ROW_SEEDS):
            raise ValueError(f"`depth` must be in [1, {len(ROW_SEEDS)}], got {depth}")
        if not 1 <= id_bits <= 31:
            raise ValueError(f"`id_bits` must be in [1, 31], got {id_bits}")
        self.capacity = int(capacity)
        self.depth = int(depth)
        self.id_bits = int(id_bits)
        self.counts = jnp.zeros((self.depth, self.capacity), jnp.float32)
        self.bitsums = jnp.zeros((self.depth, self.capacity, self.id_bits), jnp.float32)

    # -- accumulation ----------------------------------------------------

    def fold(self, ids: Array, weights: Optional[Array] = None) -> "HeavyHitterSketch":
        """A new sketch with a batch of integer ids (optionally weighted)
        folded in. Pure, jit-safe: ``depth`` scatter-adds."""
        counts, bitsums = _fold_linear(self.counts, self.bitsums, ids, weights, self.capacity)
        return self._replace_leaves(counts=counts, bitsums=bitsums)

    # -- queries ---------------------------------------------------------

    @property
    def count(self) -> Array:
        """Total folded weight (row 0's mass — every row holds all of it)."""
        return self.counts[0].sum()

    def estimate(self, ids: Array) -> Array:
        """Frequency estimates for ``ids`` — rigorous UPPER bounds (the
        SpaceSaving contract: never an underestimate)."""
        _lo, up = _candidate_bounds(
            self.counts, self.bitsums, jnp.ravel(jnp.asarray(ids)).astype(jnp.uint32), self.capacity
        )
        return up

    def frequency_bounds(self, ids: Array) -> Tuple[Array, Array]:
        """Rigorous per-id ``(lower, upper)`` envelope: the true count of
        every queried id lies inside, deterministically (no probabilistic
        caveat — both sides are theorems of the exact bit-plane sums)."""
        return _candidate_bounds(
            self.counts, self.bitsums, jnp.ravel(jnp.asarray(ids)).astype(jnp.uint32), self.capacity
        )

    def topk(self, k: int) -> Tuple[Array, Array, Array]:
        """The fixed-capacity SpaceSaving-style condensation:
        ``(ids int32[k], counts f32[k], overestimates f32[k])``, ordered
        by (count desc, id asc); empty slots carry ``id=-1``. The true
        count of item ``i`` lies in ``[counts[i] - overestimates[i],
        counts[i]]`` — always."""
        ids, valid = _decode_candidates(self.counts, self.bitsums, self.capacity)
        lo, up = _candidate_bounds(self.counts, self.bitsums, ids.reshape(-1), self.capacity)
        return _rank_candidates(ids, valid, lo, up, int(k))

    def bin_masses(self) -> Array:
        """Normalized row-0 bucket masses (drift-monitor input: the
        hashed frequency profile of the stream)."""
        total = jnp.maximum(self.counts[0].sum(), 1.0)
        return self.counts[0] / total


class CoOccurrenceSketch(Sketch):
    """Mergeable confusion/co-occurrence counts for label spaces beyond
    the C<=128 pallas confusion tile.

    ``(row, col)`` pairs pack into a single id (``row * num_cols + col``)
    and feed the same exact-sum linear structure as
    :class:`HeavyHitterSketch` — hashed ``(row, col)`` binning with an
    exact bitwise sum merge — plus EXACT per-axis marginals
    (``row_marg``/``col_marg``), which both tighten the per-cell upper
    bound (a cell never exceeds its row or column total) and answer the
    marginal label distributions exactly.

    State: ``4 * (D * W * (1 + ceil(log2(R*C))) + R + C)`` bytes, fixed.
    Collision behaviour is per-CELL, not per-class: a 10k x 10k label
    space costs the same device bytes as a 100 x 100 one.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import CoOccurrenceSketch
        >>> sk = CoOccurrenceSketch(num_rows=1000, num_cols=1000, capacity=64)
        >>> sk = sk.fold(jnp.asarray([3, 3, 7]), jnp.asarray([3, 5, 7]))
        >>> lo, hi = sk.cell_bounds(jnp.asarray([3]), jnp.asarray([3]))
        >>> float(lo[0]) <= 1.0 <= float(hi[0])
        True
    """

    _leaf_fields = (
        ("cells", "sum"),
        ("bitsums", "sum"),
        ("row_marg", "sum"),
        ("col_marg", "sum"),
    )
    _config_fields = ("num_rows", "num_cols", "capacity", "depth")
    # hashed cell tables distribute lane-wise; the exact marginals are
    # small and stay replicated
    _shard_dims = {"cells": 1, "bitsums": 1}

    def __init__(
        self, num_rows: int, num_cols: Optional[int] = None, capacity: int = 256, depth: int = 4
    ) -> None:
        num_cols = num_rows if num_cols is None else num_cols
        if num_rows < 1 or num_cols < 1:
            raise ValueError(f"label space must be positive, got {num_rows} x {num_cols}")
        if num_rows * num_cols > 1 << 31:
            raise ValueError(
                f"label space {num_rows} x {num_cols} exceeds 2^31 packed pair ids;"
                " hash the labels down first"
            )
        if capacity < 2:
            raise ValueError(f"`capacity` must be >= 2, got {capacity}")
        if not 1 <= depth <= len(ROW_SEEDS):
            raise ValueError(f"`depth` must be in [1, {len(ROW_SEEDS)}], got {depth}")
        self.num_rows = int(num_rows)
        self.num_cols = int(num_cols)
        self.capacity = int(capacity)
        self.depth = int(depth)
        self.cells = jnp.zeros((self.depth, self.capacity), jnp.float32)
        self.bitsums = jnp.zeros((self.depth, self.capacity, self._pair_bits), jnp.float32)
        self.row_marg = jnp.zeros(self.num_rows, jnp.float32)
        self.col_marg = jnp.zeros(self.num_cols, jnp.float32)

    @property
    def _pair_bits(self) -> int:
        return max((self.num_rows * self.num_cols - 1).bit_length(), 1)

    def _pack(self, rows: Array, cols: Array) -> Array:
        return rows.astype(jnp.uint32) * jnp.uint32(self.num_cols) + cols.astype(jnp.uint32)

    def _unpack(self, pair_ids: Array) -> Tuple[Array, Array]:
        pair_ids = pair_ids.astype(jnp.uint32)
        return (
            (pair_ids // jnp.uint32(self.num_cols)).astype(jnp.int32),
            (pair_ids % jnp.uint32(self.num_cols)).astype(jnp.int32),
        )

    # -- accumulation ----------------------------------------------------

    def fold(
        self, rows: Array, cols: Array, weights: Optional[Array] = None
    ) -> "CoOccurrenceSketch":
        """A new sketch with a batch of ``(row, col)`` label pairs folded
        in (confusion convention: row = true label, col = prediction).
        Pure, jit-safe."""
        rows = jnp.ravel(jnp.asarray(rows)).astype(jnp.int32)
        cols = jnp.ravel(jnp.asarray(cols)).astype(jnp.int32)
        w = (
            jnp.ones(rows.shape, jnp.float32)
            if weights is None
            else jnp.ravel(jnp.asarray(weights)).astype(jnp.float32)
        )
        cells, bitsums = _fold_linear(
            self.cells, self.bitsums, self._pack(rows, cols), w, self.capacity
        )
        return self._replace_leaves(
            cells=cells,
            bitsums=bitsums,
            row_marg=self.row_marg.at[rows].add(w),
            col_marg=self.col_marg.at[cols].add(w),
        )

    # -- queries ---------------------------------------------------------

    @property
    def count(self) -> Array:
        """Total folded weight."""
        return self.row_marg.sum()

    def cell_bounds(self, rows: Array, cols: Array) -> Tuple[Array, Array]:
        """Rigorous ``(lower, upper)`` count envelope for each queried
        ``(row, col)`` cell: linear-decode bounds intersected with the
        exact marginals (``true <= min(row total, col total)``)."""
        rows = jnp.ravel(jnp.asarray(rows)).astype(jnp.int32)
        cols = jnp.ravel(jnp.asarray(cols)).astype(jnp.int32)
        lo, up = _candidate_bounds(self.cells, self.bitsums, self._pack(rows, cols), self.capacity)
        up = jnp.minimum(up, jnp.minimum(self.row_marg[rows], self.col_marg[cols]))
        return jnp.minimum(lo, up), up

    def cell_estimate(self, rows: Array, cols: Array) -> Array:
        """Per-cell count estimates — rigorous upper bounds (never an
        underestimate; the collision bound is ``estimate - lower``)."""
        _lo, up = self.cell_bounds(rows, cols)
        return up

    def top_cells(self, k: int) -> Tuple[Array, Array, Array, Array]:
        """The ``k`` heaviest cells:
        ``(rows int32[k], cols int32[k], counts f32[k], overestimates
        f32[k])`` ordered by (count desc, packed id asc); empty slots
        carry ``row=col=-1``. Same contract as
        :meth:`HeavyHitterSketch.topk`, marginal-tightened."""
        ids, valid = _decode_candidates(self.cells, self.bitsums, self.capacity)
        flat = ids.reshape(-1)
        in_space = flat < jnp.uint32(self.num_rows * self.num_cols)
        lo, up = _candidate_bounds(self.cells, self.bitsums, flat, self.capacity)
        r_idx, c_idx = self._unpack(jnp.where(in_space, flat, 0))
        up = jnp.minimum(up, jnp.minimum(self.row_marg[r_idx], self.col_marg[c_idx]))
        lo = jnp.minimum(lo, up)
        pair_ids, counts, over = _rank_candidates(
            ids, valid & in_space.reshape(valid.shape), lo, up, int(k)
        )
        got = pair_ids >= 0
        rr, cc = self._unpack(jnp.where(got, pair_ids, 0))
        return (
            jnp.where(got, rr, -1).astype(jnp.int32),
            jnp.where(got, cc, -1).astype(jnp.int32),
            counts,
            over,
        )

    def bin_masses(self) -> Array:
        """Normalized row-marginal masses (drift input: the true-label
        distribution, exact)."""
        total = jnp.maximum(self.row_marg.sum(), 1.0)
        return self.row_marg / total
