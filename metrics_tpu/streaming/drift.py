"""Distribution-drift monitors over sketch summaries.

Always-on monitoring's third question (after "what is the metric in this
window" and "what are its quantiles"): *has the input distribution moved
away from the one the model was validated on?* The sketches already carry
the answer — their normalized bin masses are a fixed-size empirical
distribution — so drift detection is a pure function of a **frozen
reference sketch** and the **live sketch**, no samples retained on either
side.

Three standard divergences (all computed on smoothed bin masses):

* :func:`population_stability_index` — PSI, the model-monitoring staple;
  common alert folklore: < 0.1 stable, 0.1-0.25 moderate shift, > 0.25
  action needed (the :class:`DriftMonitor` default threshold is 0.2).
* :func:`kl_divergence` — KL(live ‖ reference), asymmetric, unbounded.
* :func:`js_divergence` — symmetric, bounded by ``ln 2``.

:class:`DriftMonitor` wraps them with thresholds and surfaces alerts
through the obs registry (``stream.drift_checks`` / ``stream.drift_alerts``
counters, per-monitor labels, plus a one-shot ``rank_zero_warn``), so a
drifting stream shows up in the same :func:`metrics_tpu.obs.snapshot` as
the metric values it is about to invalidate.
"""
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.streaming.sketches import Sketch

Array = jax.Array

__all__ = [
    "DriftMonitor",
    "js_divergence",
    "kl_divergence",
    "population_stability_index",
]


def _masses(dist: Union[Sketch, Array], eps: float) -> Array:
    """Smoothed, renormalized bin masses from a sketch or a raw mass
    vector (adding ``eps`` everywhere keeps empty bins from blowing up the
    log ratios — the standard PSI smoothing)."""
    m = dist.bin_masses() if isinstance(dist, Sketch) else jnp.asarray(dist, jnp.float32)
    m = m + jnp.asarray(eps, jnp.float32)
    return m / m.sum()


def population_stability_index(
    reference: Union[Sketch, Array], live: Union[Sketch, Array], eps: float = 1e-6
) -> Array:
    """PSI = sum_b (live_b - ref_b) * ln(live_b / ref_b); jit-safe."""
    p = _masses(live, eps)
    q = _masses(reference, eps)
    return ((p - q) * jnp.log(p / q)).sum()


def kl_divergence(
    reference: Union[Sketch, Array], live: Union[Sketch, Array], eps: float = 1e-6
) -> Array:
    """KL(live ‖ reference) over smoothed bin masses; jit-safe."""
    p = _masses(live, eps)
    q = _masses(reference, eps)
    return (p * jnp.log(p / q)).sum()


def js_divergence(
    reference: Union[Sketch, Array], live: Union[Sketch, Array], eps: float = 1e-6
) -> Array:
    """Jensen-Shannon divergence (symmetric, <= ln 2); jit-safe."""
    p = _masses(live, eps)
    q = _masses(reference, eps)
    m = (p + q) / 2.0
    return ((p * jnp.log(p / m)).sum() + (q * jnp.log(q / m)).sum()) / 2.0


class DriftMonitor:
    """Threshold alerts on the divergence between a frozen reference sketch
    and the live stream's sketch.

    Args:
        reference: the frozen validation-time sketch (any
            :class:`~metrics_tpu.streaming.sketches.Sketch`; a sketch-backed
            metric also works — its sketch state is extracted and frozen).
        psi_threshold: alert when PSI exceeds this (``None`` disarms).
        kl_threshold / js_threshold: further optional alarms.
        eps: bin-mass smoothing for the log ratios.
        name: label on the ``stream.drift_*`` obs counter series.
        warn: emit a one-shot ``rank_zero_warn`` on the first alert.

    :meth:`check` is eager (host-side booleans + obs counters); the module
    divergence functions are jit-safe for in-graph use.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import DriftMonitor, QuantileSketch
        >>> ref = QuantileSketch(num_bins=32).fold(jnp.linspace(0.0, 1.0, 512))
        >>> live = QuantileSketch(num_bins=32).fold(jnp.linspace(0.0, 1.0, 512))
        >>> report = DriftMonitor(ref, warn=False).check(live)
        >>> bool(report["alert"])
        False
    """

    def __init__(
        self,
        reference: Union[Sketch, Any],
        psi_threshold: Optional[float] = 0.2,
        kl_threshold: Optional[float] = None,
        js_threshold: Optional[float] = None,
        eps: float = 1e-6,
        name: str = "default",
        warn: bool = True,
    ) -> None:
        self.reference = self._extract_sketch(reference)
        self.psi_threshold = psi_threshold
        self.kl_threshold = kl_threshold
        self.js_threshold = js_threshold
        if psi_threshold is None and kl_threshold is None and js_threshold is None:
            raise ValueError("DriftMonitor needs at least one armed threshold")
        self.eps = float(eps)
        self.name = str(name)
        self.warn = bool(warn)
        self._warned = False

    @staticmethod
    def _extract_sketch(source: Any) -> Sketch:
        if isinstance(source, Sketch):
            return source
        # a sketch-backed Metric: freeze its (single) sketch state
        defaults = getattr(source, "_defaults", None)
        if defaults:
            sketches = [getattr(source, n) for n in defaults if isinstance(getattr(source, n), Sketch)]
            if len(sketches) == 1:
                return sketches[0]
        raise ValueError(
            "DriftMonitor reference must be a Sketch or a metric with exactly one sketch state,"
            f" got {type(source).__name__}"
        )

    def divergences(self, live: Union[Sketch, Any]) -> Dict[str, Array]:
        """All three divergences of ``live`` vs the frozen reference
        (traced values; no thresholds, no counters)."""
        live = self._extract_sketch(live)
        return {
            "psi": population_stability_index(self.reference, live, self.eps),
            "kl": kl_divergence(self.reference, live, self.eps),
            "js": js_divergence(self.reference, live, self.eps),
        }

    def check(self, live: Union[Sketch, Any]) -> Dict[str, Any]:
        """Divergences + threshold verdict, with obs accounting.

        Returns ``{"psi", "kl", "js"`` (floats)``, "alert"`` (bool)``,
        "triggered"`` (list of threshold names that fired)``}``. Every call
        bumps ``stream.drift_checks{monitor=name}``; every alerting call
        bumps ``stream.drift_alerts{monitor=name}``.
        """
        values = {k: float(v) for k, v in self.divergences(live).items()}
        triggered = [
            key
            for key, threshold in (
                ("psi", self.psi_threshold),
                ("kl", self.kl_threshold),
                ("js", self.js_threshold),
            )
            if threshold is not None and values[key] > threshold
        ]
        if _obs_enabled():
            _obs_inc("stream.drift_checks", monitor=self.name)
            if triggered:
                _obs_inc("stream.drift_alerts", monitor=self.name)
        if triggered and self.warn and not self._warned:
            from metrics_tpu.utilities.prints import rank_zero_warn

            self._warned = True
            details = ", ".join(f"{k}={values[k]:.4f}" for k in triggered)
            rank_zero_warn(
                f"DriftMonitor {self.name!r}: live distribution drifted past threshold(s)"
                f" ({details}). Metric values over this stream may no longer be"
                " comparable to the reference window. Further alerts are counted"
                " under stream.drift_alerts{monitor=" + self.name + "} without warning again.",
                UserWarning,
            )
        return {**values, "alert": bool(triggered), "triggered": triggered}

    def __repr__(self) -> str:
        armed = {
            k: v
            for k, v in (
                ("psi", self.psi_threshold),
                ("kl", self.kl_threshold),
                ("js", self.js_threshold),
            )
            if v is not None
        }
        return f"DriftMonitor(name={self.name!r}, reference={self.reference!r}, thresholds={armed})"
