"""Distinct-count sketch: HyperLogLog registers with an exact bitwise merge.

HyperLogLog (Flajolet et al. 2007) is the rare randomized-analysis sketch
whose MERGE is nonetheless a perfect algebraic object: each register
holds the max leading-zero rank ever observed for its hash slice, so the
union of two streams is the elementwise ``max`` of their register arrays
— a true idempotent commutative monoid (``a ∨ a == a``, any fold order,
any duplication, bitwise identical). That idempotence is worth calling
out: unlike the sum-family sketches, re-merging the SAME HLL payload
twice is harmless, and mesh sync rides the existing ``pmax`` path of
``sync_sketch_in_context`` with no dedup caveats.

The flip side, and why :mod:`metrics_tpu.serve.history` must refuse
interval deltas over these registers: ``max`` is not invertible. Knowing
the registers at t1 and t2 says nothing about the uniques *between* them
(every register may already have been saturated at t1). Distinct counts
over a window come from :class:`~metrics_tpu.streaming.windows.
WindowedMetric` (fresh sketch per window) — never from subtracting
cumulative snapshots.

Determinism: ids hash through the fixed :func:`~metrics_tpu.streaming.
hashing.fmix32` finalizer (no PRNG key), so every process — client,
root re-fold, resume replay — maps an id to the same register/rank and
the monoid stays bitwise across the whole platform.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.streaming.hashing import fmix32, leading_rho, register_index
from metrics_tpu.streaming.sketches import Sketch

Array = jax.Array

__all__ = ["DistinctCountSketch"]

# bias-correction constant alpha_m for m >= 128 (Flajolet et al., Fig. 3);
# small-m special cases below
_ALPHA_LARGE = 0.7213
_ALPHA_DENOM = 1.079


def _alpha(m: int) -> float:
    if m <= 16:
        return 0.673
    if m <= 32:
        return 0.697
    if m <= 64:
        return 0.709
    return _ALPHA_LARGE / (1.0 + _ALPHA_DENOM / m)


class DistinctCountSketch(Sketch):
    """HyperLogLog cardinality summary with an EXACT bitwise merge.

    State: ``2^precision`` int32 registers; ``regs`` carries the ``max``
    reduction, so merge == elementwise max — idempotent, commutative,
    associative, bitwise, with the all-zero fresh sketch as identity.
    Standard error of :meth:`estimate` is ``1.04 / sqrt(2^precision)``
    (~1.6% at the default ``precision=12``, 16KB of registers), with
    linear-counting below ~2.5m and the 32-bit large-range correction
    above 2^32/30 (Flajolet et al. 2007).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.streaming import DistinctCountSketch
        >>> sk = DistinctCountSketch(precision=12)
        >>> sk = sk.fold(jnp.arange(10_000))
        >>> abs(float(sk.estimate()) / 10_000 - 1.0) < 3 * float(sk.relative_error())
        True
    """

    _leaf_fields = (("regs", "max"),)
    _config_fields = ("precision",)
    _shard_dims = {"regs": 0}

    def __init__(self, precision: int = 12) -> None:
        if not 4 <= precision <= 18:
            raise ValueError(f"`precision` must be in [4, 18], got {precision}")
        self.precision = int(precision)
        self.regs = jnp.zeros(1 << self.precision, jnp.int32)

    @property
    def num_registers(self) -> int:
        return 1 << self.precision

    # -- accumulation ----------------------------------------------------

    def fold(self, ids: Array) -> "DistinctCountSketch":
        """A new sketch with a batch of integer ids observed. Pure and
        jit-safe: one hash + one scatter-max. Duplicate ids are free —
        the register max is already at least their rank."""
        h = fmix32(jnp.ravel(jnp.asarray(ids)).astype(jnp.uint32))
        idx = register_index(h, self.precision)
        rho = leading_rho(h, self.precision)
        return self._replace_leaves(regs=self.regs.at[idx].max(rho))

    # -- queries ---------------------------------------------------------

    def estimate(self) -> Array:
        """Estimated number of distinct ids folded in (f32 scalar), with
        the standard linear-counting and large-range corrections."""
        return _hll_estimate(self.regs, self.precision)

    def relative_error(self) -> Array:
        """The standard-error envelope ``1.04 / sqrt(m)`` — the estimate
        is within ``±2σ`` of the truth ~95% of the time."""
        return jnp.float32(1.04 / float(self.num_registers) ** 0.5)

    def bounds(self) -> Tuple[Array, Array]:
        """``(lower, upper)`` 2-sigma envelope around :meth:`estimate`."""
        est = self.estimate()
        sigma = 2.0 * self.relative_error()
        return est * (1.0 - sigma), est * (1.0 + sigma)

    def bin_masses(self) -> Array:
        """Normalized register-rank masses (drift input: the register
        profile distinguishes cardinality regimes)."""
        total = jnp.maximum(self.regs.sum().astype(jnp.float32), 1.0)
        return self.regs.astype(jnp.float32) / total


def _hll_estimate(regs: Array, precision: int) -> Array:
    """The corrected HLL estimator over a full register array (also the
    final step of the mesh-sharded kernel, which pmax-syncs registers and
    computes locally — see ``utilities/sharding.py``)."""
    m = 1 << precision
    regs_f = regs.astype(jnp.float32)
    raw = _alpha(m) * m * m / jnp.exp2(-regs_f).sum()
    zeros = (regs == 0).sum().astype(jnp.float32)
    # small-range: linear counting when any register is still empty
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
    # large-range: correct for 32-bit hash collisions
    two32 = jnp.float32(2.0**32)
    large = -two32 * jnp.log1p(-est / two32)
    return jnp.where(est > two32 / 30.0, large, est)
