"""PearsonCorrCoef metric class.

Behavioral equivalent of reference ``torchmetrics/regression/pearson.py:55``:
six scalar moment states with ``dist_reduce_fx=None`` (sync stacks per-rank
values) merged at compute by the parallel-variance formula
(``_final_aggregation``, reference ``regression/pearson.py:23-54``) — the
custom cross-device reduction pattern SURVEY.md §2.5 calls out.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.pearson import (
    _final_aggregation,
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class PearsonCorrCoef(Metric):
    """Pearson correlation via streaming moments (O(1) state per device).

    Update folds each batch into running mean/variance/covariance, so the
    state is six scalars regardless of sample count; cross-device sync
    gathers the per-device moment sets and merges them pairwise at compute.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrCoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> pearson = PearsonCorrCoef()
        >>> pearson(preds, target)
        Array(0.98488414, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = None  # both -1 and 1 are optimal
    # Running-moment updates consume the prior state, so the fused
    # batch-stats forward path does not apply (reference runs the
    # double-update, metric.py:248-264).
    full_state_update = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("mean_x", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("mean_y", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("var_x", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("var_y", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("corr_xy", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("n_total", default=jnp.asarray(0.0), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
        )

    def compute(self) -> Array:
        if jnp.asarray(self.mean_x).ndim > 0 and jnp.asarray(self.mean_x).size > 1:
            # synced: leading dim is the device axis -> parallel moment merge
            var_x, var_y, corr_xy, n_total = _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            var_x, var_y, corr_xy, n_total = self.var_x, self.var_y, self.corr_xy, self.n_total
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)
