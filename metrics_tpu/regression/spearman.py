"""SpearmanCorrCoef metric class.

Behavioral equivalent of reference ``torchmetrics/regression/spearman.py:23``
(cat-list states; rank transform at compute).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.regression.spearman import _spearman_corrcoef_compute, _spearman_corrcoef_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.buffers import _cat_state_default
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


class SpearmanCorrCoef(Metric):
    """Spearman rank correlation over all accumulated samples.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrCoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> spearman = SpearmanCorrCoef()
        >>> spearman(preds, target)
        Array(1., dtype=float32)

    Args:
        sample_capacity: switches the unbounded cat-list states to a
            fixed-capacity HBM buffer holding at most this many samples
            (static shapes under jit; overflow raises at compute) —
            bounding the memory footprint the warning below refers to.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, sample_capacity: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `SpearmanCorrcoef` will save all targets and predictions in the buffer."
            " For large datasets, this may lead to a large memory footprint."
        )
        self.add_state("preds", default=_cat_state_default(sample_capacity), dist_reduce_fx="cat")
        self.add_state("target", default=_cat_state_default(sample_capacity), dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _spearman_corrcoef_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)
