"""CosineSimilarity metric class.

Behavioral equivalent of reference
``torchmetrics/regression/cosine_similarity.py:24`` (cat-list states).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.buffers import _cat_state_default
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class CosineSimilarity(Metric):
    """Row-wise cosine similarity accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CosineSimilarity
        >>> target = jnp.asarray([[0.0, 1.0], [1.0, 1.0]])
        >>> preds = jnp.asarray([[0.0, 1.0], [0.0, 1.0]])
        >>> cosine_similarity = CosineSimilarity(reduction='mean')
        >>> cosine_similarity(preds, target)
        Array(0.8535534, dtype=float32)

    Args:
        reduction: how to reduce over samples — ``"sum"``, ``"mean"`` or
            ``"none"``/``None``.
        sample_capacity: switches the unbounded cat-list states to a
            fixed-capacity HBM buffer holding at most this many samples
            (static shapes under jit; overflow raises at compute).
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, reduction: str = "sum", sample_capacity: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", default=_cat_state_default(sample_capacity), dist_reduce_fx="cat")
        self.add_state("target", default=_cat_state_default(sample_capacity), dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _cosine_similarity_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)
