"""Lightning-style metric logging lifecycle.

The reference's Lightning integration (``integrations/test_lightning.py``)
rests on ``LightningModule.log(name, metric)``: metrics logged with
``on_step=True`` report their batch-local forward value every step, metrics
with ``on_epoch=True`` are computed and reset at epoch end by the trainer.
``MetricLogger`` reproduces that lifecycle for plain JAX training loops —
the trainer-side bookkeeping without the trainer:

    logger = MetricLogger()
    for epoch in range(E):
        for xb, yb in batches:
            probs = train_step(...)
            logger.log("train/acc", acc_metric, probs, yb)
            logger.log("train/loss", loss)              # plain scalars too
            step_vals = logger.step_values()            # on_step logging
        epoch_vals = logger.epoch_values()              # compute + reset

Metrics are identified by name: logging the same name again with a Metric
object drives ``forward`` on that object; `epoch_values()` computes every
logged metric (triggering its distributed sync), resets it, and archives the
values in ``history``.
"""
from typing import Any, Dict, List, Optional

from metrics_tpu.metric import Metric

__all__ = ["MetricLogger"]


def _jsonable(value: Any) -> Any:
    """History values (jnp scalars/arrays, nested dicts) as plain JSON types
    — the manifest the CheckpointManager bundles must be json.dump-able."""
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if hasattr(value, "tolist"):  # jnp / numpy arrays and scalars
        return value.tolist()
    return value


class MetricLogger:
    """Drives ``forward``-per-step / ``compute``+``reset``-per-epoch logging."""

    def __init__(self) -> None:
        self._metrics: Dict[str, Metric] = {}
        self._scalars: Dict[str, List[Any]] = {}
        self._step_values: Dict[str, Any] = {}
        self.history: List[Dict[str, Any]] = []
        # index-parallel to `history`: one obs snapshot per closed epoch
        # (None for epochs closed while metrics_tpu.obs was disabled) —
        # kept beside `history`, not inside it, so epoch_values() consumers
        # never see a phantom metric name
        self.obs_history: List[Optional[Dict[str, Any]]] = []

    def log(self, name: str, value: Any, *update_args: Any, on_step: bool = True, **update_kwargs: Any) -> Optional[Any]:
        """Log a metric (with its update args) or a plain scalar under ``name``.

        With a :class:`Metric`, calls ``value.forward(*update_args)`` —
        accumulating state AND producing the batch-local value (recorded when
        ``on_step``). Plain scalars are buffered and mean-reduced at epoch
        end (Lightning's default scalar aggregation).
        """
        if isinstance(value, Metric):
            if name in self._scalars:
                raise ValueError(f"`{name}` is already logged as a scalar; pick a distinct name")
            bound = self._metrics.get(name, value)
            if bound is not value and bound._effective_update_count():
                # a fresh Metric per step would silently report only the last
                # batch as the epoch aggregate — construct it once outside.
                # (Rebinding a fully-reset metric — e.g. one built per epoch —
                # is harmless and stays allowed.)
                raise ValueError(
                    f"`{name}` is already bound to a different Metric object with"
                    " pending updates; construct the metric once and log the same"
                    " object every step"
                )
            if not on_step:
                # no batch value needed: plain update skips forward's
                # snapshot/compute machinery
                value.update(*update_args, **update_kwargs)
                self._metrics[name] = value  # register only after success
                return None
            batch_value = value.forward(*update_args, **update_kwargs)
            self._metrics[name] = value
            self._step_values[name] = batch_value
            return batch_value
        if update_args or update_kwargs:
            raise ValueError("update args are only valid when logging a Metric")
        if name in self._metrics:
            raise ValueError(f"`{name}` is already logged as a Metric; pick a distinct name")
        self._scalars.setdefault(name, []).append(value)
        if on_step:
            self._step_values[name] = value
        return value

    def step_values(self) -> Dict[str, Any]:
        """Batch-local values of everything logged since the last call."""
        out, self._step_values = self._step_values, {}
        return out

    def epoch_values(self, reset: bool = True) -> Dict[str, Any]:
        """Epoch aggregates: ``compute()`` (with dist sync) for metrics, mean
        for scalars. With ``reset`` (default), metrics are reset and scalar
        buffers cleared — the trainer's end-of-epoch behavior — and the
        values are appended to ``history``.

        ``obs_history`` stays index-parallel to ``history``:
        ``logger.obs_history[e]`` is the obs snapshot at the close of epoch
        ``e`` when the observability layer was armed then
        (``metrics_tpu.obs.enable()``), and ``None`` for epochs closed while
        it was off — kept OUT of the returned values dict so metric
        consumers never see a phantom key.
        """
        out: Dict[str, Any] = {}
        for name, metric in self._metrics.items():
            if metric._effective_update_count():
                out[name] = metric.compute()
                if reset:
                    metric.reset()
        for name, vals in self._scalars.items():
            if vals:
                out[name] = sum(float(v) for v in vals) / len(vals)
        if reset:
            self._scalars = {k: [] for k in self._scalars}
            # _step_values is left alone: step_values() drains itself, and a
            # loop may flush the final batch's step values after epoch close
            self.history.append(out)
            from metrics_tpu import obs

            # None (not absence) for obs-off epochs: obs_history[e] must
            # always describe history[e], even if obs is toggled mid-run.
            # spans=False: archiving the full span ring every epoch would
            # duplicate ~max_spans dicts per entry over a long run
            self.obs_history.append(obs.snapshot(spans=False) if obs.enabled() else None)
        return out

    # ------------------------------------------------------------------
    # Fault-tolerant resume (rides the ft.CheckpointManager manifest)
    # ------------------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        """JSON-serializable logger archive for checkpoint manifests.

        Covers the closed-epoch record (``history`` + the index-parallel
        ``obs_history``) and the mid-epoch scalar buffers, so a run resumed
        by :class:`metrics_tpu.ft.CheckpointManager` keeps its full logging
        trajectory across a preemption. Metric OBJECTS are not here — their
        states ride the checkpoint's orbax tree; re-bind them by logging
        the restored metrics under the same names. History values come back
        as plain floats/lists (device arrays do not survive JSON).
        """
        # every field is a snapshot COPY: an async CheckpointManager save
        # serializes this dict on a background thread while the loop keeps
        # closing epochs — aliasing the live lists would let obs_history
        # grow mid-serialization and break its history index-parallelism
        return {
            "history": _jsonable(self.history),
            "obs_history": _jsonable(self.obs_history),
            "scalars": {k: [float(v) for v in vs] for k, vs in self._scalars.items()},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "MetricLogger":
        """Restore :meth:`state_dict` — ``history``/``obs_history`` continue
        appending after the restored epochs; mid-epoch scalar buffers resume
        accumulating. Returns ``self``."""
        self.history = list(state.get("history", []))
        self.obs_history = list(state.get("obs_history", []))
        self._scalars = {k: list(vs) for k, vs in state.get("scalars", {}).items()}
        return self
