"""Framework integrations (reference ``integrations/``)."""
from metrics_tpu.integrations.logger import MetricLogger

__all__ = ["MetricLogger"]
