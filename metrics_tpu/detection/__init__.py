from metrics_tpu.detection.mean_ap import MeanAveragePrecision  # noqa: F401
