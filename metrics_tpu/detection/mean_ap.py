"""COCO-style mean average precision / recall.

Behavioral equivalent of reference ``torchmetrics/detection/mean_ap.py:133``
(``MeanAveragePrecision``; IoU step :332, greedy matching :421/:513,
precision accumulation :672, summarization :541, ``compute`` :737-790),
which itself follows the pycocotools evaluation protocol.

TPU-first redesign of the state layout: instead of the reference's five
ragged lists of per-image tensors, detections and ground truths are stored
**flattened** — one ``(N, 4)`` box buffer plus score/label vectors and a
per-box ``img_idx`` vector, with a scalar image counter — the same
sort+segment representation the retrieval domain uses. Flat buffers are
static-shape friendly, make the distributed sync a plain concatenation
(``img_idx`` is re-offset per rank by the gathered image counts, see
``_sync_dist``), and let the IoU matrices batch.

The evaluation itself runs host-side at ``compute`` time (the greedy
COCO matching is inherently sequential over score-ranked detections) but is
vectorized over the IoU-threshold axis, replacing the reference's
``thresholds x detections`` double Python loop with one pass over
detections updating all thresholds at once.
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.detection.box_ops import box_convert
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.distributed import gather_all_tensors

Array = jax.Array


class BaseMetricResults(dict):
    """Dict with attribute access to the fixed result fields."""

    def __getattr__(self, key: str):
        if key in self:
            return self[key]
        raise AttributeError(f"No such attribute: {key}")

    def __setattr__(self, key: str, value) -> None:
        self[key] = value


class MAPMetricResults(BaseMetricResults):
    __slots__ = ("map", "map_50", "map_75", "map_small", "map_medium", "map_large")


class MARMetricResults(BaseMetricResults):
    __slots__ = ("mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large")


class COCOMetricResults(BaseMetricResults):
    __slots__ = (
        "map",
        "map_50",
        "map_75",
        "map_small",
        "map_medium",
        "map_large",
        "mar_1",
        "mar_10",
        "mar_100",
        "mar_small",
        "mar_medium",
        "mar_large",
        "map_per_class",
        "mar_100_per_class",
    )


def _input_validator(preds: Sequence[Dict[str, Any]], targets: Sequence[Dict[str, Any]]) -> None:
    """Shape/key checks (reference ``mean_ap.py:83``)."""
    if not isinstance(preds, Sequence):
        raise ValueError("Expected argument `preds` to be of type Sequence")
    if not isinstance(targets, Sequence):
        raise ValueError("Expected argument `target` to be of type Sequence")
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")
    for k in ("boxes", "scores", "labels"):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in ("boxes", "labels"):
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")
    for i, item in enumerate(targets):
        n_boxes = np.asarray(item["boxes"]).reshape(-1, 4).shape[0] if np.asarray(item["boxes"]).size else 0
        if n_boxes != np.asarray(item["labels"]).size:
            raise ValueError(
                f"Input boxes and labels of sample {i} in targets have a"
                f" different length (expected {n_boxes} labels, got {np.asarray(item['labels']).size})"
            )
    for i, item in enumerate(preds):
        n_boxes = np.asarray(item["boxes"]).reshape(-1, 4).shape[0] if np.asarray(item["boxes"]).size else 0
        if not (n_boxes == np.asarray(item["labels"]).size == np.asarray(item["scores"]).size):
            raise ValueError(
                f"Input boxes, labels and scores of sample {i} in predictions have a"
                f" different length (expected {n_boxes} labels and scores,"
                f" got {np.asarray(item['labels']).size} labels and {np.asarray(item['scores']).size} scores)"
            )


def _np_box_area(boxes: np.ndarray) -> np.ndarray:
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def _np_box_iou(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    area_d = _np_box_area(det)
    area_g = _np_box_area(gt)
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area_d[:, None] + area_g[None, :] - inter
    return np.where(union > 0, inter / np.where(union > 0, union, 1.0), 0.0)


def _greedy_match(
    ious: np.ndarray, iou_thresholds: np.ndarray, gt_ignore: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """COCO greedy matching, vectorized over the threshold axis.

    Args:
        ious: (n_det, n_gt) IoU matrix, detections in descending-score order,
            ground truths with ignored ones sorted last.
        iou_thresholds: (T,) thresholds.
        gt_ignore: (n_gt,) ignore flags.

    Returns:
        (det_matches (T, n_det) bool, gt_matches (T, n_gt) bool,
        det_ignore (T, n_det) bool from matched-ignored-gt propagation).

    Follows reference ``_find_best_gt_match`` (mean_ap.py:513): previously
    matched and ignored gts are masked out entirely before the argmax.
    """
    n_det, n_gt = ious.shape
    n_thrs = len(iou_thresholds)
    gt_matches = np.zeros((n_thrs, n_gt), dtype=bool)
    det_matches = np.zeros((n_thrs, n_det), dtype=bool)
    det_ignore = np.zeros((n_thrs, n_det), dtype=bool)
    if n_gt == 0 or n_det == 0:
        return det_matches, gt_matches, det_ignore
    thr_idx = np.arange(n_thrs)
    for idx_det in range(n_det):
        masked = ious[idx_det][None, :] * ~(gt_matches | gt_ignore[None, :])  # (T, n_gt)
        m = masked.argmax(axis=1)
        ok = masked[thr_idx, m] > iou_thresholds
        det_matches[ok, idx_det] = True
        det_ignore[ok, idx_det] = gt_ignore[m[ok]]
        gt_matches[ok[:, None] & (np.arange(n_gt)[None, :] == m[:, None])] = True
    return det_matches, gt_matches, det_ignore


class MeanAveragePrecision(Metric):
    r"""COCO mAP / mAR over object-detection predictions.

    Boxes are expected in absolute image coordinates; format per
    ``box_format``. See the class docstring of the reference for the exact
    update input schema (list of per-image dicts with ``boxes``/``scores``/
    ``labels``).

    Args:
        box_format: ``'xyxy'``, ``'xywh'`` or ``'cxcywh'``.
        iou_thresholds: IoU thresholds (default 0.5:0.05:0.95).
        rec_thresholds: recall thresholds (default 0:0.01:1).
        max_detection_thresholds: max detections per image (default [1, 10, 100]).
        class_metrics: also compute per-class mAP / mAR.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.detection import MeanAveragePrecision
        >>> preds = [dict(
        ...     boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
        ...     scores=jnp.asarray([0.536]),
        ...     labels=jnp.asarray([0]))]
        >>> target = [dict(
        ...     boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]),
        ...     labels=jnp.asarray([0]))]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> round(float(result['map']), 4), round(float(result['map_50']), 4)
        (0.6, 1.0)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_thresholds = list(iou_thresholds) if iou_thresholds else np.linspace(0.5, 0.95, 10).tolist()
        self.rec_thresholds = list(rec_thresholds) if rec_thresholds else np.linspace(0.0, 1.0, 101).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        self.bbox_area_ranges = {
            "all": (0**2, int(1e5**2)),
            "small": (0**2, 32**2),
            "medium": (32**2, 96**2),
            "large": (96**2, int(1e5**2)),
        }
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics

        for name in ("det_boxes", "det_scores", "det_labels", "det_img_idx", "gt_boxes", "gt_labels", "gt_img_idx"):
            self.add_state(name, default=[], dist_reduce_fx="cat")
        self.add_state("n_images", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:
        """Buffer one batch of per-image predictions/ground truths (flattened)."""
        _input_validator(preds, target)
        start = int(self.n_images)
        for offset, (pred, tgt) in enumerate(zip(preds, target)):
            img_id = start + offset
            boxes = jnp.asarray(pred["boxes"], dtype=jnp.float32).reshape(-1, 4)
            boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
            self.det_boxes.append(boxes)
            self.det_scores.append(jnp.asarray(pred["scores"], dtype=jnp.float32).reshape(-1))
            self.det_labels.append(jnp.asarray(pred["labels"]).reshape(-1).astype(jnp.int32))
            self.det_img_idx.append(jnp.full((boxes.shape[0],), img_id, dtype=jnp.int32))

            g_boxes = jnp.asarray(tgt["boxes"], dtype=jnp.float32).reshape(-1, 4)
            g_boxes = box_convert(g_boxes, in_fmt=self.box_format, out_fmt="xyxy")
            self.gt_boxes.append(g_boxes)
            self.gt_labels.append(jnp.asarray(tgt["labels"]).reshape(-1).astype(jnp.int32))
            self.gt_img_idx.append(jnp.full((g_boxes.shape[0],), img_id, dtype=jnp.int32))
        self.n_images = self.n_images + len(preds)

    def _sync_dist(self, dist_sync_fn=gather_all_tensors, process_group=None) -> None:
        """Concatenate flat buffers across ranks, re-offsetting image ids.

        Rank r's ``img_idx`` values are shifted by the total image count of
        ranks 0..r-1 so per-image grouping survives the gather (the flat-
        buffer analogue of the reference's list-of-tensors gather).
        """
        group = process_group or self.process_group
        gathered: Dict[str, List] = {}
        for name in ("det_boxes", "det_scores", "det_labels", "det_img_idx", "gt_boxes", "gt_labels", "gt_img_idx"):
            value = getattr(self, name)
            cat = _cat_or_empty(value, name)
            gathered[name] = dist_sync_fn(cat, group=group)
        gathered_counts = dist_sync_fn(self.n_images, group=group)

        offsets = np.concatenate([[0], np.cumsum([int(c) for c in gathered_counts])])
        for name in ("det_img_idx", "gt_img_idx"):
            gathered[name] = [chunk + offsets[rank] for rank, chunk in enumerate(gathered[name])]
        for name, chunks in gathered.items():
            setattr(self, name, [jnp.concatenate(chunks)])
        self.n_images = jnp.asarray(int(offsets[-1]), dtype=jnp.int32)

    # ------------------------------------------------------------------
    # Evaluation (host side)
    # ------------------------------------------------------------------

    def _evaluate_image(
        self,
        det: np.ndarray,
        scores: np.ndarray,
        gt: np.ndarray,
        area_range: Tuple[int, int],
        max_det: int,
        ious: np.ndarray,
    ) -> Optional[Dict[str, np.ndarray]]:
        """Per-(image, class, area-range) match statistics (ref :421)."""
        if len(gt) == 0 and len(det) == 0:
            return None
        areas = _np_box_area(gt)
        ignore_area = (areas < area_range[0]) | (areas > area_range[1])
        gtind = np.argsort(ignore_area, kind="stable")  # non-ignored first
        gt = gt[gtind]
        gt_ignore = ignore_area[gtind]

        det = det[:max_det]
        scores = scores[:max_det]
        ious_sorted = ious[:max_det][:, gtind] if ious.size else ious

        det_matches, gt_matches, det_ignore = _greedy_match(
            ious_sorted, np.asarray(self.iou_thresholds), gt_ignore
        )

        # unmatched detections outside the area range are ignored too
        if len(det):
            det_areas = _np_box_area(det)
            det_out = (det_areas < area_range[0]) | (det_areas > area_range[1])
            det_ignore = det_ignore | (~det_matches & det_out[None, :])
        return {
            "dtMatches": det_matches,
            "gtMatches": gt_matches,
            "dtScores": scores,
            "gtIgnore": gt_ignore,
            "dtIgnore": det_ignore,
        }

    def _accumulate(
        self, evals: List[Optional[Dict[str, np.ndarray]]], max_det: int
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Merge per-image evals into (recall (T,), precision (T, R)) (ref :672)."""
        evals = [e for e in evals if e is not None]
        if not evals:
            return None
        n_rec_thrs = len(self.rec_thresholds)
        det_scores = np.concatenate([e["dtScores"][:max_det] for e in evals])
        # mergesort for Matlab/pycocotools-consistent tie order (ref :694)
        inds = np.argsort(-det_scores, kind="mergesort")
        det_scores_sorted = det_scores[inds]
        det_matches = np.concatenate([e["dtMatches"][:, :max_det] for e in evals], axis=1)[:, inds]
        det_ignore = np.concatenate([e["dtIgnore"][:, :max_det] for e in evals], axis=1)[:, inds]
        gt_ignore = np.concatenate([e["gtIgnore"] for e in evals])
        npig = int(np.count_nonzero(~gt_ignore))
        if npig == 0:
            return None
        tps = det_matches & ~det_ignore
        fps = ~det_matches & ~det_ignore
        tp_sum = np.cumsum(tps, axis=1, dtype=np.float64)
        fp_sum = np.cumsum(fps, axis=1, dtype=np.float64)

        n_thrs = len(self.iou_thresholds)
        recall = np.zeros(n_thrs)
        precision = np.zeros((n_thrs, n_rec_thrs))
        rec_thresholds = np.asarray(self.rec_thresholds)
        for idx, (tp, fp) in enumerate(zip(tp_sum, fp_sum)):
            nd = len(tp)
            rc = tp / npig
            pr = tp / (fp + tp + np.finfo(np.float64).eps)
            recall[idx] = rc[-1] if nd else 0
            # precision envelope: non-increasing from the right (ref :721-726)
            pr = np.maximum.accumulate(pr[::-1])[::-1]
            inds_r = np.searchsorted(rc, rec_thresholds, side="left")
            num_inds = int(inds_r.argmax()) if inds_r.max() >= nd else n_rec_thrs
            prec_row = np.zeros(n_rec_thrs)
            prec_row[:num_inds] = pr[inds_r[:num_inds]]
            precision[idx] = prec_row
        return recall, precision

    def _calculate(self, class_ids: List[int]) -> Tuple[np.ndarray, np.ndarray]:
        """precision (T, R, K, A, M) and recall (T, K, A, M) arrays (ref :596)."""
        det_boxes = _to_np_cat(self.det_boxes, (0, 4))
        det_scores = _to_np_cat(self.det_scores, (0,))
        det_labels = _to_np_cat(self.det_labels, (0,), dtype=np.int64)
        det_img = _to_np_cat(self.det_img_idx, (0,), dtype=np.int64)
        gt_boxes = _to_np_cat(self.gt_boxes, (0, 4))
        gt_labels = _to_np_cat(self.gt_labels, (0,), dtype=np.int64)
        gt_img = _to_np_cat(self.gt_img_idx, (0,), dtype=np.int64)
        max_det_global = self.max_detection_thresholds[-1]

        # group per (image, class) with one lexsort + contiguous-run slicing —
        # O(N log N) over the flat buffers instead of an O(n_images * N)
        # boolean-mask scan (same sort+segment trick as the retrieval domain)
        def _runs(img: np.ndarray, labels: np.ndarray):
            order = np.lexsort((labels, img))
            keys = np.stack([img[order], labels[order]], axis=1)
            if len(order) == 0:
                return order, np.zeros((0, 2), dtype=np.int64), np.zeros((0,), dtype=np.int64)
            change = np.nonzero(np.any(keys[1:] != keys[:-1], axis=1))[0] + 1
            starts = np.concatenate([[0], change])
            return order, keys[starts], np.concatenate([starts, [len(order)]])

        d_order, d_keys, d_bounds = _runs(det_img, det_labels)
        g_order, g_keys, g_bounds = _runs(gt_img, gt_labels)
        per_img_cls: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]] = {}
        d_slices = {tuple(k): d_order[d_bounds[i] : d_bounds[i + 1]] for i, k in enumerate(d_keys)}
        g_slices = {tuple(k): g_order[g_bounds[i] : g_bounds[i + 1]] for i, k in enumerate(g_keys)}
        for key in set(d_slices) | set(g_slices):
            d_sel = d_slices.get(key, np.zeros((0,), dtype=np.int64))
            g_sel = g_slices.get(key, np.zeros((0,), dtype=np.int64))
            d_b, d_s = det_boxes[d_sel], det_scores[d_sel]
            order = np.argsort(-d_s, kind="stable")[:max_det_global]
            d_b, d_s = d_b[order], d_s[order]
            g_b = gt_boxes[g_sel]
            ious = _np_box_iou(d_b, g_b) if len(d_b) and len(g_b) else np.zeros((len(d_b), len(g_b)))
            per_img_cls[(int(key[0]), int(key[1]))] = (d_b, d_s, g_b, ious)

        n_thrs = len(self.iou_thresholds)
        n_rec = len(self.rec_thresholds)
        shape = (n_thrs, n_rec, len(class_ids), len(self.bbox_area_ranges), len(self.max_detection_thresholds))
        precision = -np.ones(shape)
        recall = -np.ones((n_thrs, len(class_ids), len(self.bbox_area_ranges), len(self.max_detection_thresholds)))

        by_class: Dict[int, List[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]] = {}
        for (img, cls), entry in sorted(per_img_cls.items()):
            by_class.setdefault(cls, []).append(entry)

        for idx_cls, cls in enumerate(class_ids):
            for idx_area, area_range in enumerate(self.bbox_area_ranges.values()):
                evals = [
                    self._evaluate_image(d_b, d_s, g_b, area_range, max_det_global, ious)
                    for d_b, d_s, g_b, ious in by_class.get(cls, [])
                ]
                for idx_md, max_det in enumerate(self.max_detection_thresholds):
                    acc = self._accumulate(evals, max_det)
                    if acc is None:
                        continue
                    rec, prec = acc
                    recall[:, idx_cls, idx_area, idx_md] = rec
                    precision[:, :, idx_cls, idx_area, idx_md] = prec
        return precision, recall

    # ------------------------------------------------------------------
    # Summarization
    # ------------------------------------------------------------------

    def _summarize(
        self,
        results: Dict[str, np.ndarray],
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> Array:
        area_idx = list(self.bbox_area_ranges.keys()).index(area_range)
        mdet_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            prec = results["precision"][..., area_idx, mdet_idx]
            if iou_threshold is not None:
                prec = prec[self.iou_thresholds.index(iou_threshold)]
        else:
            prec = results["recall"][..., area_idx, mdet_idx]
            if iou_threshold is not None:
                prec = prec[self.iou_thresholds.index(iou_threshold)]
        valid = prec[prec > -1]
        return jnp.asarray(valid.mean() if valid.size else -1.0, dtype=jnp.float32)

    def _summarize_results(
        self, precisions: np.ndarray, recalls: np.ndarray
    ) -> Tuple[MAPMetricResults, MARMetricResults]:
        results = dict(precision=precisions, recall=recalls)
        last_max_det = self.max_detection_thresholds[-1]
        map_metrics = MAPMetricResults()
        map_metrics.map = self._summarize(results, True, max_dets=last_max_det)
        if 0.5 in self.iou_thresholds:
            map_metrics.map_50 = self._summarize(results, True, iou_threshold=0.5, max_dets=last_max_det)
        else:
            map_metrics.map_50 = jnp.asarray(-1.0)
        if 0.75 in self.iou_thresholds:
            map_metrics.map_75 = self._summarize(results, True, iou_threshold=0.75, max_dets=last_max_det)
        else:
            map_metrics.map_75 = jnp.asarray(-1.0)
        map_metrics.map_small = self._summarize(results, True, area_range="small", max_dets=last_max_det)
        map_metrics.map_medium = self._summarize(results, True, area_range="medium", max_dets=last_max_det)
        map_metrics.map_large = self._summarize(results, True, area_range="large", max_dets=last_max_det)

        mar_metrics = MARMetricResults()
        for max_det in self.max_detection_thresholds:
            mar_metrics[f"mar_{max_det}"] = self._summarize(results, False, max_dets=max_det)
        mar_metrics.mar_small = self._summarize(results, False, area_range="small", max_dets=last_max_det)
        mar_metrics.mar_medium = self._summarize(results, False, area_range="medium", max_dets=last_max_det)
        mar_metrics.mar_large = self._summarize(results, False, area_range="large", max_dets=last_max_det)
        return map_metrics, mar_metrics

    def _get_classes(self) -> List[int]:
        labels = [np.asarray(x) for x in self.det_labels + self.gt_labels]
        if labels:
            all_labels = np.concatenate([x.reshape(-1) for x in labels])
            return sorted(np.unique(all_labels).astype(int).tolist())
        return []

    def compute(self) -> dict:
        """COCO summary dict (map, map_50, ..., mar_100_per_class)."""
        classes = self._get_classes()
        precisions, recalls = self._calculate(classes)
        map_val, mar_val = self._summarize_results(precisions, recalls)

        map_per_class = jnp.asarray([-1.0])
        mar_per_class = jnp.asarray([-1.0])
        if self.class_metrics and classes:
            # only map / mar_<last> are reported per class, so summarize just
            # those two slices instead of the full 12-entry summary per class
            last_idx = len(self.max_detection_thresholds) - 1
            area_all = list(self.bbox_area_ranges.keys()).index("all")
            map_list, mar_list = [], []
            for class_idx in range(len(classes)):
                prec = precisions[:, :, class_idx, area_all, last_idx]
                rec = recalls[:, class_idx, area_all, last_idx]
                map_list.append(prec[prec > -1].mean() if (prec > -1).any() else -1.0)
                mar_list.append(rec[rec > -1].mean() if (rec > -1).any() else -1.0)
            map_per_class = jnp.asarray(map_list, dtype=jnp.float32)
            mar_per_class = jnp.asarray(mar_list, dtype=jnp.float32)

        metrics = COCOMetricResults()
        metrics.update(map_val)
        metrics.update(mar_val)
        metrics.map_per_class = map_per_class
        metrics[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = mar_per_class
        return metrics


def _cat_or_empty(value: List[Array], name: str) -> Array:
    if isinstance(value, list):
        if not value:
            if name.endswith("boxes"):
                return jnp.zeros((0, 4), dtype=jnp.float32)
            dtype = jnp.int32 if name.endswith(("labels", "img_idx")) else jnp.float32
            return jnp.zeros((0,), dtype=dtype)
        return jnp.concatenate(value)
    return value


def _to_np_cat(value, empty_shape: Tuple[int, ...], dtype=np.float64) -> np.ndarray:
    if isinstance(value, list):
        if not value:
            return np.zeros(empty_shape, dtype=dtype)
        return np.concatenate([np.asarray(v, dtype=dtype) for v in value])
    return np.asarray(value, dtype=dtype)
