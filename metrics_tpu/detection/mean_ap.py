"""COCO-style mean average precision / recall.

Behavioral equivalent of reference ``torchmetrics/detection/mean_ap.py:133``
(``MeanAveragePrecision``; IoU step :332, greedy matching :421/:513,
precision accumulation :672, summarization :541, ``compute`` :737-790),
which itself follows the pycocotools evaluation protocol.

TPU-first redesign of the state layout: instead of the reference's five
ragged lists of per-image tensors, detections and ground truths are stored
**flattened** — one ``(N, 4)`` box buffer plus score/label vectors and a
per-box ``img_idx`` vector, with a scalar image counter — the same
sort+segment representation the retrieval domain uses. Flat buffers are
static-shape friendly, make the distributed sync a plain concatenation
(``img_idx`` is re-offset per rank by the gathered image counts, see
``_sync_dist``), and let the IoU matrices batch.

The evaluation itself runs host-side at ``compute`` time (the greedy
COCO matching is inherently sequential over score-ranked detections) but is
vectorized over the IoU-threshold axis, replacing the reference's
``thresholds x detections`` double Python loop with one pass over
detections updating all thresholds at once.
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.detection.box_ops import box_convert
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.distributed import gather_all_tensors

Array = jax.Array


class BaseMetricResults(dict):
    """Dict with attribute access to the fixed result fields."""

    def __getattr__(self, key: str):
        if key in self:
            return self[key]
        raise AttributeError(f"No such attribute: {key}")

    def __setattr__(self, key: str, value) -> None:
        self[key] = value


class MAPMetricResults(BaseMetricResults):
    __slots__ = ("map", "map_50", "map_75", "map_small", "map_medium", "map_large")


class MARMetricResults(BaseMetricResults):
    __slots__ = ("mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large")


class COCOMetricResults(BaseMetricResults):
    __slots__ = (
        "map",
        "map_50",
        "map_75",
        "map_small",
        "map_medium",
        "map_large",
        "mar_1",
        "mar_10",
        "mar_100",
        "mar_small",
        "mar_medium",
        "mar_large",
        "map_per_class",
        "mar_100_per_class",
    )


def _validate_container_types(preds: Any, targets: Any) -> None:
    """Reject non-Sequence containers (str iterates as characters, so exclude it)."""
    if not isinstance(preds, Sequence) or isinstance(preds, str):
        raise ValueError("Expected argument `preds` to be of type Sequence")
    if not isinstance(targets, Sequence) or isinstance(targets, str):
        raise ValueError("Expected argument `target` to be of type Sequence")


def _input_validator(preds: Sequence[Dict[str, Any]], targets: Sequence[Dict[str, Any]]) -> None:
    """Shape/key checks (reference ``mean_ap.py:83``)."""
    _validate_container_types(preds, targets)
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")
    for k in ("boxes", "scores", "labels"):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in ("boxes", "labels"):
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")
    for i, item in enumerate(targets):
        n_boxes = np.asarray(item["boxes"]).reshape(-1, 4).shape[0] if np.asarray(item["boxes"]).size else 0
        if n_boxes != np.asarray(item["labels"]).size:
            raise ValueError(
                f"Input boxes and labels of sample {i} in targets have a"
                f" different length (expected {n_boxes} labels, got {np.asarray(item['labels']).size})"
            )
    for i, item in enumerate(preds):
        n_boxes = np.asarray(item["boxes"]).reshape(-1, 4).shape[0] if np.asarray(item["boxes"]).size else 0
        if not (n_boxes == np.asarray(item["labels"]).size == np.asarray(item["scores"]).size):
            raise ValueError(
                f"Input boxes, labels and scores of sample {i} in predictions have a"
                f" different length (expected {n_boxes} labels and scores,"
                f" got {np.asarray(item['labels']).size} labels and {np.asarray(item['scores']).size} scores)"
            )


def _np_box_area(boxes: np.ndarray) -> np.ndarray:
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def _np_box_iou(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    area_d = _np_box_area(det)
    area_g = _np_box_area(gt)
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area_d[:, None] + area_g[None, :] - inter
    return np.where(union > 0, inter / np.where(union > 0, union, 1.0), 0.0)


class MeanAveragePrecision(Metric):
    r"""COCO mAP / mAR over object-detection predictions.

    Boxes are expected in absolute image coordinates; format per
    ``box_format``. See the class docstring of the reference for the exact
    update input schema (list of per-image dicts with ``boxes``/``scores``/
    ``labels``).

    Args:
        box_format: ``'xyxy'``, ``'xywh'`` or ``'cxcywh'``.
        iou_thresholds: IoU thresholds (default 0.5:0.05:0.95).
        rec_thresholds: recall thresholds (default 0:0.01:1).
        max_detection_thresholds: max detections per image (default [1, 10, 100]).
        class_metrics: also compute per-class mAP / mAR.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.detection import MeanAveragePrecision
        >>> preds = [dict(
        ...     boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
        ...     scores=jnp.asarray([0.536]),
        ...     labels=jnp.asarray([0]))]
        >>> target = [dict(
        ...     boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]),
        ...     labels=jnp.asarray([0]))]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> round(float(result['map']), 4), round(float(result['map_50']), 4)
        (0.6, 1.0)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_thresholds = list(iou_thresholds) if iou_thresholds else np.linspace(0.5, 0.95, 10).tolist()
        self.rec_thresholds = list(rec_thresholds) if rec_thresholds else np.linspace(0.0, 1.0, 101).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        self.bbox_area_ranges = {
            "all": (0**2, int(1e5**2)),
            "small": (0**2, 32**2),
            "medium": (32**2, 96**2),
            "large": (96**2, int(1e5**2)),
        }
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics

        for name in ("det_boxes", "det_scores", "det_labels", "det_img_idx", "gt_boxes", "gt_labels", "gt_img_idx"):
            self.add_state(name, default=[], dist_reduce_fx="cat")
        self.add_state("n_images", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:
        """Buffer one batch of per-image predictions/ground truths (flattened).

        The whole batch is concatenated host-side first so the device sees
        ONE chunk per state per call — per-image eager device ops would pay
        a dispatch (and on tunneled TPUs a round trip) per image.
        """
        # container-type errors must surface before normalization touches items
        _validate_container_types(preds, target)
        # pull everything to host in ONE batched transfer (per-array eager
        # fetches pay a round trip each — fatal on tunneled TPUs), then
        # normalize; absent keys stay absent so the validator reports them
        preds, target = jax.device_get((list(preds), list(target)))
        def _normalize(item: Dict[str, Any], float_keys: Tuple[str, ...]) -> Dict[str, Any]:
            out = dict(item)
            if "boxes" in out:
                out["boxes"] = np.asarray(out["boxes"], dtype=np.float32).reshape(-1, 4)
            for key in float_keys:
                if key in out:
                    out[key] = np.asarray(out[key], dtype=np.float32).reshape(-1)
            if "labels" in out:
                out["labels"] = np.asarray(out["labels"], dtype=np.int64).reshape(-1)
            return out

        preds = [_normalize(p, ("scores",)) for p in preds]
        target = [_normalize(t, ()) for t in target]
        _input_validator(preds, target)
        if not preds:  # empty shard: avoid growing the state lists with 0-size chunks
            return
        start = int(self.n_images)

        def _cat(arrays, empty_shape, dtype):
            arrays = list(arrays)
            return np.concatenate(arrays) if arrays else np.zeros(empty_shape, dtype)

        d_boxes = [p["boxes"] for p in preds]
        d_counts = [b.shape[0] for b in d_boxes]
        g_boxes = [t["boxes"] for t in target]
        g_counts = [b.shape[0] for b in g_boxes]
        img_ids = np.arange(start, start + len(preds), dtype=np.int32)

        # ONE batched host->device transfer for all seven state chunks — a
        # put per array would pay one tunnel round trip each
        boxes, scores, labels, det_idx, gboxes, glabels, gt_idx = jax.device_put(
            (
                _cat(d_boxes, (0, 4), np.float32),
                _cat((p["scores"] for p in preds), (0,), np.float32),
                _cat((p["labels"] for p in preds), (0,), np.int64).astype(np.int32),
                np.repeat(img_ids, d_counts),
                _cat(g_boxes, (0, 4), np.float32),
                _cat((t["labels"] for t in target), (0,), np.int64).astype(np.int32),
                np.repeat(img_ids, g_counts),
            )
        )
        self.det_boxes.append(box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy"))
        self.det_scores.append(scores)
        self.det_labels.append(labels)
        self.det_img_idx.append(det_idx)
        self.gt_boxes.append(box_convert(gboxes, in_fmt=self.box_format, out_fmt="xyxy"))
        self.gt_labels.append(glabels)
        self.gt_img_idx.append(gt_idx)
        self.n_images = self.n_images + len(preds)

    def _sync_dist(self, dist_sync_fn=gather_all_tensors, process_group=None) -> None:
        """Concatenate flat buffers across ranks, re-offsetting image ids.

        Rank r's ``img_idx`` values are shifted by the total image count of
        ranks 0..r-1 so per-image grouping survives the gather (the flat-
        buffer analogue of the reference's list-of-tensors gather).

        Like ``Metric._sync_dist``, degradation is atomic: the 8 gathers
        here must agree on the world — local detections against globally
        gathered ground truths would mass-produce false negatives, and a
        degraded ``gathered_counts`` shorter than the box chunk lists
        would break the offset arithmetic. If any gather degrades to its
        per-host partial, the whole sync falls back to local-only state.
        """
        from metrics_tpu.ft.retry import degraded_sync_scope

        group = process_group or self.process_group
        names = ("det_boxes", "det_scores", "det_labels", "det_img_idx", "gt_boxes", "gt_labels", "gt_img_idx")
        local = {name: _cat_or_empty(getattr(self, name), name) for name in names}
        gathered: Dict[str, List] = {}
        with degraded_sync_scope() as scope:
            for name in names:
                gathered[name] = dist_sync_fn(local[name], group=group)
            gathered_counts = dist_sync_fn(self.n_images, group=group)
        if scope["degraded"]:
            gathered = {name: [local[name]] for name in names}
            gathered_counts = [self.n_images]

        offsets = np.concatenate([[0], np.cumsum([int(c) for c in gathered_counts])])
        for name in ("det_img_idx", "gt_img_idx"):
            gathered[name] = [chunk + offsets[rank] for rank, chunk in enumerate(gathered[name])]
        for name, chunks in gathered.items():
            setattr(self, name, [jnp.concatenate(chunks)])
        self.n_images = jnp.asarray(int(offsets[-1]), dtype=jnp.int32)

    # ------------------------------------------------------------------
    # Evaluation (host side)
    # ------------------------------------------------------------------

    def _accumulate_batch(
        self,
        matches: np.ndarray,
        ignore: np.ndarray,
        npig: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(recall (G,), precision (G, R)) from stacked score-sorted det rows.

        Vectorized form of the reference's per-(iou-threshold) PR
        accumulation (ref :672-726): every (area, iou-threshold) pair is one
        row of ``matches``/``ignore`` (G, D), ``npig`` (G,) its positive-gt
        count. Rows with ``npig == 0`` are left at -1 (the reference's
        "skip this cell" sentinel). The per-row recall->precision lookup is
        a single flat ``searchsorted`` over offset-stacked rows instead of
        G small ones.
        """
        n_groups, n_dets = matches.shape
        n_rec_thrs = len(self.rec_thresholds)
        recall = -np.ones(n_groups)
        precision = -np.ones((n_groups, n_rec_thrs))
        pos = npig > 0
        if not pos.any():
            return recall, precision
        if n_dets == 0:
            recall[pos] = 0.0
            precision[pos] = 0.0
            return recall, precision
        tp = np.cumsum(matches & ~ignore, axis=1, dtype=np.float64)
        fp = np.cumsum(~matches & ~ignore, axis=1, dtype=np.float64)
        rc = tp / np.where(pos, npig, 1).astype(np.float64)[:, None]
        pr = tp / (fp + tp + np.finfo(np.float64).eps)
        # precision envelope: non-increasing from the right (ref :721-726)
        pr = np.maximum.accumulate(pr[:, ::-1], axis=1)[:, ::-1]
        # per-row searchsorted on the raw doubles: an offset-stacked single
        # call would perturb values by ~1 ulp and flip exact threshold
        # crossings (rc == thr happens routinely: tp/npig vs linspace)
        rec_thresholds = np.asarray(self.rec_thresholds)
        inds = np.empty((n_groups, n_rec_thrs), dtype=np.int64)
        for g in range(n_groups):
            inds[g] = np.searchsorted(rc[g], rec_thresholds, side="left")
        valid = inds < n_dets  # past-the-end recall thresholds score 0
        # reference prefix truncation (ref :729-731): everything from the
        # FIRST past-the-end threshold onward scores 0 — with a custom
        # non-ascending rec_thresholds list an in-range threshold after a
        # past-the-end one is zeroed too, matching the reference exactly
        overflow = inds.max(axis=1) >= n_dets
        cols = np.arange(n_rec_thrs)
        valid &= ~overflow[:, None] | (cols[None, :] < inds.argmax(axis=1)[:, None])
        prec = np.where(valid, np.take_along_axis(pr, np.minimum(inds, n_dets - 1), axis=1), 0.0)
        recall[pos] = rc[pos, -1]
        precision[pos] = prec[pos]
        return recall, precision

    def _calculate(self, class_ids: List[int]) -> Tuple[np.ndarray, np.ndarray]:
        """precision (T, R, K, A, M) and recall (T, K, A, M) arrays (ref :596)."""
        det_boxes = _to_np_cat(self.det_boxes, (0, 4))
        det_scores = _to_np_cat(self.det_scores, (0,))
        det_labels = _to_np_cat(self.det_labels, (0,), dtype=np.int64)
        det_img = _to_np_cat(self.det_img_idx, (0,), dtype=np.int64)
        gt_boxes = _to_np_cat(self.gt_boxes, (0, 4))
        gt_labels = _to_np_cat(self.gt_labels, (0,), dtype=np.int64)
        gt_img = _to_np_cat(self.gt_img_idx, (0,), dtype=np.int64)
        max_det_global = self.max_detection_thresholds[-1]

        # group per (image, class) WITHOUT any per-cell Python work: encode
        # (img, label) into one int64 key, lexsort once, derive within-run
        # ranks arithmetically, and scatter straight into the padded batch
        # (same sort+segment trick as the retrieval domain; profiling showed
        # ~15k tiny per-cell numpy calls dominating the old layout)
        n_thrs = len(self.iou_thresholds)
        n_rec = len(self.rec_thresholds)
        n_areas = len(self.bbox_area_ranges)
        n_mdets = len(self.max_detection_thresholds)

        def _empty():
            # -1 sentinels; only the numpy fallback and the no-cells early
            # exit materialize these (the native path returns its own arrays)
            return (
                -np.ones((n_thrs, n_rec, len(class_ids), n_areas, n_mdets)),
                -np.ones((n_thrs, len(class_ids), n_areas, n_mdets)),
            )

        # labels may be arbitrary ints (incl. negative), so encode via their
        # DENSE index in the sorted unique-label set — keys stay collision-
        # free and ordered by (img, label) like the old dict grouping
        uniq_labels = np.unique(np.concatenate([det_labels, gt_labels]))
        enc_base = max(1, len(uniq_labels))
        enc_d = det_img * enc_base + np.searchsorted(uniq_labels, det_labels)
        enc_g = gt_img * enc_base + np.searchsorted(uniq_labels, gt_labels)

        # cells sorted by (img, cls) — the ascending encoded key order —
        # which fixes cross-cell score tie-breaks exactly like the old
        # sorted(dict.items()) layout
        cells_enc = np.unique(np.concatenate([enc_d, enc_g]))
        n_cells = len(cells_enc)
        if n_cells == 0:
            precision, recall = _empty()
            return precision, recall
        cell_cls = uniq_labels[(cells_enc % enc_base).astype(np.int64)]

        def _ranks(enc_sorted: np.ndarray) -> np.ndarray:
            """Position of each element within its contiguous key run."""
            n = len(enc_sorted)
            if n == 0:
                return np.zeros((0,), dtype=np.int64)
            new_run = np.empty(n, dtype=bool)
            new_run[0] = True
            np.not_equal(enc_sorted[1:], enc_sorted[:-1], out=new_run[1:])
            starts = np.flatnonzero(new_run)
            run_id = np.cumsum(new_run) - 1
            return np.arange(n, dtype=np.int64) - starts[run_id]

        # detections: one lexsort puts each cell's dets contiguous AND
        # descending by score (stable, so equal scores keep input order —
        # the same tie-break as the old per-cell stable argsort)
        d_ord = np.lexsort((-det_scores, enc_d))
        enc_d_sorted = enc_d[d_ord]
        d_rank = _ranks(enc_d_sorted)
        d_cell = np.searchsorted(cells_enc, enc_d_sorted)
        d_counts = np.bincount(d_cell, minlength=n_cells)
        md = max(1, min(max_det_global, int(d_counts.max()) if d_counts.size else 1))
        d_keep = d_rank < md

        # CSR det layout: kept dets stay cell-major (ascending encoded key)
        # and score-descending within each cell — ragged, no padding
        d_cell_f = d_cell[d_keep]
        d_scores_f = np.ascontiguousarray(det_scores[d_ord][d_keep], dtype=np.float32)
        d_rank_f = d_rank[d_keep]
        d_boxes_f = np.ascontiguousarray(det_boxes[d_ord][d_keep], dtype=np.float32)
        nd_c = np.bincount(d_cell_f, minlength=n_cells).astype(np.int64)
        det_off = np.zeros(n_cells + 1, dtype=np.int64)
        np.cumsum(nd_c, out=det_off[1:])

        # ground truths: stable sort by key; CSR position within the cell's
        # contiguous run IS the rank
        g_ord = np.argsort(enc_g, kind="stable")
        g_cell = np.searchsorted(cells_enc, enc_g[g_ord])
        ng_c = np.bincount(g_cell, minlength=n_cells).astype(np.int64)
        gt_off = np.zeros(n_cells + 1, dtype=np.int64)
        np.cumsum(ng_c, out=gt_off[1:])
        gt_boxes_f = np.ascontiguousarray(gt_boxes[g_ord], dtype=np.float32)

        # flat pair IoUs: only the REAL det x gt pairs of each cell — the
        # old bucketed (n_cells, max_nd, max_ng) padding computed ~100x more
        # pairs than exist at COCO-like densities
        pc = nd_c * ng_c
        iou_off = np.zeros(n_cells + 1, dtype=np.int64)
        np.cumsum(pc, out=iou_off[1:])
        n_pairs = int(iou_off[-1])
        pair_cell = np.repeat(np.arange(n_cells), pc)
        rr = np.arange(n_pairs, dtype=np.int64) - iou_off[:-1][pair_cell]
        di = det_off[:-1][pair_cell] + rr // ng_c[pair_cell]
        gi = gt_off[:-1][pair_cell] + rr % ng_c[pair_cell]
        d_area_f = _np_box_area(d_boxes_f).astype(np.float32)
        g_area_f = _np_box_area(gt_boxes_f).astype(np.float32)
        lt = np.maximum(d_boxes_f[di, :2], gt_boxes_f[gi, :2])
        rb = np.minimum(d_boxes_f[di, 2:], gt_boxes_f[gi, 2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        union = d_area_f[di] + g_area_f[gi] - inter
        pair_iou = np.where(union > 0, inter / np.where(union > 0, union, 1.0), 0.0).astype(np.float32)

        area_lo = np.asarray([r[0] for r in self.bbox_area_ranges.values()], dtype=np.float32)
        area_hi = np.asarray([r[1] for r in self.bbox_area_ranges.values()], dtype=np.float32)
        gt_ignore_flat = (g_area_f[None, :] < area_lo[:, None]) | (g_area_f[None, :] > area_hi[:, None])
        gt_cell_ids = np.repeat(np.arange(n_cells), ng_c)
        gt_ignore_counts = np.stack(
            [np.bincount(gt_cell_ids, weights=~ign, minlength=n_cells) for ign in gt_ignore_flat]
        )  # (A, n_cells)
        det_out_flat = (d_area_f[None, :] < area_lo[:, None]) | (d_area_f[None, :] > area_hi[:, None])

        # greedy matching (ref :421/:513 semantics: matched and ignored gts
        # are masked out entirely before the argmax) — native C kernel over
        # the ragged cells, numpy per-cell fallback without a compiler
        iou_thrs = np.asarray(self.iou_thresholds, dtype=np.float64)
        from metrics_tpu import native

        det_matches = native.coco_match(
            pair_iou, iou_off[:-1], nd_c, ng_c, det_off[:-1], gt_off[:-1],
            gt_ignore_flat.astype(np.uint8), iou_thrs,
        )
        if det_matches is None:
            det_matches = _coco_match_numpy(
                pair_iou, iou_off, nd_c, ng_c, det_off, gt_off, gt_ignore_flat, iou_thrs
            )  # (A, T, total_det)

        d_cls = cell_cls[d_cell_f]  # label of every kept det (flat)

        # class-major, score-descending global det order (stable, so ties
        # keep the cell-major flat order — the same sequence a fresh
        # per-class mergesort of -score yields), plus per-(class, area)
        # positive-gt totals: the full accumulation over every
        # (class, area, maxdet, iou-threshold) group is ONE native call
        native_acc = None
        rec_sorted = not np.any(np.diff(np.asarray(self.rec_thresholds)) < 0)
        if rec_sorted and native.native_available():
            cls_arr = np.asarray(class_ids, dtype=np.int64)  # sorted (``_get_classes``)
            perm = np.lexsort((-d_scores_f, d_cls))
            cls_counts = np.bincount(
                np.searchsorted(cls_arr, d_cls), minlength=len(cls_arr)
            )
            cls_off = np.zeros(len(cls_arr) + 1, dtype=np.int64)
            np.cumsum(cls_counts, out=cls_off[1:])
            npig_ca = np.zeros((len(cls_arr), n_areas), dtype=np.float64)
            np.add.at(npig_ca, np.searchsorted(cls_arr, cell_cls), gt_ignore_counts.T)
            native_acc = native.pr_accumulate(
                det_matches,
                det_out_flat,
                perm,
                cls_off,
                d_rank_f,
                npig_ca.astype(np.int64),
                np.asarray(self.rec_thresholds, dtype=np.float64),
                np.asarray(self.max_detection_thresholds, dtype=np.int64),
            )
        if native_acc is not None:
            rec_c, prec_c = native_acc  # (C, A, M, T), (C, A, M, T, R)
            recall = rec_c.transpose(3, 0, 1, 2)  # -> (T, K, A, M)
            precision = prec_c.transpose(3, 4, 0, 1, 2)  # -> (T, R, K, A, M)
            return np.ascontiguousarray(precision), np.ascontiguousarray(recall)

        precision, recall = _empty()
        for idx_cls, cls in enumerate(class_ids):
            sel = cell_cls == cls
            if not sel.any():
                continue
            # ONE sort per class (ref :694 tie order): the md-threshold
            # subsets are rank-filters of the same descending-score order,
            # so restricting the sorted sequence to rank < t reproduces the
            # order a fresh masked sort would give. Flat dets are cell-major
            # rank-major, the same sequence the old padded layout flattened.
            dm = np.flatnonzero(d_cls == cls)
            order = dm[np.argsort(-d_scores_f[dm], kind="mergesort")]
            sorted_rank = d_rank_f[order]
            m_all = det_matches[:, :, order]  # (A, T, D)
            ig_all = ~m_all & det_out_flat[:, order][:, None, :]  # (A, T, D)
            npig_area = np.array(
                [gt_ignore_counts[idx_area][sel].sum() for idx_area in range(n_areas)]
            )
            for idx_md, max_det in enumerate(self.max_detection_thresholds):
                keep_t = sorted_rank < max_det
                rec_g, prec_g = self._accumulate_batch(
                    m_all[:, :, keep_t].reshape(n_areas * n_thrs, -1),
                    ig_all[:, :, keep_t].reshape(n_areas * n_thrs, -1),
                    np.repeat(npig_area, n_thrs),
                )
                recall[:, idx_cls, :, idx_md] = rec_g.reshape(n_areas, n_thrs).T
                precision[:, :, idx_cls, :, idx_md] = prec_g.reshape(
                    n_areas, n_thrs, n_rec
                ).transpose(1, 2, 0)
        return precision, recall

    # ------------------------------------------------------------------
    # Summarization
    # ------------------------------------------------------------------

    def _summarize(
        self,
        results: Dict[str, np.ndarray],
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> Array:
        area_idx = list(self.bbox_area_ranges.keys()).index(area_range)
        mdet_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            prec = results["precision"][..., area_idx, mdet_idx]
            if iou_threshold is not None:
                prec = prec[self.iou_thresholds.index(iou_threshold)]
        else:
            prec = results["recall"][..., area_idx, mdet_idx]
            if iou_threshold is not None:
                prec = prec[self.iou_thresholds.index(iou_threshold)]
        valid = prec[prec > -1]
        return jnp.asarray(valid.mean() if valid.size else -1.0, dtype=jnp.float32)

    def _summarize_results(
        self, precisions: np.ndarray, recalls: np.ndarray
    ) -> Tuple[MAPMetricResults, MARMetricResults]:
        results = dict(precision=precisions, recall=recalls)
        last_max_det = self.max_detection_thresholds[-1]
        map_metrics = MAPMetricResults()
        map_metrics.map = self._summarize(results, True, max_dets=last_max_det)
        if 0.5 in self.iou_thresholds:
            map_metrics.map_50 = self._summarize(results, True, iou_threshold=0.5, max_dets=last_max_det)
        else:
            map_metrics.map_50 = jnp.asarray(-1.0)
        if 0.75 in self.iou_thresholds:
            map_metrics.map_75 = self._summarize(results, True, iou_threshold=0.75, max_dets=last_max_det)
        else:
            map_metrics.map_75 = jnp.asarray(-1.0)
        map_metrics.map_small = self._summarize(results, True, area_range="small", max_dets=last_max_det)
        map_metrics.map_medium = self._summarize(results, True, area_range="medium", max_dets=last_max_det)
        map_metrics.map_large = self._summarize(results, True, area_range="large", max_dets=last_max_det)

        mar_metrics = MARMetricResults()
        for max_det in self.max_detection_thresholds:
            mar_metrics[f"mar_{max_det}"] = self._summarize(results, False, max_dets=max_det)
        mar_metrics.mar_small = self._summarize(results, False, area_range="small", max_dets=last_max_det)
        mar_metrics.mar_medium = self._summarize(results, False, area_range="medium", max_dets=last_max_det)
        mar_metrics.mar_large = self._summarize(results, False, area_range="large", max_dets=last_max_det)
        return map_metrics, mar_metrics

    def _get_classes(self) -> List[int]:
        labels = [np.asarray(x) for x in self.det_labels + self.gt_labels]
        if labels:
            all_labels = np.concatenate([x.reshape(-1) for x in labels])
            return sorted(np.unique(all_labels).astype(int).tolist())
        return []

    def compute(self) -> dict:
        """COCO summary dict (map, map_50, ..., mar_100_per_class)."""
        classes = self._get_classes()
        precisions, recalls = self._calculate(classes)
        map_val, mar_val = self._summarize_results(precisions, recalls)

        map_per_class = jnp.asarray([-1.0])
        mar_per_class = jnp.asarray([-1.0])
        if self.class_metrics and classes:
            # only map / mar_<last> are reported per class, so summarize just
            # those two slices instead of the full 12-entry summary per class
            last_idx = len(self.max_detection_thresholds) - 1
            area_all = list(self.bbox_area_ranges.keys()).index("all")
            map_list, mar_list = [], []
            for class_idx in range(len(classes)):
                prec = precisions[:, :, class_idx, area_all, last_idx]
                rec = recalls[:, class_idx, area_all, last_idx]
                map_list.append(prec[prec > -1].mean() if (prec > -1).any() else -1.0)
                mar_list.append(rec[rec > -1].mean() if (rec > -1).any() else -1.0)
            map_per_class = jnp.asarray(map_list, dtype=jnp.float32)
            mar_per_class = jnp.asarray(mar_list, dtype=jnp.float32)

        metrics = COCOMetricResults()
        metrics.update(map_val)
        metrics.update(mar_val)
        metrics.map_per_class = map_per_class
        metrics[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = mar_per_class
        return metrics


def _coco_match_numpy(
    pair_iou: np.ndarray,
    iou_off: np.ndarray,
    nd_c: np.ndarray,
    ng_c: np.ndarray,
    det_off: np.ndarray,
    gt_off: np.ndarray,
    gt_ignore: np.ndarray,
    iou_thrs: np.ndarray,
) -> np.ndarray:
    """Pure-numpy greedy matching over the CSR cell layout (fallback for
    environments without a C compiler; same semantics as coco_match.c)."""
    n_areas, _ = gt_ignore.shape
    n_thrs = len(iou_thrs)
    total_det = int(nd_c.sum())
    out = np.zeros((n_areas, n_thrs, total_det), dtype=bool)
    for c in np.nonzero((nd_c > 0) & (ng_c > 0))[0]:
        ndc, ngc = int(nd_c[c]), int(ng_c[c])
        m = pair_iou[iou_off[c] : iou_off[c] + ndc * ngc].reshape(ndc, ngc)
        gi = gt_ignore[:, gt_off[c] : gt_off[c] + ngc]  # (A, ngc)
        gt_matched = np.zeros((n_areas, n_thrs, ngc), dtype=bool)
        for d in range(ndc):
            masked = m[d][None, None, :] * ~(gt_matched | gi[:, None, :])
            g = masked.argmax(-1)  # (A, T)
            val = np.take_along_axis(masked, g[..., None], -1)[..., 0]
            ok = val > iou_thrs[None, :]
            out[:, :, det_off[c] + d] = ok
            a_i, t_i = np.nonzero(ok)
            gt_matched[a_i, t_i, g[a_i, t_i]] = True
    return out


def _cat_or_empty(value: List[Array], name: str) -> Array:
    if isinstance(value, list):
        if not value:
            if name.endswith("boxes"):
                return jnp.zeros((0, 4), dtype=jnp.float32)
            dtype = jnp.int32 if name.endswith(("labels", "img_idx")) else jnp.float32
            return jnp.zeros((0,), dtype=dtype)
        return jnp.concatenate(value)
    return value


def _to_np_cat(value, empty_shape: Tuple[int, ...], dtype=np.float64) -> np.ndarray:
    if isinstance(value, list):
        if not value:
            return np.zeros(empty_shape, dtype=dtype)
        return np.concatenate([np.asarray(v, dtype=dtype) for v in value])
    return np.asarray(value, dtype=dtype)
