/* COCO precision/recall accumulation over every (class, area, max-det,
 * IoU-threshold) group in one pass.
 *
 * Equivalent of the accumulation step of the COCO evaluation protocol
 * (reference torchmetrics/detection/mean_ap.py:672-726): detections are
 * walked in descending score order, TP/FP running counts become a
 * recall/precision curve, precision takes its non-increasing right-to-left
 * envelope, and the curve is sampled at R recall thresholds.
 *
 * The det walk order is supplied as `perm` — class-major, score-descending
 * global det indices (cls_off CSR) — so the kernel gathers straight from
 * the (A, T, Dtot) match table; no per-class copies are materialized.
 * Rows with npig == 0 are skipped entirely, leaving the caller's -1
 * sentinel in place. The recall-threshold sampling is a two-pointer merge
 * (both sequences are non-decreasing): O(D + R) per group instead of R
 * binary searches.
 */
#include <float.h>
#include <stdint.h>

void mtpu_pr_accumulate(
    const uint8_t *matches,   /* (A, T, Dtot) greedy-match flags */
    const uint8_t *out_area,  /* (A, Dtot) det outside area range */
    const int64_t *perm,      /* (Dtot,) class-major score-desc det index */
    const int64_t *cls_off,   /* (C+1,) class CSR over perm */
    const int64_t *rank,      /* (Dtot,) within-cell score rank of each det */
    const int64_t *npig,      /* (C, A) non-ignored positive gts */
    const double *rec_thr,    /* (R,) ascending recall thresholds */
    const int64_t *max_dets,  /* (M,) per-image det caps */
    int64_t C,
    int64_t A,
    int64_t T,
    int64_t R,
    int64_t M,
    int64_t Dtot,
    double *recall,           /* out: (C, A, M, T), caller-filled with -1 */
    double *precision,        /* out: (C, A, M, T, R), caller-filled with -1 */
    double *scratch)          /* (2 * max class det count) doubles */
{
    for (int64_t c = 0; c < C; ++c) {
        const int64_t j0 = cls_off[c], j1 = cls_off[c + 1];
        double *rc = scratch;
        double *pr = scratch + (j1 - j0);
        for (int64_t a = 0; a < A; ++a) {
            const int64_t np_ca = npig[c * A + a];
            if (np_ca <= 0)
                continue; /* keep the -1 sentinel (no positives to recall) */
            const uint8_t *oa = out_area + a * Dtot;
            for (int64_t m = 0; m < M; ++m) {
                const int64_t cap = max_dets[m];
                for (int64_t t = 0; t < T; ++t) {
                    const uint8_t *mt = matches + (a * T + t) * Dtot;
                    double tp = 0.0, fp = 0.0;
                    int64_t n = 0;
                    for (int64_t j = j0; j < j1; ++j) {
                        const int64_t d = perm[j];
                        if (rank[d] >= cap)
                            continue;
                        const int md = mt[d] != 0;
                        const int ig = !md && oa[d]; /* unmatched out-of-area det */
                        tp += (double)(md & !ig);
                        fp += (double)(!md & !ig);
                        rc[n] = tp / (double)np_ca;
                        pr[n] = tp / (fp + tp + DBL_EPSILON);
                        ++n;
                    }
                    double *prec_row =
                        precision + (((c * A + a) * M + m) * T + t) * R;
                    recall[((c * A + a) * M + m) * T + t] = n ? rc[n - 1] : 0.0;
                    double run = 0.0;
                    for (int64_t i = n - 1; i >= 0; --i) {
                        if (pr[i] > run)
                            run = pr[i];
                        pr[i] = run;
                    }
                    int64_t i = 0;
                    for (int64_t r = 0; r < R; ++r) {
                        while (i < n && rc[i] < rec_thr[r])
                            ++i;
                        prec_row[r] = i < n ? pr[i] : 0.0;
                    }
                }
            }
        }
    }
}
