"""Native (C) runtime kernels with transparent Python fallbacks.

The compute path of this framework is JAX/XLA; the runtime *around* it —
here, the host-side string kernels of the text domain — is native where it
pays. The C sources ship with the package and are compiled lazily on first
use (cc -O2 -shared), cached next to the source; if no compiler is
available the callers fall back to their numpy implementations, so the
package never hard-depends on a toolchain.

Set ``METRICS_TPU_NO_NATIVE=1`` to force the Python fallbacks.
"""
import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import List, Optional

import numpy as np

_HERE = Path(__file__).resolve().parent
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _info(msg: str) -> None:
    # local import: utilities.prints -> jax, keep the native loader lean
    from metrics_tpu.utilities.prints import rank_zero_info

    rank_zero_info(f"metrics_tpu.native: {msg}")


def _cache_dirs():
    """Candidate output dirs: package dir, then a per-user cache.

    Never a world-writable shared dir — a predictable .so name in /tmp could
    be pre-planted by another local user and dlopened into this process.
    """
    yield _HERE
    xdg = os.environ.get("XDG_CACHE_HOME")
    home_cache = Path(xdg) if xdg else Path.home() / ".cache"
    yield home_cache / "metrics_tpu"


def _safe_to_load(path: Path) -> bool:
    """Only load libraries this user owns (best effort on non-POSIX)."""
    try:
        st = path.stat()
        return st.st_uid == os.getuid()
    except (OSError, AttributeError):
        return True


def _build_timeout() -> float:
    """Compile timeout: a 44-line TU builds in seconds, but a loaded host or
    cold NFS cache can stall a legitimate gcc run far longer — default
    generous, overridable via METRICS_TPU_NATIVE_BUILD_TIMEOUT."""
    raw = os.environ.get("METRICS_TPU_NATIVE_BUILD_TIMEOUT", "")
    try:
        value = float(raw)
        if value > 0:
            return value
        _info(f"ignoring non-positive METRICS_TPU_NATIVE_BUILD_TIMEOUT={raw!r}; using 60s")
    except ValueError:
        if raw:
            _info(f"ignoring malformed METRICS_TPU_NATIVE_BUILD_TIMEOUT={raw!r}; using 60s")
    return 60.0


def _compile(sources: List[Path]) -> Optional[Path]:
    """cc -O2 -shared -fPIC srcs -> one content-addressed .so, atomically."""
    tag = hashlib.sha256(b"".join(s.read_bytes() for s in sources)).hexdigest()[:16]
    name = f"{sources[0].stem}-{tag}.so"
    timeout_s = _build_timeout()
    for out_dir in _cache_dirs():
        so = out_dir / name
        if so.exists() and _safe_to_load(so):
            return so
        try:
            out_dir.mkdir(parents=True, exist_ok=True)
        except OSError:
            continue
        for cc in ("cc", "gcc", "clang"):
            # build under a unique temp name, then rename into place so a
            # concurrent importer never dlopens a half-written file
            try:
                fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(out_dir))
            except OSError:
                break  # dir not writable: try the next cache dir
            os.close(fd)
            try:
                # announce the build so a hung compiler/NFS cache stall is
                # attributable
                _info(f"compiling native kernels {[s.name for s in sources]} with {cc} -> {so}")
                res = subprocess.run(
                    [cc, "-O2", "-shared", "-fPIC", "-o", tmp] + [str(s) for s in sources],
                    capture_output=True,
                    timeout=timeout_s,
                )
                if res.returncode == 0:
                    os.replace(tmp, so)
                    return so
                _info(f"native kernel build failed ({cc} rc={res.returncode}); trying next compiler")
            except FileNotFoundError:
                pass
            except subprocess.TimeoutExpired:
                _info(f"native kernel build with {cc} timed out after {timeout_s:g}s; trying next compiler")
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        # compiler exists but this dir may be read-only: try the next dir
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("METRICS_TPU_NO_NATIVE"):
        return None
    try:
        so = _compile([_HERE / "levenshtein.c", _HERE / "coco_match.c", _HERE / "pr_accumulate.c"])
    except Exception:
        # e.g. Path.home() RuntimeError under an arbitrary UID with no HOME:
        # native is an optimization — never let its setup crash a metric
        return None
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(str(so))
        i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(dtype=np.float32, flags="C_CONTIGUOUS")
        f64p = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
        lib.mtpu_edit_distance.argtypes = [i64p, ctypes.c_int64, i64p, ctypes.c_int64]
        lib.mtpu_edit_distance.restype = ctypes.c_int64
        lib.mtpu_edit_distance_batch.argtypes = [i64p, i64p, i64p, i64p, ctypes.c_int64, i64p]
        lib.mtpu_edit_distance_batch.restype = None
        lib.mtpu_text_dist_batch.argtypes = [
            u8p, i64p, u8p, i64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p, i64p,
        ]
        lib.mtpu_text_dist_batch.restype = ctypes.c_int64
        lib.mtpu_coco_match.argtypes = [
            f32p, i64p, i64p, i64p, i64p, i64p, u8p, f64p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            u8p, u8p,
        ]
        lib.mtpu_coco_match.restype = None
        lib.mtpu_pr_accumulate.argtypes = [
            u8p, u8p, i64p, i64p, i64p, i64p, f64p, i64p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            f64p, f64p, f64p,
        ]
        lib.mtpu_pr_accumulate.restype = None
    except (OSError, AttributeError):
        # unreadable or stale library (missing symbol): fall back to numpy
        return None
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return _load() is not None


def edit_distance(a: np.ndarray, b: np.ndarray) -> Optional[int]:
    """Native unit-cost Levenshtein; None when no native library."""
    lib = _load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, dtype=np.int64)
    b = np.ascontiguousarray(b, dtype=np.int64)
    out = int(lib.mtpu_edit_distance(a, len(a), b, len(b)))
    return None if out < 0 else out


def edit_distance_batch(seqs_a: List[np.ndarray], seqs_b: List[np.ndarray]) -> Optional[np.ndarray]:
    """Batched native Levenshtein over a corpus; None when no native library.

    One FFI crossing for the whole batch: sequences are flattened CSR-style.
    """
    lib = _load()
    if lib is None:
        return None
    n = len(seqs_a)
    off_a = np.zeros(n + 1, dtype=np.int64)
    off_b = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(s) for s in seqs_a], out=off_a[1:])
    np.cumsum([len(s) for s in seqs_b], out=off_b[1:])
    flat_a = np.concatenate(seqs_a) if n else np.zeros(0, dtype=np.int64)
    flat_b = np.concatenate(seqs_b) if n else np.zeros(0, dtype=np.int64)
    flat_a = np.ascontiguousarray(flat_a, dtype=np.int64)
    flat_b = np.ascontiguousarray(flat_b, dtype=np.int64)
    out = np.empty(n, dtype=np.int64)
    lib.mtpu_edit_distance_batch(flat_a, off_a, flat_b, off_b, n, out)
    if (out < 0).any():  # allocation failure inside the kernel
        return None
    return out


def text_dist_batch(corpus_a: List[str], corpus_b: List[str], mode: str):
    """Whole-corpus edit-distance stats in ONE crossing; None when no lib.

    ``mode`` is ``"words"`` (WER family: CPython whitespace split + FNV-64
    token hashing, done in C) or ``"chars"`` (CER: Unicode code points).
    Returns ``(dist, cnt_a, cnt_b)`` int64 arrays — per-pair edit distance
    and both sides' token/char counts. Strings with lone surrogates cannot
    be UTF-8-encoded; callers catch UnicodeEncodeError and take the Python
    path.
    """
    if mode not in ("chars", "words"):
        raise ValueError(f"mode must be 'chars' or 'words', got {mode!r}")
    if len(corpus_a) != len(corpus_b):
        raise ValueError(f"Corpus has different size {len(corpus_a)} != {len(corpus_b)}")
    lib = _load()
    if lib is None or not hasattr(lib, "mtpu_text_dist_batch"):
        return None
    n = len(corpus_a)

    def pack(strs):
        bs = [s.encode("utf-8") for s in strs]
        off = np.zeros(len(strs) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in bs], out=off[1:])
        flat = np.frombuffer(b"".join(bs), dtype=np.uint8) if off[-1] else np.zeros(0, np.uint8)
        return np.ascontiguousarray(flat), off

    flat_a, off_a = pack(corpus_a)
    flat_b, off_b = pack(corpus_b)
    dist = np.empty(n, dtype=np.int64)
    cnt_a = np.empty(n, dtype=np.int64)
    cnt_b = np.empty(n, dtype=np.int64)
    rc = lib.mtpu_text_dist_batch(
        flat_a, off_a, flat_b, off_b, n, 0 if mode == "chars" else 1, dist, cnt_a, cnt_b
    )
    return None if rc < 0 else (dist, cnt_a, cnt_b)


def pr_accumulate(
    matches: np.ndarray,
    out_area: np.ndarray,
    perm: np.ndarray,
    cls_off: np.ndarray,
    rank: np.ndarray,
    npig: np.ndarray,
    rec_thresholds: np.ndarray,
    max_dets: np.ndarray,
):
    """Native COCO PR accumulation over all (class, area, maxdet, iou) groups.

    ``matches`` (A, T, Dtot) / ``out_area`` (A, Dtot) bool-or-uint8 det
    flags, ``perm``/``cls_off`` the class-major score-descending det CSR,
    ``rank`` per-det within-cell rank, ``npig`` (C, A) positive-gt counts.
    Returns ``(recall (C, A, M, T), precision (C, A, M, T, R))`` float64
    with -1 where ``npig == 0``, or None when no native library.
    """
    lib = _load()
    if lib is None or not hasattr(lib, "mtpu_pr_accumulate"):
        return None
    if np.any(np.diff(rec_thresholds) < 0):
        # the C kernel's two-pointer sampling needs ascending thresholds;
        # callers with a custom unsorted list take the numpy fallback
        return None
    A, T, Dtot = matches.shape
    C = len(cls_off) - 1
    R = len(rec_thresholds)
    M = len(max_dets)
    recall = -np.ones((C, A, M, T), dtype=np.float64)
    precision = -np.ones((C, A, M, T, R), dtype=np.float64)
    cls_off = np.ascontiguousarray(cls_off, dtype=np.int64)
    max_class_d = int(np.diff(cls_off).max()) if C else 0
    scratch = np.empty(max(2, 2 * max_class_d), dtype=np.float64)
    lib.mtpu_pr_accumulate(
        np.ascontiguousarray(matches).view(np.uint8),
        np.ascontiguousarray(out_area).view(np.uint8),
        np.ascontiguousarray(perm, dtype=np.int64),
        cls_off,
        np.ascontiguousarray(rank, dtype=np.int64),
        np.ascontiguousarray(npig, dtype=np.int64),
        np.ascontiguousarray(rec_thresholds, dtype=np.float64),
        np.ascontiguousarray(max_dets, dtype=np.int64),
        C, A, T, R, M, Dtot,
        recall,
        precision,
        scratch,
    )
    return recall, precision


def coco_match(
    pair_ious: np.ndarray,
    iou_off: np.ndarray,
    nd: np.ndarray,
    ng: np.ndarray,
    det_off: np.ndarray,
    gt_off: np.ndarray,
    gt_ignore: np.ndarray,
    iou_thresholds: np.ndarray,
) -> Optional[np.ndarray]:
    """Native greedy COCO matching over ragged cells; None when unavailable.

    Args are the CSR cell layout documented in ``coco_match.c``; returns
    ``det_matches`` of shape ``(A, T, total_det)`` (bool).
    """
    lib = _load()
    if lib is None or not hasattr(lib, "mtpu_coco_match"):
        return None
    A, total_gt = gt_ignore.shape
    T = len(iou_thresholds)
    total_det = int(nd.sum())
    out = np.zeros((A, T, total_det), dtype=np.uint8)
    scratch = np.empty(max(1, total_gt), dtype=np.uint8)
    lib.mtpu_coco_match(
        np.ascontiguousarray(pair_ious, dtype=np.float32),
        np.ascontiguousarray(iou_off, dtype=np.int64),
        np.ascontiguousarray(nd, dtype=np.int64),
        np.ascontiguousarray(ng, dtype=np.int64),
        np.ascontiguousarray(det_off, dtype=np.int64),
        np.ascontiguousarray(gt_off, dtype=np.int64),
        np.ascontiguousarray(gt_ignore, dtype=np.uint8),
        np.ascontiguousarray(iou_thresholds, dtype=np.float64),
        T,
        A,
        len(nd),
        total_det,
        total_gt,
        out,
        scratch,
    )
    return out.astype(bool)
