/* Greedy COCO detection-to-ground-truth matching over ragged cells.
 *
 * Equivalent of the matching step of the COCO evaluation protocol
 * (reference torchmetrics/detection/mean_ap.py:421/:513, itself following
 * pycocotools): per (area-range, IoU-threshold, image-class cell), walk
 * detections in descending score order and greedily claim the unmatched,
 * unignored ground truth with the highest IoU; the claim stands when that
 * IoU strictly exceeds the threshold.
 *
 * Layout is CSR over cells: cell c owns dets [det_off[c], det_off[c]+nd[c])
 * and gts [gt_off[c], gt_off[c]+ng[c]); its IoU block is row-major
 * (nd[c] x ng[c]) at ious + iou_off[c]. Complexity is
 * A * T * sum_c(nd_c * ng_c) — the count of REAL pairs, where the padded
 * dense formulation pays for max_nd * max_ng in every cell.
 */
#include <stdint.h>
#include <string.h>

void mtpu_coco_match(
    const float *ious,           /* sum(nd*ng) pair IoUs, cell-major */
    const int64_t *iou_off,      /* n_cells: start of each cell's IoU block */
    const int64_t *nd,           /* n_cells: detections per cell (score-desc) */
    const int64_t *ng,           /* n_cells: ground truths per cell */
    const int64_t *det_off,      /* n_cells: global det start per cell */
    const int64_t *gt_off,       /* n_cells: global gt start per cell */
    const uint8_t *gt_ignore,    /* A x total_gt: area-ignored gts */
    const double *thrs,          /* T IoU thresholds */
    int64_t T,
    int64_t A,
    int64_t n_cells,
    int64_t total_det,
    int64_t total_gt,
    uint8_t *det_matches,        /* out: A x T x total_det, caller-zeroed */
    uint8_t *gt_matched_scratch) /* total_gt bytes of scratch */
{
    for (int64_t a = 0; a < A; ++a) {
        const uint8_t *ign = gt_ignore + a * total_gt;
        for (int64_t t = 0; t < T; ++t) {
            const double thr = thrs[t];
            uint8_t *outm = det_matches + (a * T + t) * total_det;
            memset(gt_matched_scratch, 0, (size_t)total_gt);
            for (int64_t c = 0; c < n_cells; ++c) {
                const int64_t ndc = nd[c], ngc = ng[c];
                if (!ndc || !ngc)
                    continue;
                const float *M = ious + iou_off[c];
                const uint8_t *gi = ign + gt_off[c];
                uint8_t *gm = gt_matched_scratch + gt_off[c];
                uint8_t *od = outm + det_off[c];
                for (int64_t d = 0; d < ndc; ++d) {
                    const float *row = M + d * ngc;
                    float best = 0.0f;
                    int64_t best_g = -1;
                    for (int64_t g = 0; g < ngc; ++g) {
                        if (gm[g] || gi[g])
                            continue;
                        /* strict > keeps the FIRST maximum, matching
                         * numpy argmax tie-breaking */
                        if (row[g] > best) {
                            best = row[g];
                            best_g = g;
                        }
                    }
                    if (best_g >= 0 && best > thr) {
                        od[d] = 1;
                        gm[best_g] = 1;
                    }
                }
            }
        }
    }
}
