/* Unit-cost Levenshtein distance over integer-encoded token sequences.
 *
 * Native counterpart of the numpy row-DP in functional/text/helper.py
 * (reference algorithm: torchmetrics functional/text/helper.py:333-355).
 * One rolling row, O(min-row) memory, branch-light inner loop. The batch
 * entry point amortizes the FFI crossing over a whole corpus: sequences are
 * passed flattened with an offsets array (CSR-style), one call per update.
 */
#include <stdint.h>
#include <stdlib.h>

int64_t mtpu_edit_distance(const int64_t *a, int64_t n,
                           const int64_t *b, int64_t m) {
    if (m == 0) return n;
    if (n == 0) return m;
    int64_t *row = (int64_t *)malloc((size_t)(m + 1) * sizeof(int64_t));
    if (!row) return -1;
    for (int64_t j = 0; j <= m; j++) row[j] = j;
    for (int64_t i = 1; i <= n; i++) {
        int64_t diag = row[0];
        int64_t ai = a[i - 1];
        row[0] = i;
        for (int64_t j = 1; j <= m; j++) {
            int64_t sub = diag + (ai != b[j - 1]);
            int64_t del = row[j] + 1;
            int64_t ins = row[j - 1] + 1;
            diag = row[j];
            int64_t best = sub < del ? sub : del;
            row[j] = best < ins ? best : ins;
        }
    }
    int64_t out = row[m];
    free(row);
    return out;
}

void mtpu_edit_distance_batch(const int64_t *flat_a, const int64_t *off_a,
                              const int64_t *flat_b, const int64_t *off_b,
                              int64_t n_pairs, int64_t *out) {
    for (int64_t p = 0; p < n_pairs; p++) {
        out[p] = mtpu_edit_distance(flat_a + off_a[p], off_a[p + 1] - off_a[p],
                                    flat_b + off_b[p], off_b[p + 1] - off_b[p]);
    }
}
