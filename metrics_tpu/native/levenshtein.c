/* Unit-cost Levenshtein distance over integer-encoded token sequences.
 *
 * Native counterpart of the numpy row-DP in functional/text/helper.py
 * (reference algorithm: torchmetrics functional/text/helper.py:333-355).
 * One rolling row, O(min-row) memory, branch-light inner loop. The batch
 * entry point amortizes the FFI crossing over a whole corpus: sequences are
 * passed flattened with an offsets array (CSR-style), one call per update.
 */
#include <stdint.h>
#include <stdlib.h>

int64_t mtpu_edit_distance(const int64_t *a, int64_t n,
                           const int64_t *b, int64_t m) {
    if (m == 0) return n;
    if (n == 0) return m;
    int64_t *row = (int64_t *)malloc((size_t)(m + 1) * sizeof(int64_t));
    if (!row) return -1;
    for (int64_t j = 0; j <= m; j++) row[j] = j;
    for (int64_t i = 1; i <= n; i++) {
        int64_t diag = row[0];
        int64_t ai = a[i - 1];
        row[0] = i;
        for (int64_t j = 1; j <= m; j++) {
            int64_t sub = diag + (ai != b[j - 1]);
            int64_t del = row[j] + 1;
            int64_t ins = row[j - 1] + 1;
            diag = row[j];
            int64_t best = sub < del ? sub : del;
            row[j] = best < ins ? best : ins;
        }
    }
    int64_t out = row[m];
    free(row);
    return out;
}

void mtpu_edit_distance_batch(const int64_t *flat_a, const int64_t *off_a,
                              const int64_t *flat_b, const int64_t *off_b,
                              int64_t n_pairs, int64_t *out) {
    for (int64_t p = 0; p < n_pairs; p++) {
        out[p] = mtpu_edit_distance(flat_a + off_a[p], off_a[p + 1] - off_a[p],
                                    flat_b + off_b[p], off_b[p + 1] - off_b[p]);
    }
}

/* ---- string-in batch: tokenize + encode + DP in ONE crossing ------------
 *
 * The WER-family hot path. Python-side per-token interning dominated the
 * corpus cost (measured ~85% of a 10k-pair WER compute), so the whole
 * prep moves here: callers pass the raw UTF-8 corpus bytes with per-string
 * offsets, and the kernel tokenizes, encodes, and runs the DP without any
 * Python per-token work.
 *
 * mode 0 (chars): the edit alphabet is Unicode code points (CER semantics,
 *   matching Python list(s)).
 * mode 1 (words): strings are split on the exact CPython str.split()
 *   whitespace set and each token is FNV-1a-64 hashed over its UTF-8
 *   bytes. Only within-pair equality matters, so a 64-bit hash stands in
 *   for interning (collision odds ~ (tokens/pair)^2 / 2^64 — negligible).
 *
 * Outputs per pair: edit distance and both sides' unit counts (tokens or
 * code points), which are the sufficient statistics for WER/MER/WIL/WIP/CER.
 */

/* CPython str.split() whitespace: Unicode Zs plus bidi WS/B/S classes. */
static int mtpu_is_pyspace(uint32_t cp) {
    if (cp < 0x80)
        return (cp >= 0x09 && cp <= 0x0D) || (cp >= 0x1C && cp <= 0x1F) || cp == 0x20;
    switch (cp) {
        case 0x85: case 0xA0: case 0x1680: case 0x2028: case 0x2029:
        case 0x202F: case 0x205F: case 0x3000:
            return 1;
        default:
            return cp >= 0x2000 && cp <= 0x200A;
    }
}

/* Decode one UTF-8 code point (input produced by Python's encoder, so it
 * is well-formed); returns bytes consumed. */
static int64_t mtpu_utf8_next(const uint8_t *s, uint32_t *cp) {
    uint8_t c = s[0];
    if (c < 0x80) { *cp = c; return 1; }
    if (c < 0xE0) { *cp = ((uint32_t)(c & 0x1F) << 6) | (s[1] & 0x3F); return 2; }
    if (c < 0xF0) {
        *cp = ((uint32_t)(c & 0x0F) << 12) | ((uint32_t)(s[1] & 0x3F) << 6) | (s[2] & 0x3F);
        return 3;
    }
    *cp = ((uint32_t)(c & 0x07) << 18) | ((uint32_t)(s[1] & 0x3F) << 12) |
          ((uint32_t)(s[2] & 0x3F) << 6) | (s[3] & 0x3F);
    return 4;
}

/* Encode one string into int64 DP symbols; returns the symbol count. */
static int64_t mtpu_text_encode(const uint8_t *s, int64_t len, int mode, int64_t *out) {
    int64_t n = 0, i = 0;
    if (mode == 0) { /* code points */
        while (i < len) {
            uint32_t cp;
            i += mtpu_utf8_next(s + i, &cp);
            out[n++] = (int64_t)cp;
        }
        return n;
    }
    /* whitespace-delimited tokens, FNV-1a-64 over each token's bytes */
    while (i < len) {
        uint32_t cp;
        int64_t adv = mtpu_utf8_next(s + i, &cp);
        if (mtpu_is_pyspace(cp)) { i += adv; continue; }
        uint64_t h = 0xcbf29ce484222325ULL;
        while (i < len) {
            int64_t start = i;
            adv = mtpu_utf8_next(s + i, &cp);
            if (mtpu_is_pyspace(cp)) break;
            for (int64_t k = start; k < start + adv; k++)
                h = (h ^ s[k]) * 0x100000001b3ULL;
            i += adv;
        }
        out[n++] = (int64_t)h;
    }
    return n;
}

/* Returns 0 on success, -1 on allocation failure. */
int64_t mtpu_text_dist_batch(const uint8_t *bytes_a, const int64_t *off_a,
                             const uint8_t *bytes_b, const int64_t *off_b,
                             int64_t n_pairs, int64_t mode,
                             int64_t *dist, int64_t *cnt_a, int64_t *cnt_b) {
    int64_t cap_a = 0, cap_b = 0;
    for (int64_t p = 0; p < n_pairs; p++) { /* symbols <= bytes, so size by bytes */
        int64_t la = off_a[p + 1] - off_a[p], lb = off_b[p + 1] - off_b[p];
        if (la > cap_a) cap_a = la;
        if (lb > cap_b) cap_b = lb;
    }
    int64_t *sym_a = (int64_t *)malloc((size_t)(cap_a ? cap_a : 1) * sizeof(int64_t));
    int64_t *sym_b = (int64_t *)malloc((size_t)(cap_b ? cap_b : 1) * sizeof(int64_t));
    if (!sym_a || !sym_b) { free(sym_a); free(sym_b); return -1; }
    int64_t rc = 0;
    for (int64_t p = 0; p < n_pairs; p++) {
        int64_t na = mtpu_text_encode(bytes_a + off_a[p], off_a[p + 1] - off_a[p], (int)mode, sym_a);
        int64_t nb = mtpu_text_encode(bytes_b + off_b[p], off_b[p + 1] - off_b[p], (int)mode, sym_b);
        cnt_a[p] = na;
        cnt_b[p] = nb;
        dist[p] = mtpu_edit_distance(sym_a, na, sym_b, nb);
        if (dist[p] < 0) { rc = -1; break; }
    }
    free(sym_a);
    free(sym_b);
    return rc;
}
