"""Aggregation metrics: running max/min/sum/cat/mean over raw values.

Equivalent surface to the reference's ``torchmetrics/aggregation.py``
(``BaseAggregator`` :24, ``MaxMetric`` :101, ``MinMetric`` :158, ``SumMetric``
:215, ``CatMetric`` :271, ``MeanMetric`` :328).
"""
from typing import Any, Callable, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

_ERROR_INERT_WARNED = False


def _warn_error_inert_under_trace() -> None:
    """One-time trace-time heads-up: ``nan_strategy='error'`` cannot raise on
    traced data, so jitted updates silently pass NaNs through. Armed as a real
    checkify guard by ``metrics_tpu.debug_checks(True)``."""
    global _ERROR_INERT_WARNED
    if not _ERROR_INERT_WARNED:
        _ERROR_INERT_WARNED = True
        rank_zero_warn(
            "nan_strategy='error' is inert under jit/scan/shard_map: a traced update cannot raise on"
            " data, so NaNs pass through silently. Enable metrics_tpu.debug_checks(True) and run the"
            " step under jax.experimental.checkify to surface them.",
            UserWarning,
        )


class BaseAggregator(Metric):
    """Base for aggregation metrics: one state, a NaN strategy, scalar-or-array input.

    Args:
        fn: reduction spec for the state ("sum"/"max"/"min"/"cat").
        default_value: reset value for the state.
        nan_strategy: "error" | "warn" | "ignore" | float (impute value).
    """

    is_differentiable = None
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        # identity of the aggregation, for exact NaN-dropping under jit:
        # imputing it makes a NaN row a no-op for max/min/sum
        self._nan_identity = {"max": -jnp.inf, "min": jnp.inf, "sum": 0.0}.get(fn)
        self.add_state("value", default=default_value, dist_reduce_fx=fn)

    def _cast_and_nan_check_input(self, x: Union[float, Array]) -> Array:
        """Cast input to float array and apply the NaN strategy
        (reference ``aggregation.py:72``)."""
        if not isinstance(x, (jnp.ndarray, jax.Array)):
            x = jnp.asarray(x, dtype=jnp.float32)
        x = x.astype(jnp.float32) if not jnp.issubdtype(x.dtype, jnp.floating) else x
        nans = jnp.isnan(x)
        if isinstance(x, jax.core.Tracer):
            # inside jit/scan/shard_map the host-side branch below cannot run
            # (data-dependent bool + dynamic-shape filtering). Float
            # imputation stays exact via `where`; warn/ignore impute the
            # aggregation identity, which is exactly "drop the row" for
            # max/min/sum (MeanMetric overrides update with the weighted
            # equivalent; CatMetric cannot drop rows under a trace and
            # "error" cannot raise on data — those pass NaNs through).
            if isinstance(self.nan_strategy, float):
                x = jnp.where(nans, jnp.asarray(self.nan_strategy, dtype=x.dtype), x)
            elif self.nan_strategy in ("warn", "ignore") and self._nan_identity is not None:
                x = jnp.where(nans, jnp.asarray(self._nan_identity, dtype=x.dtype), x)
            elif self.nan_strategy == "error":
                from metrics_tpu.utilities.debug import debug_checks_enabled

                if debug_checks_enabled():
                    from jax.experimental import checkify

                    checkify.check(~jnp.any(nans), "Encountered `nan` values in tensor")
                else:
                    _warn_error_inert_under_trace()
            return x.astype(jnp.float32)
        if bool(nans.any()):
            if self.nan_strategy == "error":
                raise RuntimeError("Encountered `nan` values in tensor")
            if self.nan_strategy == "warn":
                rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
                x = x[~nans]
            elif self.nan_strategy == "ignore":
                x = x[~nans]
            else:
                x = jnp.where(nans, jnp.asarray(self.nan_strategy, dtype=x.dtype), x)
        return x.astype(jnp.float32)

    def update(self, value: Union[float, Array]) -> None:  # noqa: D102
        pass

    def compute(self) -> Array:
        return self.value


class MaxMetric(BaseAggregator):
    """Running maximum (reference ``aggregation.py:101``)."""

    full_state_update = False

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", -jnp.asarray(jnp.inf), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = jnp.maximum(self.value, value.max())


class MinMetric(BaseAggregator):
    """Running minimum (reference ``aggregation.py:158``)."""

    full_state_update = False

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = jnp.minimum(self.value, value.min())


class SumMetric(BaseAggregator):
    """Running sum (reference ``aggregation.py:215``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = self.value + value.sum()


class CatMetric(BaseAggregator):
    """Concatenation of all seen values (reference ``aggregation.py:271``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value.append(value)

    def compute(self) -> Array:
        if isinstance(self.value, list) and self.value:
            return dim_zero_cat(self.value)
        return self.value


class MeanMetric(BaseAggregator):
    """Weighted running mean (reference ``aggregation.py:328``)."""

    supports_sample_weights = True  # update(value, weight): weight==c equals c repeats

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, **kwargs)
        self.add_state("weight", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        # Broadcast BEFORE the NaN strategy so value/weight stay aligned when
        # rows are dropped (independent filtering would misalign them).
        value = jnp.asarray(value, dtype=jnp.float32) if not isinstance(value, (jnp.ndarray, jax.Array)) else value
        weight = jnp.asarray(weight, dtype=jnp.float32) if not isinstance(weight, (jnp.ndarray, jax.Array)) else weight
        weight = jnp.broadcast_to(weight, value.shape)
        nans = jnp.isnan(value) | jnp.isnan(weight.astype(jnp.float32))
        if isinstance(value, jax.core.Tracer) or isinstance(weight, jax.core.Tracer):
            # trace-safe path (see _cast_and_nan_check_input): float
            # imputation via where; warn/ignore zero out both value and
            # weight on NaN rows — the exact weighted-mean equivalent of
            # dropping them; "error" cannot raise on data under a trace
            if isinstance(self.nan_strategy, float):
                fill = jnp.asarray(self.nan_strategy, dtype=jnp.float32)
                value = jnp.where(jnp.isnan(value), fill, value)
                weight = jnp.where(jnp.isnan(weight.astype(jnp.float32)), fill, weight)
            elif self.nan_strategy in ("warn", "ignore"):
                value = jnp.where(nans, 0.0, value)
                weight = jnp.where(nans, 0.0, weight.astype(jnp.float32))
            elif self.nan_strategy == "error":
                from metrics_tpu.utilities.debug import debug_checks_enabled

                if debug_checks_enabled():
                    from jax.experimental import checkify

                    checkify.check(~jnp.any(nans), "Encountered `nan` values in tensor")
                else:
                    _warn_error_inert_under_trace()
        elif bool(nans.any()):
            if self.nan_strategy == "error":
                raise RuntimeError("Encountered `nan` values in tensor")
            if self.nan_strategy in ("warn", "ignore"):
                if self.nan_strategy == "warn":
                    rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
                value, weight = value[~nans], weight[~nans]
            else:
                fill = jnp.asarray(self.nan_strategy, dtype=jnp.float32)
                value = jnp.where(jnp.isnan(value), fill, value)
                weight = jnp.where(jnp.isnan(weight.astype(jnp.float32)), fill, weight)
        value = value.astype(jnp.float32)
        weight = weight.astype(jnp.float32)
        if value.size == 0:
            return
        self.value = self.value + (value * weight).sum()
        self.weight = self.weight + weight.sum()

    def compute(self) -> Array:
        return self.value / self.weight
