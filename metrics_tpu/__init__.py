"""metrics_tpu: TPU-native machine-learning metrics (JAX/XLA/pallas).

A from-scratch re-design of the TorchMetrics capability surface
(`/root/reference`, v0.9.0dev) for TPU: metric state lives as pytrees of jnp
arrays in HBM, update/compute are jit-traceable XLA computations, and
distributed synchronization lowers to mesh collectives
(psum/pmin/pmax/all_gather) over ICI/DCN.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

__version__ = "0.3.0"

from metrics_tpu.utilities.compat import install_jax_compat  # noqa: E402

install_jax_compat()

from metrics_tpu import functional  # noqa: E402, F401
from metrics_tpu.aggregation import CatMetric, MaxMetric, MeanMetric, MinMetric, SumMetric  # noqa: E402, F401
from metrics_tpu.classification import (  # noqa: E402, F401
    AUC,
    AUROC,
    Accuracy,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    CalibrationError,
    CoverageError,
    HingeLoss,
    KLDivergence,
    LabelRankingAveragePrecision,
    LabelRankingLoss,
    PrecisionRecallCurve,
    ROC,
    CohenKappa,
    ConfusionMatrix,
    F1Score,
    FBetaScore,
    HammingDistance,
    JaccardIndex,
    MatthewsCorrCoef,
    Precision,
    Recall,
    Specificity,
    StatScores,
)
from metrics_tpu.audio import (  # noqa: E402, F401
    PermutationInvariantTraining,
    PerceptualEvaluationSpeechQuality,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402, F401
from metrics_tpu.detection import MeanAveragePrecision  # noqa: E402, F401
from metrics_tpu.image import (  # noqa: E402, F401
    ErrorRelativeGlobalDimensionlessSynthesis,
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    LearnedPerceptualImagePatchSimilarity,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.metric import CompositionalMetric, Metric  # noqa: E402, F401
from metrics_tpu.regression import (  # noqa: E402, F401
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)
from metrics_tpu.retrieval import (  # noqa: E402, F401
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRPrecision,
    RetrievalRecall,
)
from metrics_tpu.text import (  # noqa: E402, F401
    BERTScore,
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    ExtendedEditDistance,
    MatchErrorRate,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from metrics_tpu import engine  # noqa: E402, F401
from metrics_tpu import experiment  # noqa: E402, F401
from metrics_tpu import ft  # noqa: E402, F401
from metrics_tpu import llm  # noqa: E402, F401
from metrics_tpu import obs  # noqa: E402, F401
from metrics_tpu import serve  # noqa: E402, F401
from metrics_tpu import streaming  # noqa: E402, F401
from metrics_tpu.metric import register_state_reduction  # noqa: E402, F401
from metrics_tpu.steps import (  # noqa: E402, F401
    make_collection_epoch,
    make_collection_step,
    make_epoch,
    make_step,
    make_stream_step,
    overlap_epoch_sync,
    prefetch_to_device,
)
from metrics_tpu.utilities.sharding import StateShardSpec  # noqa: E402, F401
from metrics_tpu.utilities.debug import debug_checks  # noqa: E402, F401
from metrics_tpu.wrappers import (  # noqa: E402, F401
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)

__all__ = [
    "AUC",
    "AUROC",
    "Accuracy",
    "AveragePrecision",
    "BinnedAveragePrecision",
    "BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision",
    "CalibrationError",
    "CoverageError",
    "HingeLoss",
    "KLDivergence",
    "LabelRankingAveragePrecision",
    "LabelRankingLoss",
    "PrecisionRecallCurve",
    "ROC",
    "CohenKappa",
    "ConfusionMatrix",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "JaccardIndex",
    "MatthewsCorrCoef",
    "Precision",
    "Recall",
    "Specificity",
    "BootStrapper",
    "CatMetric",
    "ClasswiseWrapper",
    "CosineSimilarity",
    "ExplainedVariance",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "PearsonCorrCoef",
    "R2Score",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "WeightedMeanAbsolutePercentageError",
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "FrechetInceptionDistance",
    "InceptionScore",
    "KernelInceptionDistance",
    "LearnedPerceptualImagePatchSimilarity",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "UniversalImageQualityIndex",
    "CompositionalMetric",
    "MetricCollection",
    "MetricTracker",
    "MinMaxMetric",
    "make_collection_epoch",
    "make_collection_step",
    "make_epoch",
    "make_step",
    "make_stream_step",
    "overlap_epoch_sync",
    "prefetch_to_device",
    "StateShardSpec",
    "register_state_reduction",
    "debug_checks",
    "engine",
    "experiment",
    "ft",
    "llm",
    "obs",
    "serve",
    "streaming",
    "MultioutputWrapper",
    "MaxMetric",
    "MeanAveragePrecision",
    "MeanMetric",
    "Metric",
    "MinMetric",
    "BERTScore",
    "BLEUScore",
    "CharErrorRate",
    "CHRFScore",
    "ExtendedEditDistance",
    "MatchErrorRate",
    "ROUGEScore",
    "SacreBLEUScore",
    "SQuAD",
    "TranslationEditRate",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
    "PermutationInvariantTraining",
    "PerceptualEvaluationSpeechQuality",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "ShortTimeObjectiveIntelligibility",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalRPrecision",
    "RetrievalRecall",
    "StatScores",
    "SumMetric",
    "functional",
]
