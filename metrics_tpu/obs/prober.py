"""Synthetic canary probes: black-box correctness through the real path.

Every other signal in :mod:`metrics_tpu.obs` is white-box — the serving
tier reporting on itself. A fleet that silently folds wrong answers
keeps all of those green. The :class:`CanaryProber` closes that gap by
continuously shipping **known-answer payloads** through a reserved
``__canary__`` tenant on the production ingest path — same wire
encoding, same dedup journal, same fold kernels — and verifying the
aggregator's ``/query`` answer **bitwise** against a locally-computed
oracle.

The oracle argument (documented in ``docs/observability.md`` §10): the
canary schema is two :class:`~metrics_tpu.aggregation.SumMetric` s fed
small integers, so every cumulative total is exactly representable in
float32 and the fold is associative bitwise — the probe's expected
answer is not a tolerance band but THE answer, and any deviation
(a corrupted leaf, a double-fold, a stale-view read) is a mismatch, not
noise. Verification keys on the aggregator's **accepted watermark** for
the probe client: a ship lost in flight leaves the root at an older
watermark whose values must still match that step's oracle exactly, so
wire chaos cannot fake a red canary — only a wrong fold can.

Probes record ``probe.probes``/``probe.results{verdict=}``/
``probe.round_trip_ms``/``probe.healthy`` per node; the match/mismatch
verdict counters are the **correctness SLI** the ``canary``
:class:`~metrics_tpu.obs.slo.SLODef` consumes, and
``/healthz/ready`` surfaces :meth:`CanaryProber.status` beside the
history alerts. One prober per aggregator: the reserved tenant's state
on a node must come only from its own prober or the oracle comparison
would be comparing against someone else's probes (enforced at attach).
"""
import threading
import time
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

from metrics_tpu.obs import registry as _reg

__all__ = ["CANARY_TENANT", "CanaryProber", "canary_metrics", "reset"]

# the reserved synthetic tenant (also re-exported by metrics_tpu.obs.slo)
CANARY_TENANT = "__canary__"

# oracle entries retained per prober: verification needs the oracle at
# whatever watermark the aggregator last ACCEPTED, which trails the ship
# sequence by at most the in-flight window — 256 is generous headroom
_ORACLE_CAP = 256

_PROBERS: "weakref.WeakSet" = weakref.WeakSet()


def canary_metrics() -> Any:
    """The canary tenant's schema: an integer-fed checksum sum plus a
    payload counter — exact in float32, hence bitwise-verifiable. Pass
    this factory wherever tenant dicts are built if a node must have the
    tenant registered before its prober attaches."""
    from metrics_tpu.aggregation import SumMetric
    from metrics_tpu.collections import MetricCollection

    return MetricCollection({"checksum": SumMetric(), "payloads": SumMetric()})


class CanaryProber:
    """Ships known-answer payloads through ``aggregator``'s real ingest
    path and verifies query answers bitwise against the local oracle.

    Args:
        aggregator: the :class:`~metrics_tpu.serve.Aggregator` under
            test. The reserved tenant is registered here if missing, and
            the prober attaches as ``aggregator._canary_prober`` (one
            per aggregator — a second attach raises).
        ingest: optional override for payload delivery (e.g. an HTTP
            client posting to the node's ``/ingest``). Defaults to
            calling ``aggregator.ingest`` in-process. Whatever the
            transport, payloads must land on **this** aggregator —
            verification reads its accepted watermark.
        client_id: wire identity of the probe client; defaults to
            ``canary:<node>``.
    """

    def __init__(
        self,
        aggregator: Any,
        *,
        ingest: Optional[Callable[[bytes], Any]] = None,
        client_id: Optional[str] = None,
    ) -> None:
        from metrics_tpu.serve.aggregator import ServeError

        if getattr(aggregator, "_canary_prober", None) is not None:
            raise ServeError(
                f"aggregator {aggregator.name!r} already has a canary prober;"
                " the reserved tenant's state must come from exactly one"
                " oracle or bitwise verification is meaningless"
            )
        self._aggregator = aggregator
        self._ingest = ingest if ingest is not None else aggregator.ingest
        self._client = str(client_id) if client_id else f"canary:{aggregator.name}"
        if CANARY_TENANT not in aggregator.tenants():
            aggregator.register_tenant(CANARY_TENANT, canary_metrics)
        self._lock = threading.Lock()
        self._collection = canary_metrics()
        self._seq = 0
        self._total = 0.0
        self._count = 0.0
        # seq -> (cumulative checksum, cumulative payload count)
        self._oracle: Dict[int, Tuple[float, float]] = {}
        self._matches = 0
        self._mismatches = 0
        self._pending = 0
        self._last_verdict: Optional[str] = None
        self._last_rtt_ms: Optional[float] = None
        aggregator._canary_prober = self
        _PROBERS.add(self)

    # -- shipping --------------------------------------------------------

    def _next_value(self) -> float:
        # deterministic small integers: cumulative sums stay exactly
        # representable in float32 for ~160k probes (sum < 2**24)
        return float((self._seq * 37) % 101 + 1)

    def ship(self) -> bytes:
        """Encode and deliver the next cumulative probe payload; returns
        the wire blob (tests replay it through chaos planners)."""
        import jax.numpy as jnp

        from metrics_tpu.serve.wire import encode_state

        with self._lock:
            value = self._next_value()
            self._collection["checksum"].update(jnp.asarray(value))
            self._collection["payloads"].update(jnp.asarray(1.0))
            self._total += value
            self._count += 1.0
            seq = self._seq
            self._oracle[seq] = (self._total, self._count)
            while len(self._oracle) > _ORACLE_CAP:
                del self._oracle[min(self._oracle)]
            self._seq += 1
            blob = encode_state(
                self._collection,
                tenant=CANARY_TENANT,
                client_id=self._client,
                watermark=(0, seq),
                meta={"canary": True},
            )
        self._ingest(blob)
        return blob

    # -- verification ----------------------------------------------------

    def verify(self) -> str:
        """Compare the aggregator's answer for the canary tenant bitwise
        against the oracle at its **accepted** watermark. Returns the
        verdict: ``"match"`` | ``"mismatch"`` | ``"pending"`` (nothing
        accepted yet, or the accepted step already aged out of the
        oracle ring — neither is evidence of a wrong fold)."""
        wm = self._aggregator.client_watermark(CANARY_TENANT, self._client)
        verdict = "pending"
        if wm is not None:
            with self._lock:
                expected = self._oracle.get(int(wm[1]))
            if expected is not None:
                answer = self._aggregator.query(CANARY_TENANT)["values"]
                got_sum = float(answer["checksum"]["value"])
                got_count = float(answer["payloads"]["value"])
                ok = got_sum == expected[0] and got_count == expected[1]
                verdict = "match" if ok else "mismatch"
        with self._lock:
            if verdict == "match":
                self._matches += 1
            elif verdict == "mismatch":
                self._mismatches += 1
            else:
                self._pending += 1
            self._last_verdict = verdict
            healthy = self._mismatches == 0
        if _reg.enabled():
            _reg.inc("probe.results", node=self._aggregator.name, verdict=verdict)
            _reg.set_gauge(
                "probe.healthy", 1.0 if healthy else 0.0, node=self._aggregator.name
            )
        return verdict

    def probe(self, flush: bool = True) -> str:
        """One full round trip: ship, (optionally) flush so the payload
        folds, verify. Records ``probe.probes`` and the round-trip
        latency histogram; returns the verdict."""
        t0 = time.perf_counter()
        self.ship()
        if flush:
            self._aggregator.flush()
        verdict = self.verify()
        rtt_ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self._last_rtt_ms = rtt_ms
        if _reg.enabled():
            _reg.inc("probe.probes", node=self._aggregator.name)
            _reg.observe("probe.round_trip_ms", rtt_ms, node=self._aggregator.name)
        return verdict

    # -- failover --------------------------------------------------------

    def rebind(
        self, aggregator: Any, *, ingest: Optional[Callable[[bytes], Any]] = None
    ) -> None:
        """Follow a checkpoint kill+restore: re-attach to the revived
        aggregator, keeping the ship sequence, cumulative collection and
        oracle ring. The revived dedup journal remembers the old client
        watermarks, so a FRESH prober's ships would all shed as stale
        duplicates and its empty oracle could never verify again — the
        surviving prober IS the oracle continuity across the restore.
        One-per-aggregator is enforced on the new node; the old node, if
        still alive, releases its slot."""
        from metrics_tpu.serve.aggregator import ServeError

        if getattr(aggregator, "_canary_prober", None) not in (None, self):
            raise ServeError(
                f"aggregator {aggregator.name!r} already has a canary prober;"
                " rebind the existing one or detach it first"
            )
        with self._lock:
            old = self._aggregator
            if getattr(old, "_canary_prober", None) is self:
                old._canary_prober = None
            self._aggregator = aggregator
            self._ingest = ingest if ingest is not None else aggregator.ingest
            aggregator._canary_prober = self

    # -- reporting -------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The ``/healthz/ready`` detail block: healthy means zero
        bitwise mismatches since the stats were last reset."""
        with self._lock:
            return {
                "node": self._aggregator.name,
                "tenant": CANARY_TENANT,
                "client": self._client,
                "probes_shipped": self._seq,
                "matches": self._matches,
                "mismatches": self._mismatches,
                "pending": self._pending,
                "healthy": self._mismatches == 0,
                "last_verdict": self._last_verdict,
                "last_rtt_ms": self._last_rtt_ms,
            }

    def reset_stats(self) -> None:
        """Zero the verdict tallies (:func:`metrics_tpu.obs.reset` calls
        this on every live prober). The ship sequence, the cumulative
        collection and the oracle ring survive — they are wire state
        shared with the aggregator's dedup journal, and rewinding them
        would make every post-reset ship a dropped duplicate."""
        with self._lock:
            self._matches = 0
            self._mismatches = 0
            self._pending = 0
            self._last_verdict = None
            self._last_rtt_ms = None


def reset() -> None:
    """Clear verdict bookkeeping on every live prober — the hook
    :func:`metrics_tpu.obs.reset` calls alongside the registry."""
    for prober in list(_PROBERS):
        prober.reset_stats()
