"""Fleet-health monitoring over the obs registry: stragglers, storms, clamps.

The registry already collects the raw signals — sync latency histograms
and the arrival-skew gauge from :mod:`metrics_tpu.utilities.distributed`,
per-step trace counters from :mod:`metrics_tpu.obs.recompile`, buffer
clamp-risk counters, and the fault-tolerance subsystem's degraded-sync
counts. :class:`HealthMonitor` turns them into verdicts: call
:meth:`~HealthMonitor.check` periodically (per epoch is the natural
cadence) and it classifies the current window into named conditions,
raises a one-shot ``rank_zero_warn`` per condition kind, and counts
``health.checks{monitor=}`` / ``health.alerts{monitor=,kind=}`` so the
alert history rides the same :func:`metrics_tpu.obs.snapshot` as the
metrics it protects — the :class:`~metrics_tpu.streaming.DriftMonitor`
pattern, applied to the fleet instead of the data distribution.

Conditions (each independently armable):

* ``straggler`` — the ``sync.arrival_skew_ms`` gauge (this host's wait in
  the pre-gather barrier — its lead over the slowest peer) exceeds
  ``skew_threshold_ms``.
* ``sync_latency`` — p95 of the ``sync.latency_ms{op=gather_all_tensors}``
  histogram exceeds ``sync_p95_ms``.
* ``recompile_storm`` — some step's ``step.traces{step=}`` counter reached
  ``recompile_threshold`` (default: the registry's
  ``recompile_warn_threshold``); catches drift on steps whose own one-shot
  warning already fired and was lost in logs.
* ``clamp_risk`` — ``capacity_buffer.clamp_risk_appends`` or
  ``capacity_buffer.eager_overflows`` is nonzero: some buffer-backed
  metric may be silently truncating samples.
* ``degraded_sync`` — any ``ft.degraded_syncs`` series fired: some host
  computed over local-only state and cross-host values are no longer
  comparable.

Serve-fleet conditions (default disarmed — arm them on processes hosting a
:class:`~metrics_tpu.serve.Aggregator`; node-level supervision with a
repair arm lives in :class:`metrics_tpu.serve.resilience.Supervisor`,
these are the registry-only verdicts):

* ``queue_saturation`` — the worst ``serve.queue_depth`` gauge series
  (one per aggregator node) at/over ``queue_depth_threshold``: ingest is
  outrunning the fold and backpressure/shedding is imminent.
* ``quarantine`` — the ``serve.clients_quarantined`` gauges report a
  client currently locked out for poisoned state, pending operator
  action. Current state, not the cumulative ``serve.quarantined``
  counter: a lifted quarantine stops firing.
* ``circuit_open`` — the ``serve.circuits_open`` gauges report a circuit
  currently open: some client is being refused for repeated invalid
  payloads. Current state, not the cumulative open-transition counter: a
  circuit that probes back closed reads healthy again.
* ``peer_stale`` — the worst ``serve.peer_staleness_ms`` gauge (one per
  cross-region replication peer, exported by
  :meth:`metrics_tpu.serve.region.Region.peer_staleness_s`) exceeds
  ``peer_staleness_ms``: some peer region's replica is aging and global
  ``/query`` answers are drifting toward local-only.
* ``partition_detected`` — a ``serve.peers_unreachable`` gauge is
  nonzero: a region's replication sweeps are actively FAILING against
  one or more peers (connection refused / dead region), the sender-side
  half of a DCN partition. The receiver-side half is ``peer_stale`` —
  a black-holing partition drops ships without failing them, so arm
  both.
* ``fenced_zombie`` — the ``serve.fenced_ships`` counter fired: a
  superseded pre-failover root is still shipping and being refused by
  the generation fence. The data is safe (that is the fence's job);
  the alert exists because a zombie burning its backoff schedule
  against 4xx responses forever deserves decommissioning, not silence.
* ``history_alert`` — a ``history.alert_active`` gauge is nonzero: a
  root-evaluated alert rule (:class:`metrics_tpu.serve.history.AlertRule`
  / :class:`~metrics_tpu.serve.history.DriftRule`, checked against every
  freshly cut retention-ring interval) is currently firing. Current
  state, not the cumulative ``history.alerts`` counter: a metric that
  recovers stops firing here.
* ``slo_burn`` — an ``slo.alert_active`` gauge is nonzero: some tenant's
  dual-window burn rate (:class:`metrics_tpu.obs.slo.SLOEngine`) is
  currently over its page thresholds. Current state, not the cumulative
  ``slo.alerts`` counter: a tenant whose burn clears stops firing.
* ``canary_mismatch`` — a ``probe.healthy`` gauge reads 0: some node's
  :class:`~metrics_tpu.obs.prober.CanaryProber` saw a bitwise MISMATCH
  between a known-answer probe and the node's ``/query`` answer — the
  one condition here that means answers (not plumbing) are wrong.
* ``rebalance_stuck`` — a ``serve.rebalance_started_ts`` gauge (stamped
  by :class:`metrics_tpu.serve.elastic.ElasticFleet` for the duration of
  every join/drain/split/merge, cleared on completion; the ``node=``
  label names the node being rebalanced, so the alert is actionable) has
  been nonzero for longer than ``rebalance_stuck_s``: a topology mutation is wedged
  mid-flight — clients may be split between their old and new homes until
  it finishes, so a stuck one deserves a page, not patience.

**Fleet mode** (``federated=True``): every condition reads the FEDERATED
view (:func:`metrics_tpu.obs.federated_snapshot` — the local registry
merged with every remote node snapshot the serving tree piggybacked up)
instead of local registry state, so the root's monitor sees a straggler
leaf's skew gauge, the deepest queue anywhere in the tree, and recompile
storms per node (the ``recompile_storm`` probe walks the per-node
snapshots and names the worst node). One extra condition exists only
there:

* ``stale_node`` — some federated node's snapshot is older than
  ``node_staleness_s``: a subtree stopped reporting (partitioned, hung or
  dead) and its metrics are silently aging while the merged view still
  renders them.
"""
import threading
from typing import Any, Dict, List, Optional

from metrics_tpu.obs import registry as _reg

__all__ = ["HealthMonitor"]


class HealthMonitor:
    """One-shot-warning health checks over the live obs registry.

    Args:
        skew_threshold_ms: arm the ``straggler`` condition at this
            cross-host arrival skew (``None`` disarms).
        sync_p95_ms: arm ``sync_latency`` when the eager DCN gather's p95
            exceeds this many milliseconds (``None`` disarms).
        recompile_threshold: arm ``recompile_storm`` at this many tracings
            of one step; ``None`` uses the registry's
            ``recompile_warn_threshold`` at check time.
        clamp_risk: arm the buffer ``clamp_risk`` condition.
        degraded_syncs: arm the ``degraded_sync`` condition.
        queue_depth_threshold: arm the serving-tier ``queue_saturation``
            condition at this ``serve.queue_depth`` gauge value, read as
            the worst node's series (``None`` disarms).
        quarantine: arm the serving-tier ``quarantine`` condition
            (a ``serve.clients_quarantined`` gauge is currently nonzero).
        circuit_open: arm the serving-tier ``circuit_open`` condition
            (a ``serve.circuits_open`` gauge is currently nonzero).
        rebalance_stuck_s: arm the serving-tier ``rebalance_stuck``
            condition when an elastic rebalance has been in flight (its
            ``serve.rebalance_started_ts`` gauge nonzero) for more than
            this many seconds (``None`` disarms).
        peer_staleness_ms: arm the multi-region ``peer_stale`` condition
            when the worst ``serve.peer_staleness_ms`` gauge (a peer
            region's replica age) exceeds this (``None`` disarms).
        partition_detected: arm the multi-region ``partition_detected``
            condition (a ``serve.peers_unreachable`` gauge reports a
            region actively failing to reach peers).
        fenced_zombie: arm the multi-region ``fenced_zombie`` condition
            (the ``serve.fenced_ships`` counter fired: a superseded
            pre-failover root is shipping into the generation fence).
        history_alert: arm the ``history_alert`` condition (a
            ``history.alert_active`` gauge is nonzero: a root-evaluated
            metric alert rule is currently firing over the retention
            ring's interval deltas).
        slo_alert: arm the ``slo_burn`` condition (an ``slo.alert_active``
            gauge is nonzero: some tenant's error-budget burn rate is
            currently over its fast+slow page thresholds).
        canary: arm the ``canary_mismatch`` condition (a ``probe.healthy``
            gauge reads 0: a node's synthetic canary answer diverged
            bitwise from its local oracle).
        federated: read every condition off the federated fleet view
            (local registry merged with the piggybacked per-node
            snapshots) instead of local registry state — the root-of-tree
            monitor configuration.
        node_staleness_s: arm the ``stale_node`` condition when some
            federated node's snapshot is older than this many seconds
            (``None`` disarms; implies reading the federation table).
        name: label on the ``health.*`` counter series.
        warn: emit a one-shot ``rank_zero_warn`` per condition kind.

    Example:
        >>> from metrics_tpu.obs.health import HealthMonitor
        >>> report = HealthMonitor(warn=False).check()
        >>> report["healthy"]
        True
    """

    def __init__(
        self,
        skew_threshold_ms: Optional[float] = 1000.0,
        sync_p95_ms: Optional[float] = None,
        recompile_threshold: Optional[int] = None,
        clamp_risk: bool = True,
        degraded_syncs: bool = True,
        queue_depth_threshold: Optional[float] = None,
        quarantine: bool = False,
        circuit_open: bool = False,
        rebalance_stuck_s: Optional[float] = None,
        peer_staleness_ms: Optional[float] = None,
        partition_detected: bool = False,
        fenced_zombie: bool = False,
        history_alert: bool = False,
        slo_alert: bool = False,
        canary: bool = False,
        federated: bool = False,
        node_staleness_s: Optional[float] = None,
        name: str = "default",
        warn: bool = True,
    ) -> None:
        self.skew_threshold_ms = skew_threshold_ms
        self.sync_p95_ms = sync_p95_ms
        self.recompile_threshold = recompile_threshold
        self.clamp_risk = bool(clamp_risk)
        self.degraded_syncs = bool(degraded_syncs)
        self.queue_depth_threshold = queue_depth_threshold
        self.quarantine = bool(quarantine)
        self.circuit_open = bool(circuit_open)
        self.rebalance_stuck_s = rebalance_stuck_s
        self.peer_staleness_ms = peer_staleness_ms
        self.partition_detected = bool(partition_detected)
        self.fenced_zombie = bool(fenced_zombie)
        self.history_alert = bool(history_alert)
        self.slo_alert = bool(slo_alert)
        self.canary = bool(canary)
        self.federated = bool(federated)
        self.node_staleness_s = node_staleness_s
        self.name = str(name)
        self.warn = bool(warn)
        self._warned_kinds: set = set()
        # per-check read surface: the live registry, or (federated) the
        # merged fleet snapshot — set at the top of check() so every probe
        # in one check reads ONE consistent view. check() holds _check_lock
        # while the views are staged and probed: one monitor wired into
        # both an HTTP health route and a supervisor loop must not have a
        # concurrent check() swap the view mid-probe (checks are cheap, so
        # serializing them costs nothing)
        self._check_lock = threading.Lock()
        self._counters_view: Optional[Dict[str, float]] = None
        self._gauges_view: Optional[Dict[str, float]] = None
        self._hists_view: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------
    # read surface (local registry or federated fleet view)
    # ------------------------------------------------------------------

    def _counters(self) -> Dict[str, float]:
        return self._counters_view if self._counters_view is not None else _reg.counters()

    def _gauges(self) -> Dict[str, float]:
        return self._gauges_view if self._gauges_view is not None else _reg.gauges()

    def _gauge(self, name: str) -> Optional[float]:
        if self._gauges_view is None:
            return _reg.get_gauge(name)
        # federated gauges carry node= labels; a point read becomes the
        # worst (max) across the fleet's series of that family
        series = self._gauge_series(name)
        return max(series, default=None)

    def _counter_sum(self, name: str) -> float:
        if self._counters_view is None:
            return _reg.sum_counter(name)
        prefix = name + "{"
        return sum(v for k, v in self._counters_view.items() if k == name or k.startswith(prefix))

    def _histogram(self, name: str, **labels: Any):
        if self._hists_view is None:
            return _reg.get_histogram(name, **labels)
        hist = self._hists_view.get(_reg._key(name, labels))
        return None if hist is None else _reg.HistogramSnapshot.from_dict(hist)

    # ------------------------------------------------------------------
    # individual condition probes (each returns a detail string or None)
    # ------------------------------------------------------------------

    def _check_straggler(self) -> Optional[str]:
        if self.skew_threshold_ms is None:
            return None
        skew = self._gauge("sync.arrival_skew_ms")
        if skew is not None and skew > self.skew_threshold_ms:
            return (
                f"cross-host arrival skew {skew:.0f} ms > {self.skew_threshold_ms:.0f} ms —"
                + (
                    " some fleet node reaches sync points far ahead of its slowest peer"
                    if self.federated
                    else " this host reaches sync points far ahead of the slowest peer"
                )
            )
        return None

    def _check_sync_latency(self) -> Optional[str]:
        if self.sync_p95_ms is None:
            return None
        hist = self._histogram("sync.latency_ms", op="gather_all_tensors")
        if hist is not None and hist.count and hist.p95 > self.sync_p95_ms:
            return (
                f"eager DCN gather p95 {hist.p95:.0f} ms > {self.sync_p95_ms:.0f} ms"
                f" over {hist.count} gathers"
            )
        return None

    def _check_recompile_storm(self) -> Optional[str]:
        threshold = self.recompile_threshold
        if threshold is None:
            threshold = _reg.get_config("recompile_warn_threshold")
        if not threshold:
            return None
        if self.federated:
            # PER-NODE: fleet counters are summed in the merged view, which
            # would read 16 healthy nodes' one-trace steps as one storming
            # step — walk the per-node snapshots (local + federation table)
            # so the verdict names the node actually storming
            from metrics_tpu.obs import federation as _fed

            per_node = {_reg.node_identity(): {"counters": _reg.counters()}}
            per_node.update(_fed.remote_snapshots())
            worst_detail = None
            storming_nodes = 0
            for node in sorted(per_node):
                detail = self._storm_in(per_node[node].get("counters") or {}, threshold)
                if detail is not None:
                    storming_nodes += 1
                    if worst_detail is None:
                        worst_detail = f"node {node}: {detail}"
            if worst_detail:
                return f"{storming_nodes} fleet node(s) storming — {worst_detail}"
            return None
        return self._storm_in(_reg.counters(), threshold)

    @staticmethod
    def _storm_in(counters: Dict[str, float], threshold: int) -> Optional[str]:
        prefix = "step.traces{"
        storming = {
            key[len(prefix):-1]: int(count)
            for key, count in counters.items()
            if key.startswith(prefix) and count >= threshold
        }
        if storming:
            worst = max(storming, key=storming.get)
            return (
                f"{len(storming)} step(s) at/over {threshold} tracings"
                f" (worst: {worst} x{storming[worst]}) — shape/dtype drift recompiles"
                " a new program per signature"
            )
        return None

    def _check_stale_node(self) -> Optional[str]:
        if self.node_staleness_s is None:
            return None
        from metrics_tpu.obs import federation as _fed

        stale = {
            node: age
            for node, age in _fed.node_ages().items()
            if age > self.node_staleness_s
        }
        if stale:
            worst = max(stale, key=stale.get)
            return (
                f"{len(stale)} federated node(s) have not reported within"
                f" {self.node_staleness_s:.0f}s (worst: {worst},"
                f" {stale[worst]:.0f}s ago) — a partitioned/hung/dead subtree's"
                " metrics are silently aging in the merged view"
            )
        return None

    def _check_clamp_risk(self) -> Optional[str]:
        if not self.clamp_risk:
            return None
        counters = self._counters()
        clamps = counters.get("capacity_buffer.clamp_risk_appends", 0.0)
        overflows = counters.get("capacity_buffer.eager_overflows", 0.0)
        if clamps or overflows:
            return (
                f"capacity-buffer overflow pressure: {int(clamps)} clamp-risk traced"
                f" append(s), {int(overflows)} eager overflow(s) — buffer-backed"
                " metrics may be truncating samples; raise sample_capacity or switch"
                " to a sketch-backed streaming metric"
            )
        return None

    def _check_degraded_sync(self) -> Optional[str]:
        if not self.degraded_syncs:
            return None
        degraded = self._counter_sum("ft.degraded_syncs")
        if degraded:
            return (
                f"{int(degraded)} degraded sync(s): some host fell back to local-only"
                " state after exhausting DCN retries — cross-host metric values are"
                " not comparable for those windows"
            )
        return None

    def _gauge_series(self, name: str) -> List[float]:
        """Every current value of gauge ``name`` across its label series
        (one series per aggregator node in a serving tree — a single
        unlabeled read would be last-writer-wins and an idle node could
        mask a saturated one). In federated mode the series span the whole
        fleet (remote gauges arrive node-labeled), so "deepest queue" is
        the deepest queue ANYWHERE in the tree."""
        prefix = name + "{"
        return [
            value
            for key, value in self._gauges().items()
            if key == name or key.startswith(prefix)
        ]

    def _check_queue_saturation(self) -> Optional[str]:
        if self.queue_depth_threshold is None:
            return None
        depths = self._gauge_series("serve.queue_depth")
        worst = max(depths, default=None)
        if worst is not None and worst >= self.queue_depth_threshold:
            return (
                f"serve ingest queue depth {worst:.0f} >= {self.queue_depth_threshold:.0f} —"
                " ingest is outrunning the fold; backpressure/shedding imminent"
            )
        return None

    def _check_quarantine(self) -> Optional[str]:
        if not self.quarantine:
            return None
        # the CURRENT-state gauge, not the cumulative serve.quarantined
        # counter: an incident resolved by unquarantine() must stop firing
        quarantined = sum(self._gauge_series("serve.clients_quarantined"))
        if quarantined:
            return (
                f"{int(quarantined)} client(s) quarantined for shipping poisoned"
                " (NaN/Inf) state — locked out pending operator unquarantine()"
            )
        return None

    def _check_circuit_open(self) -> Optional[str]:
        if not self.circuit_open:
            return None
        # current-state gauge (serve.circuits_open), not the cumulative
        # open-transition counter: a circuit that probed back to closed
        # must read healthy again
        opened = sum(self._gauge_series("serve.circuits_open"))
        if opened:
            return (
                f"{int(opened)} ingest circuit(s) currently open: some client is"
                " being refused for repeated invalid payloads (serve.circuits_open)"
            )
        return None

    def _check_peer_stale(self) -> Optional[str]:
        if self.peer_staleness_ms is None:
            return None
        # one series per (region, peer) replication edge; the worst age is
        # the verdict, and in federated mode the series span every region
        stale = {
            key: value
            for key, value in self._gauges().items()
            if (key == "serve.peer_staleness_ms" or key.startswith("serve.peer_staleness_ms{"))
            and value > self.peer_staleness_ms
        }
        if stale:
            worst = max(stale, key=stale.get)
            return (
                f"{len(stale)} cross-region replication peer(s) stale beyond"
                f" {self.peer_staleness_ms:.0f} ms (worst: {worst},"
                f" {stale[worst]:.0f} ms) — global /query answers are drifting"
                " toward local-only for the affected regions (partition, dead"
                " peer, or a wedged replication loop)"
            )
        return None

    def _check_partition_detected(self) -> Optional[str]:
        if not self.partition_detected:
            return None
        unreachable = sum(self._gauge_series("serve.peers_unreachable"))
        if unreachable:
            return (
                f"{int(unreachable)} cross-region replication link(s) actively"
                " failing (serve.peers_unreachable) — a DCN partition or dead"
                " region; each side keeps serving local-complete / global-stale"
                " answers, and the next successful cumulative cross-ship repairs"
                " the global views bitwise on heal"
            )
        return None

    def _check_fenced_zombie(self) -> Optional[str]:
        if not self.fenced_zombie:
            return None
        fenced = self._counter_sum("serve.fenced_ships")
        if fenced:
            return (
                f"{int(fenced)} generation-fenced ship(s) refused"
                " (serve.fenced_ships): a superseded pre-failover root is still"
                " shipping — the fence is holding (no state resurrected), but"
                " the zombie should be decommissioned"
            )
        return None

    def _check_history_alert(self) -> Optional[str]:
        if not self.history_alert:
            return None
        # one series per firing (rule, tenant) — the gauge is edge-driven
        # by MetricHistory (1 on healthy→firing, 0 on recovery), so this
        # reads CURRENT alert state, not the cumulative history.alerts count
        firing = sorted(
            key
            for key, value in self._gauges().items()
            if (key == "history.alert_active" or key.startswith("history.alert_active{"))
            and value
        )
        if firing:
            return (
                f"{len(firing)} metric alert rule(s) currently firing at the"
                f" root (worst: {firing[0]}) — an interval delta crossed its"
                " configured threshold or drift test; the firing edge was"
                " warned once and counted under history.alerts{rule=,tenant=}"
            )
        return None

    def _check_slo_burn(self) -> Optional[str]:
        if not self.slo_alert:
            return None
        # one series per firing (tenant, slo) — edge-driven by SLOEngine
        # (1 on clear→firing, 0 on recovery), so this reads CURRENT alert
        # state, not the cumulative slo.alerts count
        firing = sorted(
            key
            for key, value in self._gauges().items()
            if (key == "slo.alert_active" or key.startswith("slo.alert_active{"))
            and value
        )
        if firing:
            return (
                f"{len(firing)} tenant SLO(s) currently burning error budget"
                f" past the fast+slow page thresholds (worst: {firing[0]}) —"
                " the firing edge was warned once and counted under"
                " slo.alerts{tenant=,slo=}; see GET /slo for budgets"
            )
        return None

    def _check_canary_mismatch(self) -> Optional[str]:
        if not self.canary:
            return None
        # probe.healthy is 1 while every verdict matched bitwise, 0 from
        # the first mismatch on — only nodes running a prober export it,
        # so an exact-zero read IS a mismatch, never an idle default
        mismatched = sorted(
            key
            for key, value in self._gauges().items()
            if (key == "probe.healthy" or key.startswith("probe.healthy{"))
            and value == 0.0
        )
        if mismatched:
            return (
                f"{len(mismatched)} node(s) with a canary MISMATCH"
                f" (worst: {mismatched[0]}) — a known-answer probe's /query"
                " answer diverged bitwise from the local oracle: the node is"
                " serving WRONG answers, not merely slow ones"
            )
        return None

    def _check_rebalance_stuck(self) -> Optional[str]:
        if self.rebalance_stuck_s is None:
            return None
        import time

        # serve.rebalance_started_ts carries the WALL-CLOCK start of an
        # in-flight elastic rebalance (0 = idle); nonzero-and-old means a
        # topology mutation is wedged with clients possibly split between
        # their old and new homes. Wall clock because the gauge federates
        # across processes (same tradeoff as the federation captured_at).
        now = time.time()
        stuck = {}
        prefix = "serve.rebalance_started_ts{"
        for key, started in self._gauges().items():
            if not (key == "serve.rebalance_started_ts" or key.startswith(prefix)):
                continue
            if started and now - started > self.rebalance_stuck_s:
                stuck[key] = now - started
        if stuck:
            worst = max(stuck, key=stuck.get)
            return (
                f"{len(stuck)} elastic rebalance(s) in flight for longer than"
                f" {self.rebalance_stuck_s:.0f}s (worst: {worst},"
                f" {stuck[worst]:.0f}s) — a join/drain/split/merge is wedged and"
                " clients may be split between their old and new homes"
            )
        return None

    # ------------------------------------------------------------------

    def check(self) -> Dict[str, Any]:
        """Run every armed condition against the current registry state.

        Returns ``{"healthy": bool, "warnings": [{"kind", "detail"}, ...]}``.
        Bumps ``health.checks{monitor=}`` per call and
        ``health.alerts{monitor=,kind=}`` per firing condition; the first
        firing of each kind also emits one ``rank_zero_warn`` (later
        firings only count — re-arm with :meth:`reset_warnings`).
        """
        probes = (
            ("straggler", self._check_straggler),
            ("sync_latency", self._check_sync_latency),
            ("recompile_storm", self._check_recompile_storm),
            ("stale_node", self._check_stale_node),
            ("clamp_risk", self._check_clamp_risk),
            ("degraded_sync", self._check_degraded_sync),
            ("queue_saturation", self._check_queue_saturation),
            ("quarantine", self._check_quarantine),
            ("circuit_open", self._check_circuit_open),
            ("rebalance_stuck", self._check_rebalance_stuck),
            ("peer_stale", self._check_peer_stale),
            ("partition_detected", self._check_partition_detected),
            ("fenced_zombie", self._check_fenced_zombie),
            ("history_alert", self._check_history_alert),
            ("slo_burn", self._check_slo_burn),
            ("canary_mismatch", self._check_canary_mismatch),
        )
        warnings: List[Dict[str, str]] = []
        with self._check_lock:
            if self.federated:
                from metrics_tpu.obs import federation as _fed

                snap = _fed.federated_snapshot()
                self._counters_view = snap.get("counters", {})
                self._gauges_view = snap.get("gauges", {})
                self._hists_view = snap.get("histograms", {})
            else:
                self._counters_view = self._gauges_view = self._hists_view = None
            for kind, probe in probes:
                detail = probe()
                if detail is not None:
                    warnings.append({"kind": kind, "detail": detail})
        if _reg.enabled():
            _reg.inc("health.checks", monitor=self.name)
            for w in warnings:
                _reg.inc("health.alerts", monitor=self.name, kind=w["kind"])
        if self.warn:
            for w in warnings:
                if w["kind"] in self._warned_kinds:
                    continue
                self._warned_kinds.add(w["kind"])
                from metrics_tpu.utilities.prints import rank_zero_warn

                rank_zero_warn(
                    f"HealthMonitor {self.name!r} [{w['kind']}]: {w['detail']}. Further"
                    " alerts of this kind are counted under health.alerts{monitor="
                    + self.name
                    + "} without warning again.",
                    UserWarning,
                )
        return {"healthy": not warnings, "warnings": warnings}

    def reset_warnings(self) -> None:
        """Re-arm the one-shot warning for every condition kind."""
        self._warned_kinds.clear()

    def __repr__(self) -> str:
        armed = {
            k: v
            for k, v in (
                ("skew_threshold_ms", self.skew_threshold_ms),
                ("sync_p95_ms", self.sync_p95_ms),
                ("recompile_threshold", self.recompile_threshold),
                ("clamp_risk", self.clamp_risk or None),
                ("degraded_syncs", self.degraded_syncs or None),
                ("queue_depth_threshold", self.queue_depth_threshold),
                ("quarantine", self.quarantine or None),
                ("circuit_open", self.circuit_open or None),
                ("rebalance_stuck_s", self.rebalance_stuck_s),
                ("peer_staleness_ms", self.peer_staleness_ms),
                ("partition_detected", self.partition_detected or None),
                ("fenced_zombie", self.fenced_zombie or None),
                ("slo_alert", self.slo_alert or None),
                ("canary", self.canary or None),
                ("federated", self.federated or None),
                ("node_staleness_s", self.node_staleness_s),
            )
            if v is not None
        }
        return f"HealthMonitor(name={self.name!r}, {armed})"
