"""Fleet-health monitoring over the obs registry: stragglers, storms, clamps.

The registry already collects the raw signals — sync latency histograms
and the arrival-skew gauge from :mod:`metrics_tpu.utilities.distributed`,
per-step trace counters from :mod:`metrics_tpu.obs.recompile`, buffer
clamp-risk counters, and the fault-tolerance subsystem's degraded-sync
counts. :class:`HealthMonitor` turns them into verdicts: call
:meth:`~HealthMonitor.check` periodically (per epoch is the natural
cadence) and it classifies the current window into named conditions,
raises a one-shot ``rank_zero_warn`` per condition kind, and counts
``health.checks{monitor=}`` / ``health.alerts{monitor=,kind=}`` so the
alert history rides the same :func:`metrics_tpu.obs.snapshot` as the
metrics it protects — the :class:`~metrics_tpu.streaming.DriftMonitor`
pattern, applied to the fleet instead of the data distribution.

Conditions (each independently armable):

* ``straggler`` — the ``sync.arrival_skew_ms`` gauge (this host's wait in
  the pre-gather barrier — its lead over the slowest peer) exceeds
  ``skew_threshold_ms``.
* ``sync_latency`` — p95 of the ``sync.latency_ms{op=gather_all_tensors}``
  histogram exceeds ``sync_p95_ms``.
* ``recompile_storm`` — some step's ``step.traces{step=}`` counter reached
  ``recompile_threshold`` (default: the registry's
  ``recompile_warn_threshold``); catches drift on steps whose own one-shot
  warning already fired and was lost in logs.
* ``clamp_risk`` — ``capacity_buffer.clamp_risk_appends`` or
  ``capacity_buffer.eager_overflows`` is nonzero: some buffer-backed
  metric may be silently truncating samples.
* ``degraded_sync`` — any ``ft.degraded_syncs`` series fired: some host
  computed over local-only state and cross-host values are no longer
  comparable.

Serve-fleet conditions (default disarmed — arm them on processes hosting a
:class:`~metrics_tpu.serve.Aggregator`; node-level supervision with a
repair arm lives in :class:`metrics_tpu.serve.resilience.Supervisor`,
these are the registry-only verdicts):

* ``queue_saturation`` — the worst ``serve.queue_depth`` gauge series
  (one per aggregator node) at/over ``queue_depth_threshold``: ingest is
  outrunning the fold and backpressure/shedding is imminent.
* ``quarantine`` — the ``serve.clients_quarantined`` gauges report a
  client currently locked out for poisoned state, pending operator
  action. Current state, not the cumulative ``serve.quarantined``
  counter: a lifted quarantine stops firing.
* ``circuit_open`` — the ``serve.circuits_open`` gauges report a circuit
  currently open: some client is being refused for repeated invalid
  payloads. Current state, not the cumulative open-transition counter: a
  circuit that probes back closed reads healthy again.
"""
from typing import Any, Dict, List, Optional

from metrics_tpu.obs import registry as _reg

__all__ = ["HealthMonitor"]


class HealthMonitor:
    """One-shot-warning health checks over the live obs registry.

    Args:
        skew_threshold_ms: arm the ``straggler`` condition at this
            cross-host arrival skew (``None`` disarms).
        sync_p95_ms: arm ``sync_latency`` when the eager DCN gather's p95
            exceeds this many milliseconds (``None`` disarms).
        recompile_threshold: arm ``recompile_storm`` at this many tracings
            of one step; ``None`` uses the registry's
            ``recompile_warn_threshold`` at check time.
        clamp_risk: arm the buffer ``clamp_risk`` condition.
        degraded_syncs: arm the ``degraded_sync`` condition.
        queue_depth_threshold: arm the serving-tier ``queue_saturation``
            condition at this ``serve.queue_depth`` gauge value, read as
            the worst node's series (``None`` disarms).
        quarantine: arm the serving-tier ``quarantine`` condition
            (a ``serve.clients_quarantined`` gauge is currently nonzero).
        circuit_open: arm the serving-tier ``circuit_open`` condition
            (a ``serve.circuits_open`` gauge is currently nonzero).
        name: label on the ``health.*`` counter series.
        warn: emit a one-shot ``rank_zero_warn`` per condition kind.

    Example:
        >>> from metrics_tpu.obs.health import HealthMonitor
        >>> report = HealthMonitor(warn=False).check()
        >>> report["healthy"]
        True
    """

    def __init__(
        self,
        skew_threshold_ms: Optional[float] = 1000.0,
        sync_p95_ms: Optional[float] = None,
        recompile_threshold: Optional[int] = None,
        clamp_risk: bool = True,
        degraded_syncs: bool = True,
        queue_depth_threshold: Optional[float] = None,
        quarantine: bool = False,
        circuit_open: bool = False,
        name: str = "default",
        warn: bool = True,
    ) -> None:
        self.skew_threshold_ms = skew_threshold_ms
        self.sync_p95_ms = sync_p95_ms
        self.recompile_threshold = recompile_threshold
        self.clamp_risk = bool(clamp_risk)
        self.degraded_syncs = bool(degraded_syncs)
        self.queue_depth_threshold = queue_depth_threshold
        self.quarantine = bool(quarantine)
        self.circuit_open = bool(circuit_open)
        self.name = str(name)
        self.warn = bool(warn)
        self._warned_kinds: set = set()

    # ------------------------------------------------------------------
    # individual condition probes (each returns a detail string or None)
    # ------------------------------------------------------------------

    def _check_straggler(self) -> Optional[str]:
        if self.skew_threshold_ms is None:
            return None
        skew = _reg.get_gauge("sync.arrival_skew_ms")
        if skew is not None and skew > self.skew_threshold_ms:
            return (
                f"cross-host arrival skew {skew:.0f} ms > {self.skew_threshold_ms:.0f} ms —"
                " this host reaches sync points far ahead of the slowest peer"
            )
        return None

    def _check_sync_latency(self) -> Optional[str]:
        if self.sync_p95_ms is None:
            return None
        hist = _reg.get_histogram("sync.latency_ms", op="gather_all_tensors")
        if hist is not None and hist.count and hist.p95 > self.sync_p95_ms:
            return (
                f"eager DCN gather p95 {hist.p95:.0f} ms > {self.sync_p95_ms:.0f} ms"
                f" over {hist.count} gathers"
            )
        return None

    def _check_recompile_storm(self) -> Optional[str]:
        threshold = self.recompile_threshold
        if threshold is None:
            threshold = _reg.get_config("recompile_warn_threshold")
        if not threshold:
            return None
        prefix = "step.traces{"
        storming = {
            key[len(prefix):-1]: int(count)
            for key, count in _reg.counters().items()
            if key.startswith(prefix) and count >= threshold
        }
        if storming:
            worst = max(storming, key=storming.get)
            return (
                f"{len(storming)} step(s) at/over {threshold} tracings"
                f" (worst: {worst} x{storming[worst]}) — shape/dtype drift recompiles"
                " a new program per signature"
            )
        return None

    def _check_clamp_risk(self) -> Optional[str]:
        if not self.clamp_risk:
            return None
        clamps = _reg.get_counter("capacity_buffer.clamp_risk_appends")
        overflows = _reg.get_counter("capacity_buffer.eager_overflows")
        if clamps or overflows:
            return (
                f"capacity-buffer overflow pressure: {int(clamps)} clamp-risk traced"
                f" append(s), {int(overflows)} eager overflow(s) — buffer-backed"
                " metrics may be truncating samples; raise sample_capacity or switch"
                " to a sketch-backed streaming metric"
            )
        return None

    def _check_degraded_sync(self) -> Optional[str]:
        if not self.degraded_syncs:
            return None
        degraded = _reg.sum_counter("ft.degraded_syncs")
        if degraded:
            return (
                f"{int(degraded)} degraded sync(s): some host fell back to local-only"
                " state after exhausting DCN retries — cross-host metric values are"
                " not comparable for those windows"
            )
        return None

    @staticmethod
    def _gauge_series(name: str) -> List[float]:
        """Every current value of gauge ``name`` across its label series
        (one series per aggregator node in a serving tree — a single
        unlabeled read would be last-writer-wins and an idle node could
        mask a saturated one)."""
        prefix = name + "{"
        return [
            value
            for key, value in _reg.gauges().items()
            if key == name or key.startswith(prefix)
        ]

    def _check_queue_saturation(self) -> Optional[str]:
        if self.queue_depth_threshold is None:
            return None
        depths = self._gauge_series("serve.queue_depth")
        worst = max(depths, default=None)
        if worst is not None and worst >= self.queue_depth_threshold:
            return (
                f"serve ingest queue depth {worst:.0f} >= {self.queue_depth_threshold:.0f} —"
                " ingest is outrunning the fold; backpressure/shedding imminent"
            )
        return None

    def _check_quarantine(self) -> Optional[str]:
        if not self.quarantine:
            return None
        # the CURRENT-state gauge, not the cumulative serve.quarantined
        # counter: an incident resolved by unquarantine() must stop firing
        quarantined = sum(self._gauge_series("serve.clients_quarantined"))
        if quarantined:
            return (
                f"{int(quarantined)} client(s) quarantined for shipping poisoned"
                " (NaN/Inf) state — locked out pending operator unquarantine()"
            )
        return None

    def _check_circuit_open(self) -> Optional[str]:
        if not self.circuit_open:
            return None
        # current-state gauge (serve.circuits_open), not the cumulative
        # open-transition counter: a circuit that probed back to closed
        # must read healthy again
        opened = sum(self._gauge_series("serve.circuits_open"))
        if opened:
            return (
                f"{int(opened)} ingest circuit(s) currently open: some client is"
                " being refused for repeated invalid payloads (serve.circuits_open)"
            )
        return None

    # ------------------------------------------------------------------

    def check(self) -> Dict[str, Any]:
        """Run every armed condition against the current registry state.

        Returns ``{"healthy": bool, "warnings": [{"kind", "detail"}, ...]}``.
        Bumps ``health.checks{monitor=}`` per call and
        ``health.alerts{monitor=,kind=}`` per firing condition; the first
        firing of each kind also emits one ``rank_zero_warn`` (later
        firings only count — re-arm with :meth:`reset_warnings`).
        """
        probes = (
            ("straggler", self._check_straggler),
            ("sync_latency", self._check_sync_latency),
            ("recompile_storm", self._check_recompile_storm),
            ("clamp_risk", self._check_clamp_risk),
            ("degraded_sync", self._check_degraded_sync),
            ("queue_saturation", self._check_queue_saturation),
            ("quarantine", self._check_quarantine),
            ("circuit_open", self._check_circuit_open),
        )
        warnings: List[Dict[str, str]] = []
        for kind, probe in probes:
            detail = probe()
            if detail is not None:
                warnings.append({"kind": kind, "detail": detail})
        if _reg.enabled():
            _reg.inc("health.checks", monitor=self.name)
            for w in warnings:
                _reg.inc("health.alerts", monitor=self.name, kind=w["kind"])
        if self.warn:
            for w in warnings:
                if w["kind"] in self._warned_kinds:
                    continue
                self._warned_kinds.add(w["kind"])
                from metrics_tpu.utilities.prints import rank_zero_warn

                rank_zero_warn(
                    f"HealthMonitor {self.name!r} [{w['kind']}]: {w['detail']}. Further"
                    " alerts of this kind are counted under health.alerts{monitor="
                    + self.name
                    + "} without warning again.",
                    UserWarning,
                )
        return {"healthy": not warnings, "warnings": warnings}

    def reset_warnings(self) -> None:
        """Re-arm the one-shot warning for every condition kind."""
        self._warned_kinds.clear()

    def __repr__(self) -> str:
        armed = {
            k: v
            for k, v in (
                ("skew_threshold_ms", self.skew_threshold_ms),
                ("sync_p95_ms", self.sync_p95_ms),
                ("recompile_threshold", self.recompile_threshold),
                ("clamp_risk", self.clamp_risk or None),
                ("degraded_syncs", self.degraded_syncs or None),
                ("queue_depth_threshold", self.queue_depth_threshold),
                ("quarantine", self.quarantine or None),
                ("circuit_open", self.circuit_open or None),
            )
            if v is not None
        }
        return f"HealthMonitor(name={self.name!r}, {armed})"
