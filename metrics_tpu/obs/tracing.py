"""Lifecycle tracing: named scopes for device timelines, spans for host time.

Two attribution surfaces, entered together by :func:`trace_span`:

* ``jax.named_scope`` — stamps every op traced inside the block with the
  scope name, so per-metric work is attributable in TPU profiler (xprof)
  timelines and in HLO dumps. This CHANGES the lowered program's metadata,
  which is exactly why it is only entered when the obs layer is enabled:
  disabled-mode HLO must stay byte-identical to an uninstrumented build
  (pinned by ``tests/bases/test_obs.py``).
* ``jax.profiler.TraceAnnotation`` — a host-side profiler marker (no HLO
  effect) for plain-Python phases.

On exit, an enabled span also records ``(name, nesting depth, wall ms)``
into the registry's host-side span log — the cheap always-available answer
to "where did the eager step spend its time" when no profiler is attached.

``annotate_always=True`` preserves pre-obs behaviour for the two sites that
already carried a bare ``TraceAnnotation`` (``Metric.update`` /
``Metric.compute``): disabled mode keeps emitting that annotation and
nothing else.
"""
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Iterator, Optional

from metrics_tpu.obs import registry as _reg

__all__ = ["pytree_nbytes", "trace_span"]

# one shared stateless instance: the disabled path must not build a fresh
# generator-based context manager per call on per-batch eager hot paths
_NULL_CM = nullcontext()


@contextmanager
def _active_span(name: str, category: Optional[str]) -> Iterator[None]:
    import jax

    depth = _reg._push_span()
    t0 = time.perf_counter()
    try:
        with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
            yield
    finally:
        _reg._pop_span()
        _reg.record_span(name, (time.perf_counter() - t0) * 1000.0, depth, category, start_s=t0)


def trace_span(name: str, category: Optional[str] = None, annotate_always: bool = False):
    """Context manager wrapping one lifecycle phase.

    Disabled: a no-op (or, with ``annotate_always``, exactly the bare
    ``TraceAnnotation`` the pre-obs code emitted). Enabled: named scope +
    trace annotation + host span record.
    """
    if not _reg.enabled():
        if annotate_always:
            import jax

            return jax.profiler.TraceAnnotation(name)
        return _NULL_CM
    return _active_span(name, category)


def pytree_nbytes(tree: Any) -> int:
    """Total bytes of every array leaf in a metric-state pytree.

    Shape/dtype metadata only — no device sync, works on tracers. Lists of
    arrays (unbounded cat states) and :class:`CapacityBuffer` instances
    (counts the allocated ``(capacity, *item)`` backing array) are walked
    like any other container.
    """
    import jax

    from metrics_tpu.utilities.buffers import CapacityBuffer

    total = 0

    def _leaf(x: Any) -> None:
        nonlocal total
        if isinstance(x, CapacityBuffer):
            if x.data is not None:
                total += x.data.size * x.data.dtype.itemsize
            total += 4  # the int32 fill counter
        elif hasattr(x, "dtype") and hasattr(x, "size"):
            total += x.size * x.dtype.itemsize

    jax.tree_util.tree_map(_leaf, tree, is_leaf=lambda x: isinstance(x, CapacityBuffer))
    return total
