"""``metrics_tpu.obs`` — observability for every metric hot path.

Six pillars, all zero-overhead when disabled (the default; the compiled
HLO of a jitted step with the layer off is byte-identical to an
uninstrumented build — pinned by ``tests/bases/test_obs.py``):

1. **Lifecycle tracing** — ``Metric.update/forward/compute/sync/reset``,
   ``MetricCollection`` and the ``make_step``/``make_epoch`` pure steps run
   under ``jax.named_scope`` + ``jax.profiler.TraceAnnotation``, so
   per-metric work is attributable in TPU profiler timelines; eager phases
   also land in a host-side span log (name, nesting, wall ms).
2. **Recompile telemetry** — tracings, compiles and compile seconds per
   jitted step, with a one-shot storm warning when one step re-traces past
   ``recompile_warn_threshold`` (shape/dtype drift).
3. **Runtime-counter registry** — updates applied, fused-epoch launches and
   batches folded, per-metric state bytes, collective sync count + payload
   bytes, ``CapacityBuffer`` clamp-risk events, and the streaming
   subsystem's ``stream.windows_expired`` / ``stream.drift_checks`` /
   ``stream.drift_alerts`` series. **Counter semantics under
   jit:** hooks are Python, so inside jitted code they run at TRACE time —
   counters on jitted paths (``metric.updates`` reached through a jitted
   step, ``sync.collectives``, ``sync.payload_bytes``) count once per
   compiled program, not per execution. Per-execution series exist where
   the entry point is eager: ``metric.*`` via the eager class API,
   ``epoch.launches``/``epoch.batches_folded`` (counted host-side at the
   ``make_epoch`` entry), ``sync.gathers`` (eager DCN path).
4. **Performance tier** — :func:`observe` feeds fixed log-spaced
   **histograms** (p50/p95/p99 via :func:`get_histogram`);
   ``configure(device_timing=True)`` times tracked launches into
   ``step.latency_ms{step=}``; ``configure(cost_analysis=True)`` pulls
   ``Compiled.cost_analysis()`` into FLOPs / bytes-accessed / arithmetic-
   intensity gauges; :func:`profile` captures an xprof timeline
   programmatically (see :mod:`metrics_tpu.obs.profile`).
5. **Health** — :class:`HealthMonitor` classifies the registry into
   straggler / sync-latency / recompile-storm / clamp-risk /
   degraded-sync conditions with one-shot warnings
   (see :mod:`metrics_tpu.obs.health`).
6. **Export** — :func:`snapshot` (plain dict), :func:`to_prometheus`
   (counters, gauges, and ``histogram`` families with
   ``_bucket``/``_sum``/``_count``), :func:`to_json`,
   :func:`to_chrome_trace` (host spans + serving-tier payload hops as
   Perfetto-loadable JSON); ``MetricLogger`` archives a snapshot per
   epoch, ``bench.py --json`` splits compile from run time per row, and
   ``bench.py --compare OLD.json`` gates new rounds against prior records
   (``benchmarks/compare.py``).
7. **Federation** — snapshots carry node identity + capture time;
   :func:`merge_snapshots` combines fleets (counters sum, gauges keep
   per-node labels, histograms merge bucketwise-exact over the shared
   :data:`HISTOGRAM_EDGES`), and the serving tree piggybacks per-node
   snapshots upward so a root's ``/metrics`` renders the whole fleet
   (:mod:`metrics_tpu.obs.federation`; see ``docs/observability.md`` §9).

Quick start::

    import metrics_tpu.obs as obs

    obs.enable()                       # or METRICS_TPU_OBS=1
    ...                                # run your metric pipeline
    print(obs.snapshot()["counters"])  # {'metric.updates{metric=Accuracy}': 128.0, ...}
    print(obs.to_prometheus())         # scrape-ready text

See ``docs/observability.md`` for the full guide.
"""
from metrics_tpu.obs import registry as _registry  # noqa: F401
from metrics_tpu.obs.export import (
    family_help,
    merge_snapshots,
    register_help,
    snapshot,
    to_chrome_trace,
    to_json,
    to_prometheus,
)
from metrics_tpu.obs.federation import (
    accept_snapshot,
    federated_snapshot,
    node_ages,
    remote_snapshots,
    wire_snapshots,
)
from metrics_tpu.obs.health import HealthMonitor
from metrics_tpu.obs.meter import tenant_id_hash, top_consumers
from metrics_tpu.obs.prober import CANARY_TENANT, CanaryProber, canary_metrics
from metrics_tpu.obs.profile import instrument, profile, record_cost_analysis, time_launch
from metrics_tpu.obs.recompile import (
    compile_listener_installed,
    install_compile_listener,
    note_trace,
    track_compiles,
)
from metrics_tpu.obs.registry import (
    HISTOGRAM_EDGES,
    HistogramSnapshot,
    configure,
    counters,
    enable,
    enabled,
    gauges,
    get_counter,
    get_gauge,
    get_histogram,
    histograms,
    hops,
    inc,
    new_trace_id,
    node_identity,
    observe,
    record_hop,
    set_gauge,
    set_node_identity,
    spans,
    sum_counter,
)
from metrics_tpu.obs.slo import ErrorBudget, SLODef, SLOEngine, default_slos
from metrics_tpu.obs.tracing import pytree_nbytes, trace_span

__all__ = [
    "CANARY_TENANT",
    "CanaryProber",
    "ErrorBudget",
    "HISTOGRAM_EDGES",
    "HealthMonitor",
    "HistogramSnapshot",
    "SLODef",
    "SLOEngine",
    "accept_snapshot",
    "canary_metrics",
    "compile_listener_installed",
    "configure",
    "counters",
    "default_slos",
    "enable",
    "enabled",
    "family_help",
    "federated_snapshot",
    "gauges",
    "get_counter",
    "get_gauge",
    "get_histogram",
    "histograms",
    "hops",
    "inc",
    "install_compile_listener",
    "instrument",
    "merge_snapshots",
    "new_trace_id",
    "node_ages",
    "node_identity",
    "note_trace",
    "observe",
    "profile",
    "pytree_nbytes",
    "record_cost_analysis",
    "record_hop",
    "register_help",
    "remote_snapshots",
    "reset",
    "set_gauge",
    "set_node_identity",
    "snapshot",
    "spans",
    "sum_counter",
    "tenant_id_hash",
    "time_launch",
    "to_chrome_trace",
    "to_json",
    "to_prometheus",
    "top_consumers",
    "trace_span",
    "track_compiles",
    "wire_snapshots",
]


def reset() -> None:
    """Clear all counters/gauges/spans/hop records, the federation table's
    per-node snapshots, and re-arm the one-shot storm warning (the enabled
    flag, config and node identity survive — this separates measurement
    windows, it doesn't disarm the layer). Clearing the trace/federation
    state here is what keeps back-to-back bench rounds and tests from
    bleeding fleet state into each other.

    The SLO plane's satellites clear too: the metering sketch/pending map,
    every live :class:`~metrics_tpu.obs.slo.SLOEngine`'s budget table, and
    every live :class:`~metrics_tpu.obs.prober.CanaryProber`'s verdict
    tallies — via ``sys.modules`` so importing :mod:`metrics_tpu.obs`
    never drags in the serving tier those modules touch."""
    import sys

    from metrics_tpu.obs import federation as _federation
    from metrics_tpu.obs import meter as _meter
    from metrics_tpu.obs import recompile as _recompile

    _registry.reset()
    _federation.reset()
    _recompile.reset_storm_warnings()
    _meter.reset()
    for modname in ("metrics_tpu.obs.slo", "metrics_tpu.obs.prober"):
        mod = sys.modules.get(modname)
        if mod is not None:
            mod.reset()
