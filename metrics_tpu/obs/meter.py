"""Per-tenant usage metering: bounded top-consumer ranking.

The serving tier's hot path attributes resource consumption per tenant
through the ordinary registry families — ``meter.wire_bytes{tenant=}``
(counter), ``meter.queue_ms``/``meter.fold_ms{tenant=}`` (histograms),
``meter.state_bytes``/``meter.history_bytes{tenant=}`` (gauges). Those
series are cardinality-guarded by ``max_series_per_family`` and federate
through :func:`metrics_tpu.obs.export.merge_snapshots` like every other
family (counters sum, gauges keep node labels, histograms merge
bucketwise-exact), so the fleet view needs no new machinery.

What a capped registry CANNOT answer is "who are the top consumers" once
the tenant space outgrows the cap: the guard drops the overflow series,
exactly as designed. This module keeps the *ranking* exact-enough anyway
with the in-tree :class:`~metrics_tpu.streaming.heavy.HeavyHitterSketch`
— every charged byte lands in a fixed-size linear sketch keyed on a
stable 24-bit hash of the tenant id, so the root ranks millions of
tenants in O(capacity) memory with a computable overestimate bound.

Cost model (documented in ``docs/observability.md`` §10): the hot path
pays one dict add per charge (:func:`charge` buffers into a bounded
pending map); the jitted sketch fold runs only when the pending map
fills (:data:`PENDING_CAP` distinct tenants — a hostile many-tenant
flood amortizes one fold per 1024 fresh ids) or when a ranking is
actually read (:func:`top_consumers`). Unarmed
(:func:`metrics_tpu.obs.enabled` false) the aggregator never calls in
here at all — zero cost, the disabled-mode HLO pin stays byte-identical.

:func:`metrics_tpu.obs.reset` clears the sketch, the pending map and the
id->name table alongside the registry.
"""
import hashlib
import threading
from typing import Any, Dict, List, Optional

__all__ = [
    "charge",
    "pending_tenants",
    "reset",
    "tenant_id_hash",
    "top_consumers",
]

# distinct tenants buffered host-side before a fold is forced; also the
# bound on the id->name table divisor below. Keeps the hot path free of
# per-payload device dispatch while bounding memory against id floods.
PENDING_CAP = 1024

# id->name entries retained for rendering (a ranking of hashes alone is
# useless to an operator). Bounded: a hostile flood evicts names, never
# grows the table — the sketch itself keeps ranking the hashes exactly
# as before, rendered as "~<hash>".
NAME_CAP = 4096

# hash space: HeavyHitterSketch ids must be non-negative < 2**id_bits
ID_BITS = 24

_lock = threading.Lock()
_pending: Dict[str, float] = {}
_names: Dict[int, str] = {}
_sketch: Optional[Any] = None


def tenant_id_hash(tenant: str) -> int:
    """Stable 24-bit sketch id for a tenant name (blake2b, process- and
    host-independent so per-node sketches stay monoid-mergeable)."""
    digest = hashlib.blake2b(str(tenant).encode("utf-8"), digest_size=4).digest()
    return int.from_bytes(digest, "big") & ((1 << ID_BITS) - 1)


def charge(tenant: str, weight: float) -> None:
    """Attribute ``weight`` (bytes) of consumption to ``tenant``.

    Host-side dict add only; the jitted sketch fold is deferred until the
    pending map holds :data:`PENDING_CAP` distinct tenants or a ranking
    is read. Non-positive weights are ignored (nothing to rank)."""
    w = float(weight)
    if w <= 0.0:
        return
    tenant = str(tenant)
    with _lock:
        _pending[tenant] = _pending.get(tenant, 0.0) + w
        if len(_pending) < PENDING_CAP:
            return
        drain = dict(_pending)
        _pending.clear()
    _fold_into_sketch(drain)


def _fold_into_sketch(drain: Dict[str, float]) -> None:
    """One batched sketch fold over a drained pending map."""
    global _sketch
    if not drain:
        return
    import numpy as np

    from metrics_tpu.streaming.heavy import HeavyHitterSketch

    ids = np.asarray([tenant_id_hash(t) for t in sorted(drain)], dtype=np.int32)
    weights = np.asarray([drain[t] for t in sorted(drain)], dtype=np.float32)
    with _lock:
        if _sketch is None:
            _sketch = HeavyHitterSketch(id_bits=ID_BITS)
        _sketch = _sketch.fold(ids, weights)
        for t in drain:
            h = tenant_id_hash(t)
            if h in _names or len(_names) < NAME_CAP:
                _names[h] = t


def top_consumers(k: int = 10) -> List[Dict[str, Any]]:
    """The fleet's top-``k`` consumers by charged bytes: drained pending
    map folded into the sketch first, so the answer is current. Each row
    carries the resolved tenant name (or ``~<hash>`` when the bounded
    name table evicted it), the estimated byte count, and the sketch's
    overestimate bound — the honesty term a capped ranking owes."""
    with _lock:
        drain = dict(_pending)
        _pending.clear()
    _fold_into_sketch(drain)
    with _lock:
        sketch = _sketch
        names = dict(_names)
    if sketch is None or int(sketch.count) == 0:
        return []
    import numpy as np

    ids, counts, over = sketch.topk(int(k))
    rows: List[Dict[str, Any]] = []
    for tid, count, bound in zip(np.asarray(ids), np.asarray(counts), np.asarray(over)):
        tid = int(tid)
        if tid < 0:
            continue  # empty sketch slot
        rows.append(
            {
                "tenant": names.get(tid, f"~{tid}"),
                "bytes": float(count),
                "overestimate": float(bound),
            }
        )
    return rows


def pending_tenants() -> int:
    """Distinct tenants currently buffered host-side (test/debug probe)."""
    with _lock:
        return len(_pending)


def reset() -> None:
    """Drop the sketch, pending charges and the id->name table
    (:func:`metrics_tpu.obs.reset` calls this alongside the registry)."""
    global _sketch
    with _lock:
        _pending.clear()
        _names.clear()
        _sketch = None
