"""Export surface: plain-dict snapshot, Prometheus text, JSON.

``snapshot()`` is the canonical read: a plain nested dict (counters,
gauges, spans, config, enabled flag) safe to log, diff between epochs
(:class:`~metrics_tpu.integrations.MetricLogger` archives one per epoch
when the layer is enabled), or attach to bench rows. The two dumpers
re-serialize a snapshot without touching live registry state, so exporters
can run on a snapshot taken at a consistent instant.

Prometheus naming: series ``a.b.c{x=y}`` becomes
``metrics_tpu_a_b_c{x="y"}`` — dots to underscores, every label value
quoted, one ``# TYPE`` line per family (counters ``counter``, gauges
``gauge``). Spans are not exported to Prometheus (they are per-event, not
a series); they ride the JSON dump.
"""
import json
import re
from typing import Any, Dict, Optional

from metrics_tpu.obs import registry as _reg

__all__ = ["snapshot", "to_json", "to_prometheus"]

_KEY_RE = re.compile(r"^(?P<name>[^{]+)(?:\{(?P<labels>.*)\})?$")


def snapshot(spans: bool = True) -> Dict[str, Any]:
    """Everything the obs layer knows, as one plain dict.

    ``spans=False`` omits the span ring (counters/gauges only, plus the
    ring's current length under ``span_count``) — the right shape for
    per-epoch archiving, where copying the full up-to-``max_spans`` ring
    every epoch would duplicate mostly-identical entries across snapshots.
    """
    out = {
        "enabled": _reg.enabled(),
        "counters": _reg.counters(),
        "gauges": _reg.gauges(),
        "config": {k: _reg.get_config(k) for k in ("recompile_warn_threshold", "max_spans")},
    }
    if spans:
        out["spans"] = _reg.spans()
    else:
        out["span_count"] = len(_reg.spans())
    return out


def _prom_series(key: str, value: float, out: list) -> None:
    m = _KEY_RE.match(key)
    name = "metrics_tpu_" + re.sub(r"[^a-zA-Z0-9_]", "_", (m.group("name") if m else key))
    labels = (m.group("labels") or "") if m else ""
    if labels:
        pairs = []
        for part in labels.split(","):
            k, _, v = part.partition("=")
            pairs.append(f'{re.sub(r"[^a-zA-Z0-9_]", "_", k)}="{v}"')
        name = f"{name}{{{','.join(pairs)}}}"
    out.append(f"{name} {value:g}")


def to_prometheus(snap: Optional[Dict[str, Any]] = None) -> str:
    """Render a snapshot in the Prometheus text exposition format."""
    snap = snapshot() if snap is None else snap
    lines: list = []
    typed: set = set()
    for kind, family in (("counter", "counters"), ("gauge", "gauges")):
        for key in sorted(snap.get(family, {})):
            m = _KEY_RE.match(key)
            base = "metrics_tpu_" + re.sub(r"[^a-zA-Z0-9_]", "_", (m.group("name") if m else key))
            if base not in typed:
                typed.add(base)
                lines.append(f"# TYPE {base} {kind}")
            _prom_series(key, snap[family][key], lines)
    return "\n".join(lines) + ("\n" if lines else "")


def to_json(snap: Optional[Dict[str, Any]] = None, path: Optional[str] = None, indent: int = 2) -> str:
    """Serialize a snapshot to JSON; optionally also write it to ``path``."""
    text = json.dumps(snapshot() if snap is None else snap, indent=indent, sort_keys=True)
    if path is not None:
        with open(path, "w") as f:
            f.write(text + "\n")
    return text
