"""Export surface: plain-dict snapshot, Prometheus text, JSON.

``snapshot()`` is the canonical read: a plain nested dict (counters,
gauges, histograms, spans, config, enabled flag) safe to log, diff between
epochs (:class:`~metrics_tpu.integrations.MetricLogger` archives one per
epoch when the layer is enabled), or attach to bench rows. The two dumpers
re-serialize a snapshot without touching live registry state, so exporters
can run on a snapshot taken at a consistent instant.

Prometheus naming: series ``a.b.c{x=y}`` becomes
``metrics_tpu_a_b_c{x="y"}`` — dots to underscores, every label value
quoted with backslash/quote/newline escaped per the text exposition
format, one ``# TYPE`` line per family (counters ``counter``, gauges
``gauge``, histograms ``histogram``). Histogram series expand into the
standard ``_bucket{le=...}`` cumulative counts (with a ``+Inf`` bucket),
``_sum`` and ``_count``. Spans are not exported to Prometheus (they are
per-event, not a series); they ride the JSON dump.

Label splitting honours the registry's quoting: a label value that
contains key syntax is stored quoted-and-escaped in the flat key
(:func:`metrics_tpu.obs.registry._fmt_label_value`), so the splitter here
breaks on commas only OUTSIDE quoted values and unescapes before
re-escaping for exposition — hostile values round-trip instead of
corrupting neighbouring labels.
"""
import json
import re
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.obs import registry as _reg

__all__ = ["snapshot", "to_json", "to_prometheus"]

_KEY_RE = re.compile(r"^(?P<name>[^{]+)(?:\{(?P<labels>.*)\})?$", re.DOTALL)
_NAME_SAFE = re.compile(r"[^a-zA-Z0-9_]")


def snapshot(spans: bool = True) -> Dict[str, Any]:
    """Everything the obs layer knows, as one plain dict.

    ``spans=False`` omits the span ring (counters/gauges/histograms only,
    plus the ring's current length under ``span_count``) — the right shape
    for per-epoch archiving, where copying the full up-to-``max_spans``
    ring every epoch would duplicate mostly-identical entries across
    snapshots.
    """
    out = {
        "enabled": _reg.enabled(),
        "counters": _reg.counters(),
        "gauges": _reg.gauges(),
        "histograms": _reg.histograms(),
        "config": {
            k: _reg.get_config(k)
            for k in (
                "recompile_warn_threshold",
                "max_spans",
                "device_timing",
                "cost_analysis",
                "arrival_skew_probe",
            )
        },
    }
    if spans:
        out["spans"] = _reg.spans()
    else:
        out["span_count"] = len(_reg.spans())
    return out


def _parse_labels(labels: str) -> List[Tuple[str, str]]:
    """Split a flat-key label blob into (name, raw value) pairs.

    Values quoted by the registry (``k="a,b\\"c"``) are unescaped; bare
    values are taken verbatim up to the next comma. Commas inside quotes
    never split.
    """
    pairs: List[Tuple[str, str]] = []
    i, n = 0, len(labels)
    while i < n:
        eq = labels.find("=", i)
        if eq < 0:  # trailing junk without '='; keep it as a valueless label
            pairs.append((labels[i:], ""))
            break
        key = labels[i:eq]
        i = eq + 1
        if i < n and labels[i] == '"':
            i += 1
            buf: List[str] = []
            while i < n:
                ch = labels[i]
                if ch == "\\" and i + 1 < n:
                    nxt = labels[i + 1]
                    buf.append("\n" if nxt == "n" else nxt)
                    i += 2
                    continue
                if ch == '"':
                    i += 1
                    break
                buf.append(ch)
                i += 1
            value = "".join(buf)
        else:
            end = labels.find(",", i)
            end = n if end < 0 else end
            value = labels[i:end]
            i = end
        if i < n and labels[i] == ",":
            i += 1
        pairs.append((key, value))
    return pairs


# exposition escaping == the registry's key escaping by construction: one
# shared implementation, so the quoted-label round trip can never drift
_escape_label_value = _reg._escape_label_value


def _prom_parts(key: str) -> Tuple[str, List[Tuple[str, str]]]:
    """Flat registry key -> (sanitized metric name, parsed label pairs)."""
    m = _KEY_RE.match(key)
    raw_name = m.group("name") if m else key
    name = "metrics_tpu_" + _NAME_SAFE.sub("_", raw_name)
    labels = _parse_labels(m.group("labels") or "") if m else []
    return name, labels


def _fmt_labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{_NAME_SAFE.sub("_", k)}="{_escape_label_value(v)}"' for k, v in pairs)
    return f"{{{inner}}}"


def _prom_series(key: str, value: float, out: list) -> None:
    name, pairs = _prom_parts(key)
    out.append(f"{name}{_fmt_labels(pairs)} {value:g}")


def _prom_histogram(key: str, hist: Dict[str, Any], out: list) -> None:
    """One histogram series -> ``_bucket``/``_sum``/``_count`` lines with
    cumulative counts and the mandatory ``+Inf`` bucket."""
    name, pairs = _prom_parts(key)
    edges = hist.get("edges") or list(_reg.HISTOGRAM_EDGES)
    buckets = hist.get("buckets") or []
    cum = 0
    for edge, count in zip(edges, buckets):
        cum += count
        out.append(f'{name}_bucket{_fmt_labels(pairs + [("le", f"{edge:g}")])} {cum}')
    out.append(f'{name}_bucket{_fmt_labels(pairs + [("le", "+Inf")])} {hist.get("count", cum)}')
    out.append(f"{name}_sum{_fmt_labels(pairs)} {hist.get('sum', 0.0):g}")
    out.append(f"{name}_count{_fmt_labels(pairs)} {hist.get('count', cum)}")


def to_prometheus(snap: Optional[Dict[str, Any]] = None) -> str:
    """Render a snapshot in the Prometheus text exposition format."""
    snap = snapshot() if snap is None else snap
    lines: list = []
    typed: set = set()
    for kind, family in (("counter", "counters"), ("gauge", "gauges")):
        for key in sorted(snap.get(family, {})):
            base, _ = _prom_parts(key)
            if base not in typed:
                typed.add(base)
                lines.append(f"# TYPE {base} {kind}")
            _prom_series(key, snap[family][key], lines)
    for key in sorted(snap.get("histograms", {})):
        base, _ = _prom_parts(key)
        if base not in typed:
            typed.add(base)
            lines.append(f"# TYPE {base} histogram")
        _prom_histogram(key, snap["histograms"][key], lines)
    return "\n".join(lines) + ("\n" if lines else "")


def to_json(snap: Optional[Dict[str, Any]] = None, path: Optional[str] = None, indent: int = 2) -> str:
    """Serialize a snapshot to JSON; optionally also write it to ``path``.

    The file write is atomic (staged sibling temp file + ``os.replace``,
    the ``atomic_dir_swap`` idiom): a scraper or a restarting process
    reading ``path`` mid-write sees either the complete previous snapshot
    or the complete new one, never a truncated JSON document. On error the
    stage is discarded and any existing ``path`` is untouched.
    """
    text = json.dumps(snapshot() if snap is None else snap, indent=indent, sort_keys=True)
    if path is not None:
        import os
        import tempfile

        final = os.fspath(os.path.abspath(path))
        parent = os.path.dirname(final) or "."
        fd, stage = tempfile.mkstemp(prefix=".tmp.obs.", suffix=".json", dir=parent)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text + "\n")
                f.flush()
                os.fsync(f.fileno())
            # mkstemp creates 0600 regardless of umask; installing that over
            # an existing snapshot would revoke other readers (a scraper
            # running as a different user). Preserve the target's mode, or
            # a plain umask-honoring open()-equivalent for a fresh file.
            try:
                mode = os.stat(final).st_mode & 0o7777
            except OSError:
                umask = os.umask(0)
                os.umask(umask)
                mode = 0o666 & ~umask
            os.chmod(stage, mode)
            os.replace(stage, final)
        except BaseException:
            try:
                os.unlink(stage)
            except OSError:
                pass
            raise
    return text
