"""Export surface: plain-dict snapshot, Prometheus text, JSON.

``snapshot()`` is the canonical read: a plain nested dict (counters,
gauges, histograms, spans, config, enabled flag) safe to log, diff between
epochs (:class:`~metrics_tpu.integrations.MetricLogger` archives one per
epoch when the layer is enabled), or attach to bench rows. The two dumpers
re-serialize a snapshot without touching live registry state, so exporters
can run on a snapshot taken at a consistent instant.

Prometheus naming: series ``a.b.c{x=y}`` becomes
``metrics_tpu_a_b_c{x="y"}`` — dots to underscores, every label value
quoted with backslash/quote/newline escaped per the text exposition
format, one ``# TYPE`` line per family (counters ``counter``, gauges
``gauge``, histograms ``histogram``), preceded by a ``# HELP`` line for
every family with a registered description (:func:`register_help` /
:data:`_FAMILY_HELP` — all built-in families ship one). Histogram series
expand into the
standard ``_bucket{le=...}`` cumulative counts (with a ``+Inf`` bucket),
``_sum`` and ``_count``. Spans are not exported to Prometheus (they are
per-event, not a series); they ride the JSON dump.

Label splitting honours the registry's quoting: a label value that
contains key syntax is stored quoted-and-escaped in the flat key
(:func:`metrics_tpu.obs.registry._fmt_label_value`), so the splitter here
breaks on commas only OUTSIDE quoted values and unescapes before
re-escaping for exposition — hostile values round-trip instead of
corrupting neighbouring labels.
"""
import json
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.obs import registry as _reg

__all__ = [
    "family_help",
    "merge_snapshots",
    "register_help",
    "snapshot",
    "to_chrome_trace",
    "to_json",
    "to_prometheus",
]

_KEY_RE = re.compile(r"^(?P<name>[^{]+)(?:\{(?P<labels>.*)\})?$", re.DOTALL)
_NAME_SAFE = re.compile(r"[^a-zA-Z0-9_]")


def snapshot(spans: bool = True) -> Dict[str, Any]:
    """Everything the obs layer knows, as one plain dict.

    ``spans=False`` omits the span ring (counters/gauges/histograms only,
    plus the ring's current length under ``span_count``) — the right shape
    for per-epoch archiving, where copying the full up-to-``max_spans``
    ring every epoch would duplicate mostly-identical entries across
    snapshots.
    """
    out = {
        "enabled": _reg.enabled(),
        # federation identity + freshness: the per-node table in
        # metrics_tpu.obs.federation keys on "node" and keep-latests on
        # "captured_at" (wall clock — snapshots cross process boundaries)
        "node": _reg.node_identity(),
        "captured_at": time.time(),
        "counters": _reg.counters(),
        "gauges": _reg.gauges(),
        "histograms": _reg.histograms(),
        "config": {
            k: _reg.get_config(k)
            for k in (
                "recompile_warn_threshold",
                "max_spans",
                "max_hops",
                "device_timing",
                "cost_analysis",
                "arrival_skew_probe",
                "max_series_per_family",
            )
        },
    }
    if spans:
        out["spans"] = _reg.spans()
    else:
        out["span_count"] = len(_reg.spans())
    return out


def _parse_labels(labels: str) -> List[Tuple[str, str]]:
    """Split a flat-key label blob into (name, raw value) pairs.

    Values quoted by the registry (``k="a,b\\"c"``) are unescaped; bare
    values are taken verbatim up to the next comma. Commas inside quotes
    never split.
    """
    pairs: List[Tuple[str, str]] = []
    i, n = 0, len(labels)
    while i < n:
        eq = labels.find("=", i)
        if eq < 0:  # trailing junk without '='; keep it as a valueless label
            pairs.append((labels[i:], ""))
            break
        key = labels[i:eq]
        i = eq + 1
        if i < n and labels[i] == '"':
            i += 1
            buf: List[str] = []
            while i < n:
                ch = labels[i]
                if ch == "\\" and i + 1 < n:
                    nxt = labels[i + 1]
                    buf.append("\n" if nxt == "n" else nxt)
                    i += 2
                    continue
                if ch == '"':
                    i += 1
                    break
                buf.append(ch)
                i += 1
            value = "".join(buf)
        else:
            end = labels.find(",", i)
            end = n if end < 0 else end
            value = labels[i:end]
            i = end
        if i < n and labels[i] == ",":
            i += 1
        pairs.append((key, value))
    return pairs


# exposition escaping == the registry's key escaping by construction: one
# shared implementation, so the quoted-label round trip can never drift
_escape_label_value = _reg._escape_label_value


def _prom_parts(key: str) -> Tuple[str, List[Tuple[str, str]]]:
    """Flat registry key -> (sanitized metric name, parsed label pairs)."""
    m = _KEY_RE.match(key)
    raw_name = m.group("name") if m else key
    name = "metrics_tpu_" + _NAME_SAFE.sub("_", raw_name)
    labels = _parse_labels(m.group("labels") or "") if m else []
    return name, labels


def _fmt_labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{_NAME_SAFE.sub("_", k)}="{_escape_label_value(v)}"' for k, v in pairs)
    return f"{{{inner}}}"


def _prom_series(key: str, value: float, out: list) -> None:
    name, pairs = _prom_parts(key)
    out.append(f"{name}{_fmt_labels(pairs)} {value:g}")


def _prom_histogram(key: str, hist: Dict[str, Any], out: list) -> None:
    """One histogram series -> ``_bucket``/``_sum``/``_count`` lines with
    cumulative counts and the mandatory ``+Inf`` bucket."""
    name, pairs = _prom_parts(key)
    edges = hist.get("edges") or list(_reg.HISTOGRAM_EDGES)
    buckets = hist.get("buckets") or []
    cum = 0
    for edge, count in zip(edges, buckets):
        cum += count
        out.append(f'{name}_bucket{_fmt_labels(pairs + [("le", f"{edge:g}")])} {cum}')
    out.append(f'{name}_bucket{_fmt_labels(pairs + [("le", "+Inf")])} {hist.get("count", cum)}')
    out.append(f"{name}_sum{_fmt_labels(pairs)} {hist.get('sum', 0.0):g}")
    out.append(f"{name}_count{_fmt_labels(pairs)} {hist.get('count', cum)}")


# ---------------------------------------------------------------------------
# # HELP description registry — one sentence per known family, keyed on the
# RAW dotted family name (the key up to its first "{"), emitted ahead of
# the family's # TYPE line. Unknown families still export (TYPE only);
# subsystems introducing a family at runtime add theirs via register_help().
# ---------------------------------------------------------------------------

_FAMILY_HELP: Dict[str, str] = {
    # core metric lifecycle
    "metric.updates": "Metric update() calls",
    "metric.computes": "Metric compute() calls",
    "metric.forwards": "Metric forward() calls (update + batch-value)",
    "metric.resets": "Metric reset() calls",
    "metric.syncs": "Cross-host state synchronisations",
    "metric.sync_noops": "Syncs skipped because the world has one host",
    "metric.sync_ms": "Wall time per cross-host synchronisation",
    "metric.state_bytes": "Serialized state size per metric",
    "collection.members": "Metrics held per MetricCollection",
    "collection.update_groups": "Distinct update signatures per collection",
    "collection.format_reuse": "Collection compute-group format reuses",
    # compilation / tracing
    "jax.compiles": "jit compilations triggered by metric programs",
    "jax.compile_seconds": "Wall seconds spent in jit compilation",
    "step.traces": "Retracings per named step (drift indicator)",
    "step.latency_ms": "Per-step wall latency",
    "step.eager_calls": "Steps executed eagerly (outside jit)",
    "step.flops": "XLA cost-analysis FLOPs per step",
    "step.bytes_accessed": "XLA cost-analysis bytes accessed per step",
    "step.arithmetic_intensity": "FLOPs per byte accessed per step",
    "compile.cache_hits": "Persistent compile-cache hits",
    "compile.cache_misses": "Persistent compile-cache misses",
    "compile.store_errors": "Persistent compile-cache store failures",
    "compile.store_invalid": "Persistent compile-cache invalid entries",
    "compile.warmup_mismatches": "AOT warmup signature mismatches",
    "compile_cache.persistent_enabled": "Persistent compile cache armed (0/1)",
    # sync / collectives
    "sync.gathers": "gather_all_tensors collective launches",
    "sync.gather_chunks": "Chunks shipped across gather launches",
    "sync.collectives": "Collective ops issued by the sync layer",
    "sync.latency_ms": "Collective latency per op",
    "sync.payload_bytes": "Bytes moved per collective payload",
    "sync.arrival_skew_ms": "This host's lead over the slowest peer at sync",
    "sync.arrival_wait_ms": "Time parked in the pre-gather barrier",
    "sync.arrival_skew_probe_failures": "Arrival-skew probe failures",
    # buffers / epochs / streaming
    "capacity_buffer.clamp_risk_appends": "Appends at/over buffer capacity",
    "capacity_buffer.eager_overflows": "Eager-mode buffer overflows",
    "capacity_buffer.checkify_guards_armed": "Checkify overflow guards armed",
    "epoch.launches": "Device launches per epoch accumulation",
    "epoch.batches_folded": "Batches folded into epoch state",
    "epoch.batches_per_launch": "Batches amortized per device launch",
    "stream.drift_checks": "DriftMonitor.check() calls",
    "stream.drift_alerts": "Drift checks that crossed an alert threshold",
    "stream.windows_expired": "WindowedMetric ring slots retired",
    "stream.hh_queries": "StreamingTopK bound/envelope queries",
    "stream.churn_queries": "StreamingTopK certified top-k churn queries",
    "stream.distinct_queries": "StreamingDistinctCount bound/envelope queries",
    "stream.cooccur_queries": "StreamingConfusion cell/top-cell bound queries",
    # fault tolerance
    "ft.checkpoint_saves": "Checkpoint save() completions",
    "ft.checkpoint_restores": "Checkpoint restore() completions",
    "ft.checkpoint_save_ms": "Wall time per checkpoint save",
    "ft.checkpoints_rotated": "Old checkpoints rotated out by keep=",
    "ft.degraded_syncs": "Syncs that fell back to local-only state",
    "ft.manifest_env_mismatches": "Restores into a mismatched environment",
    "ft.retries": "Retry attempts by the ft retry policy",
    "ft.save_timeouts": "Checkpoint saves abandoned on timeout",
    # health / profiling / chaos
    "health.checks": "HealthMonitor.check() calls",
    "health.alerts": "Health conditions that fired, by kind",
    "profile.captures": "Profiler trace captures",
    "profile.capture_ms": "Wall time per profiler capture",
    "profile.cost_analysis_failures": "XLA cost-analysis failures",
    "chaos.injected": "Faults injected by the chaos layer",
    "debug.checks_enabled": "Debug checks armed (0/1)",
    # obs plane itself
    "obs.scrape_ms": "Wall time per /metrics scrape (same-scrape sample)",
    "obs.federation_accepts": "Remote node snapshots accepted",
    "obs.federation_oversized": "Remote snapshots refused for size",
    "obs.federation_nodes_dropped": "Federated nodes evicted from the table",
    "obs.spans_dropped": "Spans dropped at the ring bound",
    "obs.hops_dropped": "Hop records dropped at the ring bound",
    "obs.series_dropped": "Series dropped at the per-family bound",
    # serving tier
    "serve.ingests": "Client snapshots accepted for fold",
    "serve.ingest_ms": "Wall time per ingest acceptance",
    "serve.merges": "Monoid merges performed by folds",
    "serve.fold_stacked": "Payloads folded via the stacked fast path",
    "serve.fold_errors": "Folds that raised and were quarantined",
    "serve.flush_ms": "Wall time per queue flush",
    "serve.flush_errors": "Flush worker iterations that raised",
    "serve.forward_errors": "Interior-node forward failures",
    "serve.queue_depth": "Current ingest queue depth",
    "serve.clients": "Live clients per tenant",
    "serve.tenants": "Registered tenants",
    "serve.value": "Latest computed scalar per tenant metric",
    "serve.query_ms": "Wall time per /query (same-scrape sample)",
    "serve.rejected": "Payloads rejected at admission",
    "serve.shed": "Payloads shed by backpressure",
    "serve.accept_errors": "Ingest decode/validation failures",
    "serve.wire_errors": "Wire-format decode failures",
    "serve.dedup_drops": "Stale payloads dropped by keep-latest dedup",
    "serve.poisoned": "Payloads flagged poisoned by the firewall",
    "serve.quarantined": "Clients quarantined (cumulative)",
    "serve.clients_quarantined": "Clients currently quarantined",
    "serve.quarantine_drops": "Payloads dropped from quarantined clients",
    "serve.circuit_open": "Circuit open transitions (cumulative)",
    "serve.circuits_open": "Circuits currently open",
    "serve.circuit_drops": "Payloads dropped by open circuits",
    "serve.firewall_untracked": "Firewall events for untracked clients",
    "serve.retired_clients": "Clients retired with tombstones",
    "serve.tombstones_evicted": "Retirement tombstones evicted at the cap",
    "serve.drains": "Node drains completed",
    "serve.heals": "Supervisor heals performed",
    "serve.heal_ms": "Wall time per supervisor heal",
    "serve.hop_queue_wait_ms": "Payload wait in a hop's ingest queue",
    "serve.hop_fold_ms": "Payload fold time at a hop",
    "serve.hop_ship_ms": "Payload ship time out of a hop",
    "serve.e2e_freshness_ms": "Encode-to-root-accept freshness per payload",
    "serve.warmed_programs": "AOT-warmed fold programs",
    "serve.ring_members": "Members in the elastic hash ring",
    "serve.rebalances": "Elastic rebalances completed",
    "serve.rebalance_ms": "Wall time per elastic rebalance",
    "serve.rebalance_started_ts": "Wall-clock start of in-flight rebalance (0=idle)",
    "serve.autoscaler_decisions": "Autoscaler scale decisions",
    "serve.autoscaler_errors": "Autoscaler evaluation failures",
    "serve.cross_region_merges": "Peer region snapshots merged into global view",
    "serve.replication_errors": "Cross-region ship failures",
    "serve.replication_loop_errors": "Replication loop iterations that raised",
    "serve.peer_staleness_ms": "Age of a peer region's replica",
    "serve.peers_unreachable": "Peer regions actively unreachable",
    "serve.global_query_staleness_ms": "Worst peer age behind a global query",
    "serve.mesh_regions": "Regions in the mesh",
    "serve.promotions": "Standby-to-root promotions",
    "serve.promote_ms": "Wall time per promotion",
    "serve.region_generation": "Current region generation (failover fence)",
    "serve.fenced_ships": "Ships refused by the generation fence",
    # time-travel history (metrics_tpu.serve.history)
    "history.cuts": "Interval snapshots cut into retention rings",
    "history.cut_ms": "Wall time per history cut across tenants",
    "history.cut_errors": "History cuts that raised (flush survives)",
    "history.intervals": "Intervals currently retained per tenant",
    "history.rollups": "Within-bucket rollup replacements at coarser levels",
    "history.intervals_evicted": "Intervals evicted past the retention horizon",
    "history.range_queries": "Range queries answered, by tenant and mode",
    "history.range_query_ms": "Wall time per range query",
    "history.fenced_range_queries": "Delta range queries refused across generations",
    "history.alerts": "Alert rule firing edges, by rule and tenant",
    "history.alert_active": "Alert rule currently firing (1) or clear (0)",
    # LLM evaluation (metrics_tpu.llm)
    "llm.perplexity_queries": "StreamingPerplexity bound/bits-per-byte queries",
    "llm.qa_queries": "StreamingTokenF1/ExactMatch bound queries",
    "llm.rag_queries": "StreamingRAGQuality bound/quantile queries",
    # online experimentation (metrics_tpu.experiment)
    "experiment.evaluations": "Sequential-test evaluations at history cuts, by experiment",
    "experiment.decisions": "Edge-triggered ship/stop decisions, by experiment and verdict",
    "experiment.fenced_evaluations": "Evaluations skipped across failover generations",
    "experiment.queries": "GET /experiment/<id> reports answered",
    "experiment.active": "Experiment still collecting (1) or decided (0)",
    # tenant-facing SLO plane (metrics_tpu.obs.slo)
    "slo.evaluations": "SLO evaluations at history cuts, by slo",
    "slo.alerts": "Edge-triggered burn-rate alert firings, by tenant and slo",
    "slo.alert_active": "Burn-rate alert currently firing (1) or clear (0)",
    "slo.burn_rate": "Error-budget burn rate over the fast/slow window",
    "slo.budget_remaining": "Fraction of the error budget left this period",
    "slo.sli": "Good-fraction SLI over the fast window, by tenant and slo",
    "slo.fenced_evaluations": "Budget baselines rebased across failover generations",
    "slo.ingest_errors": "Failed tenant ingests, by reason (accept/backpressure/shed/wire)",
    "slo.queries": "GET /slo reports answered",
    # per-tenant usage metering (metrics_tpu.obs.meter)
    "meter.wire_bytes": "Wire payload bytes decoded, by tenant",
    "meter.queue_ms": "Ingest-to-accept queue residency, by tenant",
    "meter.fold_ms": "Fold wall time attributed to the tenant",
    "meter.state_bytes": "Resident client + merged state bytes, by tenant",
    "meter.history_bytes": "Retention-ring bytes held for the tenant",
    # synthetic canary probes (metrics_tpu.obs.prober)
    "probe.probes": "Canary probe round trips completed, by node",
    "probe.results": "Canary verdicts, by node (match/mismatch/pending)",
    "probe.round_trip_ms": "Canary ship-to-verified round-trip latency",
    "probe.healthy": "Canary bitwise-correct so far (1) or mismatched (0)",
}


def register_help(family: str, text: str) -> None:
    """Register (or override) the one-line ``# HELP`` text for a raw
    dotted family name (e.g. ``"serve.ingests"``). Families without an
    entry still export, with a ``# TYPE`` line only."""
    _FAMILY_HELP[str(family)] = str(text)


def family_help(family: str) -> Optional[str]:
    """The registered ``# HELP`` text for a raw family name, or None."""
    return _FAMILY_HELP.get(family)


def _escape_help(text: str) -> str:
    # exposition format: HELP text escapes backslash and newline only
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _family_header(key: str, base: str, kind: str, lines: list) -> None:
    raw = key.split("{", 1)[0]
    text = _FAMILY_HELP.get(raw)
    if text is not None:
        lines.append(f"# HELP {base} {_escape_help(text)}")
    lines.append(f"# TYPE {base} {kind}")


def to_prometheus(snap: Optional[Dict[str, Any]] = None) -> str:
    """Render a snapshot in the Prometheus text exposition format."""
    snap = snapshot() if snap is None else snap
    lines: list = []
    typed: set = set()
    for kind, family in (("counter", "counters"), ("gauge", "gauges")):
        for key in sorted(snap.get(family, {})):
            base, _ = _prom_parts(key)
            if base not in typed:
                typed.add(base)
                _family_header(key, base, kind, lines)
            _prom_series(key, snap[family][key], lines)
    for key in sorted(snap.get("histograms", {})):
        base, _ = _prom_parts(key)
        if base not in typed:
            typed.add(base)
            _family_header(key, base, "histogram", lines)
        _prom_histogram(key, snap["histograms"][key], lines)
    return "\n".join(lines) + ("\n" if lines else "")


def _merge_hist(into: Dict[str, Any], new: Dict[str, Any], key: str) -> Dict[str, Any]:
    """Bucketwise-exact merge of two histogram dicts sharing the fixed
    :data:`~metrics_tpu.obs.registry.HISTOGRAM_EDGES` — counts add per
    bucket, ``sum``/``count`` add, ``min``/``max`` combine. Exact because
    every histogram in the package uses the same static edges; a bucket
    count mismatch means the snapshots came from incompatible builds and
    is refused rather than guessed at."""
    a, b = list(into.get("buckets") or []), list(new.get("buckets") or [])
    if len(a) != len(b):
        raise ValueError(
            f"histogram {key!r}: bucket counts differ ({len(a)} vs {len(b)}) —"
            " snapshots were built against different HISTOGRAM_EDGES"
        )
    x, y = _reg.HistogramSnapshot.from_dict(into), _reg.HistogramSnapshot.from_dict(new)
    snap = _reg.HistogramSnapshot(
        [i + j for i, j in zip(x.counts, y.counts)],
        x.sum + y.sum,
        x.count + y.count,
        min((h.min for h in (x, y) if h.count), default=float("inf")),
        max((h.max for h in (x, y) if h.count), default=float("-inf")),
    )
    return snap.to_dict()


def merge_snapshots(*snaps: Dict[str, Any]) -> Dict[str, Any]:
    """Merge obs snapshots from different nodes into one fleet view.

    The algebra (commutative and associative over distinct-node inputs,
    pinned by ``tests/bases/test_obs_federation.py``):

    * **counters** sum on identical series keys — fleet totals
      (per-node attribution stays available in the federation table's
      per-node snapshots, and in series that already carry ``node=``
      labels at the source, like ``serve.hop_*_ms{node=}``).
    * **gauges** keep per-node labels: a gauge without a ``node=`` label is
      tagged with its source snapshot's node identity (last-value semantics
      do not sum — ``serve.tenants`` from two nodes must stay two series);
      one already labeled (``serve.queue_depth{node=}``) passes through —
      aggregator node names are fleet-unique by the tree's client-identity
      contract.
    * **histograms** merge bucketwise — EXACT because
      :data:`~metrics_tpu.obs.registry.HISTOGRAM_EDGES` is shared by every
      histogram, so fleet percentiles are computed from true fleet bucket
      counts, not averaged per-node percentiles.

    Multiple snapshots carrying the SAME node identity are deduplicated to
    the newest ``captured_at`` first (snapshots are cumulative, so
    keep-latest is exact — summing two generations of one node would
    double-count). A plain snapshot that is NEWER than its node's
    contribution already summed inside a federated input cannot be excised
    exactly and is refused with ``ValueError`` — merge from per-node
    originals instead (the federation table always does).

    Returns a snapshot-shaped dict with ``federated: True`` and a
    ``nodes: {identity: captured_at}`` roster; :func:`to_prometheus` /
    :func:`to_json` render it unchanged.
    """
    plain: Dict[str, Dict[str, Any]] = {}
    federated: List[Dict[str, Any]] = []
    for snap in snaps:
        if snap.get("federated"):
            federated.append(snap)
            continue
        node = str(snap.get("node", ""))
        held = plain.get(node)
        if held is None or _snap_order(snap) > _snap_order(held):
            plain[node] = snap
    fed_rosters: Dict[str, float] = {}
    for fed in federated:
        for node in fed.get("nodes") or {}:
            if node in fed_rosters:
                # two federated inputs both already SUMMED this node's
                # counters; neither contribution can be excised, so a
                # silent merge would double-count — refuse, same as the
                # plain-vs-federated conflict below
                raise ValueError(
                    f"cannot merge: node {node!r} appears inside two already-"
                    "federated inputs — its counters would double-count."
                    " Merge from per-node originals (metrics_tpu.obs.federation"
                    " does)."
                )
            fed_rosters[node] = 1.0
    for fed in federated:
        for node, captured in (fed.get("nodes") or {}).items():
            held = plain.get(node)
            if held is None:
                continue
            if float(held.get("captured_at", 0.0)) > float(captured):
                raise ValueError(
                    f"cannot merge: node {node!r} has a newer standalone snapshot"
                    " than its contribution inside an already-federated input —"
                    " its old counters cannot be excised exactly. Merge from"
                    " per-node originals (metrics_tpu.obs.federation does)."
                )
            del plain[node]

    ordered = federated + [plain[k] for k in sorted(plain)]
    ordered.sort(key=_snap_order)
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    histograms: Dict[str, Dict[str, Any]] = {}
    nodes: Dict[str, float] = {}
    enabled = False
    for snap in ordered:
        enabled = enabled or bool(snap.get("enabled"))
        if snap.get("federated"):
            nodes.update(snap.get("nodes") or {})
        else:
            nodes[str(snap.get("node", ""))] = float(snap.get("captured_at", 0.0))
        for key, value in (snap.get("counters") or {}).items():
            counters[key] = counters.get(key, 0.0) + float(value)
        identity = None if snap.get("federated") else str(snap.get("node", ""))
        for key, value in (snap.get("gauges") or {}).items():
            gauges[_tag_node(key, identity)] = float(value)
        for key, hist in (snap.get("histograms") or {}).items():
            held = histograms.get(key)
            histograms[key] = _merge_hist(held, hist, key) if held is not None else _hist_dict(hist)
    return {
        "federated": True,
        "enabled": enabled,
        "nodes": nodes,
        "captured_at": max(nodes.values(), default=0.0),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
    }


def _snap_order(snap: Dict[str, Any]) -> Tuple[float, str]:
    """Deterministic, argument-order-independent processing order for the
    merge: by capture time, ties broken by node identity — so last-writer-
    wins gauge collisions resolve the same way however the call was
    parenthesized or ordered."""
    return (float(snap.get("captured_at", 0.0)), str(snap.get("node", "")))


def _tag_node(key: str, identity: Optional[str]) -> str:
    """Add ``node=identity`` to a flat series key unless it already carries
    a ``node=`` label (source-labeled serve series keep their fleet-unique
    aggregator node names)."""
    if identity is None:
        return key
    m = _KEY_RE.match(key)
    labels = (m.group("labels") or "") if m else ""
    if any(k == "node" for k, _ in _parse_labels(labels)):
        return key
    name = m.group("name") if m else key
    pairs = _parse_labels(labels) + [("node", identity)]
    inner = ",".join(f"{k}={_reg._fmt_label_value(v)}" for k, v in sorted(pairs))
    return f"{name}{{{inner}}}"


def _hist_dict(hist: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a (possibly edge-stripped wire-compact) histogram dict to
    the full :meth:`~metrics_tpu.obs.registry.HistogramSnapshot.to_dict`
    shape, recomputing the headline percentiles."""
    return _reg.HistogramSnapshot.from_dict(hist).to_dict()


def to_chrome_trace(path: Optional[str] = None) -> str:
    """Export the span log and hop ring as Chrome-trace JSON (the
    ``traceEvents`` array format Perfetto / ``chrome://tracing`` load).

    Two tracks: **host spans** (pid 1, one thread per nesting depth) and
    **payload lifecycles** (pid 2, one thread per trace id, events named by
    hop phase with the node in ``args``) — both on the wall clock, so a
    payload's client-encode → leaf-fold → root-queryable path lines up
    against the host work that produced it. Served by the root's
    ``/trace`` debug route (:class:`metrics_tpu.serve.MetricsServer`).
    """
    events: List[Dict[str, Any]] = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": f"host spans ({_reg.node_identity()})"}},
        {"ph": "M", "pid": 2, "name": "process_name", "args": {"name": "payload lifecycles"}},
    ]
    for span in _reg.spans():
        dur_us = max(0.0, span["wall_ms"] * 1000.0)
        events.append(
            {
                "name": span["name"],
                "cat": span.get("category") or "host",
                "ph": "X",
                "pid": 1,
                "tid": int(span.get("depth", 0)) + 1,
                "ts": (span["t"] - span["wall_ms"] / 1000.0) * 1e6,
                "dur": dur_us,
                "args": {"depth": span.get("depth", 0)},
            }
        )
    tids: Dict[str, int] = {}
    for hop in _reg.hops():
        tid = tids.get(hop["trace"])
        if tid is None:
            tid = tids[hop["trace"]] = len(tids) + 1
            events.append(
                {"ph": "M", "pid": 2, "tid": tid, "name": "thread_name",
                 "args": {"name": f"trace {hop['trace']}"}}
            )
        dur_us = max(0.0, hop["dur_ms"] * 1000.0)
        events.append(
            {
                "name": f"{hop['phase']}@{hop['node']}",
                "cat": "hop",
                "ph": "X",
                "pid": 2,
                "tid": tid,
                "ts": (hop["ts"] - hop["dur_ms"] / 1000.0) * 1e6,
                "dur": dur_us,
                "args": {k: v for k, v in hop.items() if k not in ("ts", "dur_ms")},
            }
        )
    text = json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})
    if path is not None:
        with open(path, "w") as f:
            f.write(text + "\n")
    return text


def to_json(snap: Optional[Dict[str, Any]] = None, path: Optional[str] = None, indent: int = 2) -> str:
    """Serialize a snapshot to JSON; optionally also write it to ``path``.

    The file write is atomic (staged sibling temp file + ``os.replace``,
    the ``atomic_dir_swap`` idiom): a scraper or a restarting process
    reading ``path`` mid-write sees either the complete previous snapshot
    or the complete new one, never a truncated JSON document. On error the
    stage is discarded and any existing ``path`` is untouched.
    """
    text = json.dumps(snapshot() if snap is None else snap, indent=indent, sort_keys=True)
    if path is not None:
        import os
        import tempfile

        final = os.fspath(os.path.abspath(path))
        parent = os.path.dirname(final) or "."
        fd, stage = tempfile.mkstemp(prefix=".tmp.obs.", suffix=".json", dir=parent)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text + "\n")
                f.flush()
                os.fsync(f.fileno())
            # mkstemp creates 0600 regardless of umask; installing that over
            # an existing snapshot would revoke other readers (a scraper
            # running as a different user). Preserve the target's mode, or
            # a plain umask-honoring open()-equivalent for a fresh file.
            try:
                mode = os.stat(final).st_mode & 0o7777
            except OSError:
                umask = os.umask(0)
                os.umask(umask)
                mode = 0o666 & ~umask
            os.chmod(stage, mode)
            os.replace(stage, final)
        except BaseException:
            try:
                os.unlink(stage)
            except OSError:
                pass
            raise
    return text
