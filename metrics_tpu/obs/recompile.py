"""Recompile telemetry: tracings, compiles and compile-seconds per step.

A jitted metric step that keeps re-tracing (batch-size drift, dtype
flapping, a Python scalar leaking into the signature) silently turns a
microsecond hot path into a seconds-long compile storm — invisible today
because jax retraces without a word. Three hooks make it visible:

* :func:`note_trace` — called at the top of every ``make_step`` /
  ``make_epoch`` function body. The body of a jitted function only executes
  when jax is TRACING it, so an in-body counter bump counts exactly the
  tracings of that step (eager calls are counted separately by probing the
  trace state). Crossing ``recompile_warn_threshold`` distinct tracings
  fires a one-shot ``rank_zero_warn`` storm warning.
* :func:`track_compiles` — wraps a jitted callable; a call during which the
  step's tracing counter advanced is attributed to ``compile_seconds``
  (trace + lower + backend compile all happen inside that call), every
  other call to ``run_seconds``. This is the compile-vs-run split
  ``bench.py --json`` publishes per row.
* :func:`install_compile_listener` — registers a ``jax.monitoring``
  duration listener so EVERY backend compile in the process (not just ones
  routed through ``make_step``) lands in ``jax.compile_seconds`` /
  ``jax.compiles``, plus (same call, same opt-in) an event listener for
  jax's persistent-compilation-cache hits and misses —
  ``compile.cache_hits{tier=jax_persistent}`` /
  ``compile.cache_misses{tier=jax_persistent}``. Together with the
  :mod:`metrics_tpu.engine` program-store counters (same families,
  ``step=``/``tier=`` labels) they make warm-start efficacy observable:
  a revived serving node that really started warm shows cache hits and
  ZERO ``jax.compiles`` growth on its first fold. Best-effort: silently
  unavailable on jax builds without the listener API.

All three are inert unless the registry is enabled; ``note_trace`` in a
traced body adds zero operations to the program (a Python-level counter
bump at trace time only).
"""
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional

from metrics_tpu.obs import registry as _reg

__all__ = [
    "compile_listener_installed",
    "install_compile_listener",
    "note_collection_fusion",
    "note_trace",
    "suppress_note_trace",
    "track_compiles",
]

_warned_steps: set = set()
# per-factory trace counts for the storm heuristic: the PUBLIC step.traces
# counter aggregates by step label (class name), so eight distinct
# make_step(Accuracy) factories tracing once each would pool to 8 and fake
# a storm; each factory passes its own token so the threshold only sees
# retraces of that one step
_traces_by_token: dict = {}
_listener_installed = False


_trace_probe: Optional[Callable[[], bool]] = None


def _resolve_trace_probe() -> Callable[[], bool]:
    """Resolve a ``() -> currently-tracing`` probe ONCE against this jax.

    ``jax.core.trace_state_clean`` is the cheap probe but lives in the
    deprecated ``jax.core`` namespace; newer releases keep it under
    ``jax._src.core``. The last-resort fallback stages a constant and asks
    whether it came back as a tracer (omnistaging guarantees it does under
    any trace) — never a silent wrong answer, unlike swallowing per call.
    """
    try:
        import jax

        fn = getattr(jax.core, "trace_state_clean", None)
        if fn is None:
            from jax._src import core as _core

            fn = getattr(_core, "trace_state_clean", None)
        if fn is not None:
            fn()  # probe once; a broken shim falls through to the fallback
            return lambda: not fn()
    except Exception:
        pass

    def _tracer_fallback() -> bool:
        import jax
        import jax.numpy as jnp

        return isinstance(jnp.zeros(()), jax.core.Tracer)

    return _tracer_fallback


def _in_trace_context() -> bool:
    global _trace_probe
    if _trace_probe is None:
        _trace_probe = _resolve_trace_probe()
    return _trace_probe()


# thread-local suppression flag: cost-analysis attribution re-traces the
# step via AOT lower(), and that bookkeeping trace must not count as a real
# (re)tracing or advance the storm threshold
_tls = threading.local()


@contextmanager
def suppress_note_trace():
    """Silence :func:`note_trace` on this thread for the enclosed block
    (used by :func:`metrics_tpu.obs.profile.record_cost_analysis` around
    its AOT lower+compile, whose retrace is attribution, not drift)."""
    prev = getattr(_tls, "suppressed", False)
    _tls.suppressed = True
    try:
        yield
    finally:
        _tls.suppressed = prev


def note_trace(step: str, token: Optional[object] = None) -> None:
    """Record one execution of a step function body under the given name.

    Inside a trace: counts a (re)tracing of the jitted step and fires the
    recompile-storm warning at the configured threshold. Outside a trace:
    counts an eager call. ``token`` identifies ONE step factory (the public
    ``step.traces`` counter aggregates by label across factories, but the
    storm threshold must only see retraces of the same step).
    """
    if not _reg.enabled() or getattr(_tls, "suppressed", False):
        return
    if not _in_trace_context():
        _reg.inc("step.eager_calls", step=step)
        return
    _reg.inc("step.traces", step=step)
    threshold = _reg.get_config("recompile_warn_threshold")
    key = token if token is not None else step
    if len(_traces_by_token) >= 4096 and key not in _traces_by_token:
        # bound the per-factory book-keeping in factory-per-job loops; losing
        # old factories' counts only delays a storm warning, never leaks
        _traces_by_token.clear()
    traces = _traces_by_token[key] = _traces_by_token.get(key, 0) + 1
    if threshold and traces >= threshold and key not in _warned_steps:
        _warned_steps.add(key)
        from metrics_tpu.utilities.prints import rank_zero_warn

        rank_zero_warn(
            f"Recompile storm: jitted metric step '{step}' has been traced {int(traces)} times"
            f" (threshold {threshold}). Every distinct input shape/dtype signature compiles a new"
            " program — pad batches to a stable shape, pin dtypes, or hash-check what varies."
            " Raise the threshold with metrics_tpu.obs.configure(recompile_warn_threshold=N).",
            UserWarning,
        )


def reset_storm_warnings() -> None:
    """Re-arm the one-shot storm warning (used by tests and obs.reset)."""
    _warned_steps.clear()
    _traces_by_token.clear()


def track_compiles(fn: Callable, step: str) -> Callable:
    """Wrap a jitted callable to split its wall time into compile vs run.

    The step's ``note_trace`` counter is read before and after each call: a
    call that advanced it paid for trace+lower+compile and lands in
    ``compile_seconds{step=...}`` / ``compiles{step=...}``; a cache-hit call
    lands in ``run_seconds{step=...}`` / ``runs{step=...}``. Disabled mode
    short-circuits to the raw callable (one predicate per call).

    Two opt-in modes extend the split (see :mod:`metrics_tpu.obs.profile`):
    with ``obs.configure(device_timing=True)`` every cache-hit launch
    blocks on its outputs and the wall delta lands in the
    ``step.latency_ms{step=...}`` histogram (compile launches are excluded
    — their wall time is compilation, already in ``compile_seconds``);
    with ``obs.configure(cost_analysis=True)`` every compile-paying call
    records the lowered program's FLOPs / bytes-accessed / arithmetic-
    intensity gauges for this step.
    """
    import functools

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if not _reg.enabled():
            return fn(*args, **kwargs)
        device_timing = bool(_reg.get_config("device_timing"))
        before = _reg.get_counter("step.traces", step=step)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        compiled_now = _reg.get_counter("step.traces", step=step) > before
        if device_timing and not compiled_now:
            import jax

            jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if compiled_now:
            _reg.inc("compile_seconds", dt, step=step)
            _reg.inc("compiles", step=step)
            if _reg.get_config("cost_analysis"):
                from metrics_tpu.obs.profile import record_cost_analysis

                # args are only read as shape/dtype metadata, so donated
                # (already-consumed) buffers are safe to pass
                record_cost_analysis(fn, args, kwargs, step)
        else:
            _reg.inc("run_seconds", dt, step=step)
            _reg.inc("runs", step=step)
            if device_timing:
                _reg.observe("step.latency_ms", dt * 1000.0, step=step)
        return out

    return wrapped


def note_epoch_launch(step: str, n_batches: Optional[int]) -> None:
    """Count one fused-epoch launch and the batches it folds (host-side,
    from the eager entry's argument shapes — zero trace impact)."""
    if not _reg.enabled():
        return
    _reg.inc("epoch.launches", step=step)
    if n_batches is not None:
        _reg.inc("epoch.batches_folded", float(n_batches), step=step)
        _reg.set_gauge("epoch.batches_per_launch", float(n_batches), step=step)


def note_collection_fusion(step: str, n_members: int, n_groups: int) -> None:
    """Record a fused collection program's member/update-group counts under
    its per-collection step label (``collection.members`` /
    ``collection.update_groups`` gauges) — the cost-attribution key for
    whole-collection fusion: ``step.flops``/``step.bytes_accessed`` rows
    carry the same ``step=`` label, so a 12-member 4-group program's cost
    is attributable to the collection rather than smeared over members.

    Called from the (possibly traced) fused body: a Python-level gauge set
    at trace time only — zero operations in the compiled program."""
    if not _reg.enabled():
        return
    _reg.set_gauge("collection.members", float(n_members), step=step)
    _reg.set_gauge("collection.update_groups", float(n_groups), step=step)


def compile_listener_installed() -> bool:
    """Whether the backend-compile listener is live — without installing it."""
    return _listener_installed


def install_compile_listener() -> bool:
    """Register a process-wide ``jax.monitoring`` listener for backend
    compile durations. Returns True when installed (idempotent).

    Installation is itself the opt-in: once installed, the listener records
    ``jax.compiles`` / ``jax.compile_seconds`` regardless of the enabled
    flag, so a consumer that only wants the compile split (e.g. ``bench.py``
    attributing section compile time) need not arm the full layer — whose
    eager-path spans/counters would sit inside timed regions."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        from jax._src import monitoring
    except Exception:
        return False
    if not hasattr(monitoring, "register_event_duration_secs_listener"):
        return False

    def _on_duration(event: str, duration: float, **kwargs: Any) -> None:
        # ONLY the backend-compile phase: jax emits several events per
        # compiled program whose names contain "compile" (jaxpr trace,
        # MLIR lowering, cache-hit time-SAVED), and summing them would
        # overcount one compile ~10x and book phantom seconds on warm
        # persistent-cache hits. The backend_compile_duration event is the
        # actual XLA compile wall time, once per program.
        if event.endswith("backend_compile_duration"):
            _reg.inc("jax.compile_seconds", duration)
            _reg.inc("jax.compiles")

    def _on_event(event: str, **kwargs: Any) -> None:
        # jax's persistent compilation cache (jax_compilation_cache_dir)
        # emits one event per compile request it resolves: a hit means the
        # backend compile was skipped (an executable deserialized from the
        # cache dir), a miss means it was paid and the result stored.
        # Counted under the same compile.cache_* families the engine's
        # program store uses, distinguished by tier=.
        if event.endswith("/compilation_cache/cache_hits"):
            _reg.inc("compile.cache_hits", tier="jax_persistent")
        elif event.endswith("/compilation_cache/cache_misses"):
            _reg.inc("compile.cache_misses", tier="jax_persistent")

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        if hasattr(monitoring, "register_event_listener"):
            monitoring.register_event_listener(_on_event)
    except Exception:
        return False
    _listener_installed = True
    return True
