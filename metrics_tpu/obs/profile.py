"""Programmatic profiling, per-launch device timing, cost-analysis gauges.

Three answers to "how fast did it run, and why" (the performance tier on
top of the counter/span registry):

* :func:`profile` — programmatic xprof capture around a code block via
  ``jax.profiler.trace``: the TPU timeline lands in a TensorBoard-readable
  log dir, with every op grouped under the ``jax.named_scope`` names the
  tracing layer stamps (enable the obs layer BEFORE building steps so the
  scopes are in the traced programs).
* **device timing** (``obs.configure(device_timing=True)``) — every
  tracked launch (the jitted ``make_epoch`` / ``make_stream_step``
  callables, eager ``make_step`` step/compute calls, eager pallas kernel
  dispatches) is followed by ``jax.block_until_ready`` and the wall delta
  lands in the ``step.latency_ms{step=...}`` histogram — real device-time
  distributions (p50/p95/p99) instead of dispatch-only wall clock.
  Opt-in because the block is a host sync: it serializes launches that an
  async dispatch queue would overlap.
* **cost analysis** (``obs.configure(cost_analysis=True)``) — each compile
  of a tracked step pulls ``Compiled.cost_analysis()`` for the lowered
  program into gauges: ``step.flops{step=}``, ``step.bytes_accessed{step=}``
  and their ratio ``step.arithmetic_intensity{step=}`` (FLOPs/byte — the
  roofline x-coordinate). Attribution is per lowered signature, refreshed
  on every retrace, so shape drift shows up as moving gauges next to the
  ``step.traces`` counter it also bumps.

All three are inert unless the registry is enabled; the two config modes
additionally default off so merely enabling the layer never adds host
syncs or AOT compiles.
"""
import functools
import time
from contextlib import contextmanager
from typing import Any, Callable, Iterator, Optional

from metrics_tpu.obs import registry as _reg

__all__ = ["instrument", "profile", "record_cost_analysis", "time_launch"]


@contextmanager
def profile(logdir: str, create_perfetto_link: bool = False) -> Iterator[str]:
    """Capture an xprof/TensorBoard profile of the enclosed block.

    Thin, obs-integrated wrapper over ``jax.profiler.trace``: the capture
    always runs (profiling is its own opt-in — like
    :func:`~metrics_tpu.obs.install_compile_listener`, calling it IS the
    consent), and when the obs layer is enabled the capture is also counted
    under ``profile.captures`` with its wall time in the
    ``profile.capture_ms`` histogram.

    Args:
        logdir: directory for the trace files (``tensorboard --logdir`` /
            xprof reads it; one timestamped subdir per capture).
        create_perfetto_link: forward to ``jax.profiler.trace`` — prints a
            Perfetto UI link for the captured trace (blocks until visited).

    Example::

        with obs.profile("/tmp/prof"):
            state, _ = epoch(state, preds, target)
    """
    import jax

    t0 = time.perf_counter()
    with jax.profiler.trace(logdir, create_perfetto_link=create_perfetto_link):
        yield logdir
    if _reg.enabled():
        _reg.inc("profile.captures")
        _reg.observe("profile.capture_ms", (time.perf_counter() - t0) * 1000.0)


def _timing_armed() -> bool:
    return _reg.enabled() and bool(_reg.get_config("device_timing"))


def time_launch(fn: Callable, step: str) -> Callable:
    """Wrap an EAGER-callable so device timing records its launch latency.

    When ``device_timing`` is armed and the call happens outside any trace,
    the wrapper blocks on the outputs and records the wall delta into
    ``step.latency_ms{step=...}``. Under a trace it is pass-through (Python
    runs at trace time only — blocking on tracers is impossible and the
    wrapper must add zero operations to compiled programs), and with the
    mode off it costs one predicate per call. For a callable YOU jitted,
    wrap the jitted object with :func:`instrument` instead, so the compile
    launches are split out of the latency distribution.
    """
    from metrics_tpu.obs.recompile import _in_trace_context

    @functools.wraps(fn)
    def timed(*args: Any, **kwargs: Any) -> Any:
        if not _timing_armed() or _in_trace_context():
            return fn(*args, **kwargs)
        import jax

        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        _reg.observe("step.latency_ms", (time.perf_counter() - t0) * 1000.0, step=step)
        return out

    return timed


def instrument(fn: Callable, step: str) -> Callable:
    """Arm a JITTED callable with the full tracked-launch telemetry.

    The same wrapper ``make_epoch`` / ``make_stream_step`` apply to their
    internal jits, for steps you jit yourself::

        init, step_fn, compute = make_step(Accuracy, num_classes=10)
        jstep = obs.instrument(jax.jit(step_fn, donate_argnums=0), "Accuracy.step")

    Per call this splits wall time into compile vs run
    (``compiles``/``runs``/``compile_seconds``/``run_seconds{step=}``);
    with ``device_timing`` armed, cache-hit launches block on their outputs
    and land in the ``step.latency_ms{step=}`` histogram (compile launches
    are excluded — their wall time is dominated by compilation and already
    attributed to ``compile_seconds``); with ``cost_analysis`` armed, each
    compile records the lowered program's FLOPs/bytes gauges.
    """
    from metrics_tpu.obs.recompile import track_compiles

    return track_compiles(fn, step)


def _as_spec(leaf: Any) -> Any:
    import jax

    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
    return leaf


def record_cost_analysis(fn: Callable, args: tuple, kwargs: dict, step: str) -> bool:
    """Pull ``Compiled.cost_analysis()`` for ``fn(*args, **kwargs)`` into
    per-step gauges; returns True when the gauges were written.

    ``fn`` must be a jitted callable. The call signature is abstracted to
    ``ShapeDtypeStruct`` leaves first, so AOT lowering never touches the
    actual buffers — donated arguments may already be consumed by the call
    that triggered the attribution (only their metadata is read). The AOT
    retrace runs with :func:`~metrics_tpu.obs.recompile.note_trace`
    suppressed so attribution can never inflate ``step.traces`` or trip the
    storm warning. Failures (backends without cost analysis, non-jit
    callables) count under ``profile.cost_analysis_failures{step=}`` and
    never raise.
    """
    import jax

    from metrics_tpu.obs import recompile as _recompile

    try:
        spec_args, spec_kwargs = jax.tree_util.tree_map(_as_spec, (tuple(args), dict(kwargs)))
        with _recompile.suppress_note_trace():
            cost = fn.lower(*spec_args, **spec_kwargs).compile().cost_analysis()
    except Exception:  # noqa: BLE001 — telemetry must never break the step
        _reg.inc("profile.cost_analysis_failures", step=step)
        return False
    # jax returns one properties dict per computation (list on older
    # releases, bare dict on newer); the entry point is always first
    entry = cost[0] if isinstance(cost, (list, tuple)) and cost else cost
    if not isinstance(entry, dict):
        _reg.inc("profile.cost_analysis_failures", step=step)
        return False
    flops = float(entry.get("flops", 0.0) or 0.0)
    nbytes = float(entry.get("bytes accessed", 0.0) or 0.0)
    _reg.set_gauge("step.flops", flops, step=step)
    _reg.set_gauge("step.bytes_accessed", nbytes, step=step)
    if nbytes > 0.0:
        _reg.set_gauge("step.arithmetic_intensity", flops / nbytes, step=step)
    return True
