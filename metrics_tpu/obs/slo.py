"""Per-tenant SLOs: declarative SLIs, error budgets, burn-rate alerting.

The serving tier's white-box counters answer "what is the fleet doing";
this module answers the operator's actual page-worthy question — "which
TENANT is unhealthy, and how fast is it burning its error budget". Three
pieces:

* :class:`SLODef` — a declarative objective over an SLI computed from
  the existing :mod:`metrics_tpu.obs` registry. The built-ins
  (:func:`default_slos`) read families the aggregator already records
  per tenant: **ingest success** (``serve.ingests`` vs the
  ``slo.ingest_errors{tenant=,reason=}`` failures instrumented at the
  ingest/accept/shed seams), **freshness** (the per-tenant
  ``serve.e2e_freshness_ms{node=,tenant=}`` histogram — good means a
  payload went encode-to-queryable under the threshold), **query
  latency** (``serve.query_ms{tenant=}``), and **canary correctness**
  (the :mod:`metrics_tpu.obs.prober` ``probe.results`` verdicts for the
  reserved ``__canary__`` tenant).

* :class:`ErrorBudget` — one durable record per ``(tenant, slo)``:
  monotone rebased good/bad totals, a bounded sample ring for window
  differencing, the firing flag and alert/evaluation counts. JSON-safe,
  so it rides the aggregator's checkpoint manifest bitwise
  (``meta["slo"]``, beside the history rings and experiment records).

* :class:`SLOEngine` — rides the same :meth:`MetricHistory.add_cut_hook`
  seam the experiment :class:`~metrics_tpu.experiment.DecisionEngine`
  uses: every cut evaluates every attached SLO for every tenant,
  differencing cumulative registry totals into per-window event deltas.
  Alerting is the Google-SRE multi-window multi-burn-rate rule: fire
  when the burn rate over BOTH the fast and slow window exceeds the
  rule's threshold (fast window catches the step change, slow window
  keeps one-sample blips from paging). Transitions are edge-triggered
  through the one-shot-warn machinery — ``slo.alerts{tenant=,slo=}``
  counts firing EDGES, ``slo.alert_active`` is the level, recovery
  clears the gauge and re-arms the counter exactly like
  ``MetricHistory._transition``.

Failover fencing: every record carries the history generation it was
built under. A promotion mints a new generation AND a new process whose
registry counters restart — differencing across that boundary would
subtract two unrelated histories, so the engine rebases the raw
baselines instead (counted under ``slo.fenced_evaluations``); the
durable rebased totals and the consumed budget survive untouched.

Unarmed cost: an aggregator without an attached engine pays nothing
(the cut hook is never registered); with obs disabled the engine's
sources read zero and the hot-path instrumentation never runs — the
disabled-mode HLO byte-identity pin is untouched.
"""
import threading
import warnings
import weakref
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.obs import registry as _reg

__all__ = [
    "CANARY_TENANT",
    "ErrorBudget",
    "SLODef",
    "SLOEngine",
    "default_slos",
    "reset",
]

# the reserved synthetic-probe tenant (see metrics_tpu.obs.prober); the
# canary SLI only ever evaluates for this tenant
CANARY_TENANT = "__canary__"

# engines register here so metrics_tpu.obs.reset() can clear budget
# tables without the obs package importing the serving tier
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()

# samples kept per (tenant, slo) ring past the window horizon — one
# anchor at-or-before the slow window start is required for exact
# differencing; the rest is headroom for irregular cut cadences
_MAX_SAMPLES = 512


class SLODef:
    """One declarative objective: ``sli`` names the source, ``objective``
    the target good-fraction, the dual windows the burn-rate rule.

    Args:
        name: the slo label on every exported series and alert.
        sli: ``"ingest_success"`` | ``"freshness"`` | ``"query_latency"``
            | ``"canary"`` — which registry families feed good/bad.
        objective: target good-fraction in (0, 1); ``1 - objective`` is
            the error budget.
        threshold_ms: for histogram-backed SLIs (freshness, query
            latency): an observation at or under this is *good*. The
            cutoff snaps to the nearest shared histogram bucket edge so
            the good-count is exact, not interpolated.
        fast_window_s / slow_window_s: the two burn-rate windows.
        fast_burn / slow_burn: burn-rate thresholds; the alert fires
            when BOTH windows exceed their threshold (the SRE-workbook
            14.4x/6x page rule shape).
        budget_window_s: the accounting period ``budget_remaining`` is
            computed over (defaults to 24h).
    """

    _SLIS = ("ingest_success", "freshness", "query_latency", "canary")

    def __init__(
        self,
        name: str,
        *,
        sli: str,
        objective: float,
        threshold_ms: Optional[float] = None,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        fast_burn: float = 14.4,
        slow_burn: float = 6.0,
        budget_window_s: float = 86400.0,
    ) -> None:
        if sli not in self._SLIS:
            raise ValueError(f"unknown sli {sli!r}; expected one of {self._SLIS}")
        if not 0.0 < float(objective) < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if sli in ("freshness", "query_latency") and threshold_ms is None:
            raise ValueError(f"sli {sli!r} needs threshold_ms (what counts as good)")
        if float(fast_window_s) <= 0 or float(slow_window_s) <= 0:
            raise ValueError("windows must be positive")
        if float(fast_window_s) > float(slow_window_s):
            raise ValueError(
                f"fast window ({fast_window_s}s) must not exceed slow window"
                f" ({slow_window_s}s)"
            )
        self.name = str(name)
        self.sli = sli
        self.objective = float(objective)
        self.threshold_ms = None if threshold_ms is None else float(threshold_ms)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.budget_window_s = float(budget_window_s)

    @property
    def budget_fraction(self) -> float:
        return 1.0 - self.objective

    def config(self) -> Dict[str, Any]:
        """JSON-safe definition (the ``GET /slo`` report's slos block)."""
        return {
            "sli": self.sli,
            "objective": self.objective,
            "threshold_ms": self.threshold_ms,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn": self.fast_burn,
            "slow_burn": self.slow_burn,
            "budget_window_s": self.budget_window_s,
        }

    def __repr__(self) -> str:
        return (
            f"SLODef({self.name!r}, sli={self.sli!r}, objective={self.objective},"
            f" windows=({self.fast_window_s:g}s@{self.fast_burn:g}x,"
            f" {self.slow_window_s:g}s@{self.slow_burn:g}x))"
        )


def default_slos() -> List[SLODef]:
    """The three built-in white-box SLOs plus the canary's black-box one.

    Objectives are deliberately conservative defaults — a deployment
    tunes them per tenant class; the smoke and tests construct their own
    tighter definitions."""
    return [
        SLODef("ingest", sli="ingest_success", objective=0.999),
        SLODef("freshness", sli="freshness", objective=0.99, threshold_ms=60_000.0),
        SLODef("query_latency", sli="query_latency", objective=0.99, threshold_ms=250.0),
        SLODef("canary", sli="canary", objective=0.999),
    ]


def _histogram_good_bad(
    name: str, threshold_ms: float, **labels: Any
) -> Optional[Tuple[float, float]]:
    """Cumulative (good, bad) split of one histogram series at the bucket
    edge nearest ``threshold_ms`` — exact, because bucket counts are."""
    snap = _reg.get_histogram(name, **labels)
    if snap is None:
        return None
    good = 0.0
    for edge, count in zip(_reg.HISTOGRAM_EDGES, snap.counts):
        if edge <= threshold_ms:
            good += count
        else:
            break
    return good, float(snap.count) - good


class ErrorBudget:
    """The durable per-``(tenant, slo)`` record. Plain-dict state
    (:meth:`to_dict`/:meth:`from_dict`) so checkpoints carry it bitwise."""

    __slots__ = (
        "tenant", "slo", "raw_good", "raw_bad", "good", "bad",
        "samples", "firing", "alerts", "evaluations", "fenced", "generation",
    )

    def __init__(self, tenant: str, slo: str, *, generation: int = 0) -> None:
        self.tenant = str(tenant)
        self.slo = str(slo)
        # last cumulative registry totals seen (the differencing baseline)
        self.raw_good = 0.0
        self.raw_bad = 0.0
        # monotone REBASED totals: survive counter resets and failovers
        self.good = 0.0
        self.bad = 0.0
        # [t, good, bad] rings (rebased totals) for window differencing
        self.samples: List[List[float]] = []
        self.firing = False
        self.alerts = 0
        self.evaluations = 0
        self.fenced = 0
        self.generation = int(generation)

    # -- accounting ------------------------------------------------------

    def observe(self, now: float, raw_good: float, raw_bad: float, horizon_s: float) -> None:
        """Fold one cumulative reading into the rebased totals + ring. A
        raw total BELOW the stored baseline means the source registry
        restarted (restore into a fresh process): the events counted so
        far are new work, so the delta rebases from zero rather than
        going negative or double-counting."""
        d_good = raw_good - self.raw_good
        if d_good < 0:
            d_good = raw_good
        d_bad = raw_bad - self.raw_bad
        if d_bad < 0:
            d_bad = raw_bad
        self.raw_good = float(raw_good)
        self.raw_bad = float(raw_bad)
        self.good += d_good
        self.bad += d_bad
        self.samples.append([float(now), self.good, self.bad])
        self._prune(now, horizon_s)

    def _prune(self, now: float, horizon_s: float) -> None:
        cutoff = now - horizon_s
        # keep ONE anchor at-or-before the horizon: window differencing
        # needs the newest sample older than the window start
        while len(self.samples) > 2 and self.samples[1][0] <= cutoff:
            self.samples.pop(0)
        while len(self.samples) > _MAX_SAMPLES:
            self.samples.pop(0)

    def _baseline(self, now: float, window_s: float) -> Tuple[float, float]:
        """Rebased (good, bad) totals at the window start: the newest
        sample at-or-before ``now - window_s``, or the implicit (0, 0)
        origin when tracking is younger than the window."""
        start = now - window_s
        base_good, base_bad = 0.0, 0.0
        for t, g, b in self.samples:
            if t <= start:
                base_good, base_bad = g, b
            else:
                break
        return base_good, base_bad

    def window_counts(self, now: float, window_s: float) -> Tuple[float, float]:
        """(good, bad) event counts inside the window ending at ``now``."""
        base_good, base_bad = self._baseline(now, window_s)
        return max(0.0, self.good - base_good), max(0.0, self.bad - base_bad)

    def burn_rate(self, now: float, window_s: float, budget_fraction: float) -> float:
        """Observed bad-fraction over the window divided by the allowed
        fraction — 1.0 burns the budget exactly at its sustainable rate."""
        good, bad = self.window_counts(now, window_s)
        total = good + bad
        if total <= 0.0:
            return 0.0
        return (bad / total) / max(budget_fraction, 1e-12)

    def sli(self, now: float, window_s: float) -> Optional[float]:
        """Good-fraction over the window; None when no events landed."""
        good, bad = self.window_counts(now, window_s)
        total = good + bad
        if total <= 0.0:
            return None
        return good / total

    def budget_remaining(self, now: float, slo: SLODef) -> float:
        """Fraction of the error budget left over ``budget_window_s``
        (clamped to [0, 1])."""
        burn = self.burn_rate(now, slo.budget_window_s, slo.budget_fraction)
        return min(1.0, max(0.0, 1.0 - burn))

    # -- durability ------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "tenant": self.tenant,
            "slo": self.slo,
            "raw_good": self.raw_good,
            "raw_bad": self.raw_bad,
            "good": self.good,
            "bad": self.bad,
            "samples": [list(s) for s in self.samples],
            "firing": bool(self.firing),
            "alerts": int(self.alerts),
            "evaluations": int(self.evaluations),
            "fenced": int(self.fenced),
            "generation": int(self.generation),
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "ErrorBudget":
        rec = cls(str(data["tenant"]), str(data["slo"]), generation=int(data.get("generation", 0)))
        rec.raw_good = float(data.get("raw_good", 0.0))
        rec.raw_bad = float(data.get("raw_bad", 0.0))
        rec.good = float(data.get("good", 0.0))
        rec.bad = float(data.get("bad", 0.0))
        rec.samples = [[float(v) for v in s] for s in (data.get("samples") or [])]
        rec.firing = bool(data.get("firing", False))
        rec.alerts = int(data.get("alerts", 0))
        rec.evaluations = int(data.get("evaluations", 0))
        rec.fenced = int(data.get("fenced", 0))
        return rec


class SLOEngine:
    """Evaluates attached :class:`SLODef` s for every tenant on each
    history cut; owns the per-``(tenant, slo)`` :class:`ErrorBudget`
    table and the ``GET /slo`` report.

    Construction requires a history-armed aggregator (the cut hook is
    the evaluation clock, exactly the DecisionEngine seam) and attaches
    the engine as ``aggregator.slo``.
    """

    def __init__(self, aggregator: Any, slos: Optional[List[SLODef]] = None) -> None:
        from metrics_tpu.serve.aggregator import ServeError

        if aggregator.history is None:
            raise ServeError(
                f"aggregator {aggregator.name!r} has no history armed; the SLO"
                " engine evaluates on interval cuts — construct the aggregator"
                " with history=HistoryConfig(...)"
            )
        slos = default_slos() if slos is None else list(slos)
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate slo names: {sorted(names)}")
        self._aggregator = aggregator
        self._history = aggregator.history
        self._slos: Dict[str, SLODef] = {s.name: s for s in slos}
        self._budgets: Dict[Tuple[str, str], ErrorBudget] = {}
        self._warned: set = set()
        self._lock = threading.Lock()
        self._history.add_cut_hook(self._on_cut)
        aggregator._slo_engine = self
        _ENGINES.add(self)

    # -- registry --------------------------------------------------------

    def slo_names(self) -> List[str]:
        return sorted(self._slos)

    def budget(self, tenant: str, slo: str) -> Optional[ErrorBudget]:
        with self._lock:
            return self._budgets.get((str(tenant), str(slo)))

    # -- evaluation ------------------------------------------------------

    def _on_cut(self, history: Any, aggregator: Any) -> None:
        try:
            self.evaluate_all(now=history._last_cut_s)
        except Exception as err:  # noqa: BLE001 — an SLO bug must not kill cuts
            if "evaluate_all" not in self._warned:
                self._warned.add("evaluate_all")
                warnings.warn(
                    f"slo evaluation failed: {type(err).__name__}: {err}",
                    stacklevel=2,
                )

    def _sli_totals(self, slo: SLODef, tenant: str) -> Optional[Tuple[float, float]]:
        """Cumulative (good, bad) registry totals for one SLI, or None
        when the SLI does not apply to / has never observed the tenant."""
        node = self._aggregator.name
        if slo.sli == "ingest_success":
            good = _reg.get_counter("serve.ingests", tenant=tenant)
            bad = 0.0
            for reason in ("accept", "backpressure", "shed", "wire"):
                bad += _reg.get_counter("slo.ingest_errors", tenant=tenant, reason=reason)
            if good == 0.0 and bad == 0.0:
                return None
            return good, bad
        if slo.sli == "freshness":
            return _histogram_good_bad(
                "serve.e2e_freshness_ms", slo.threshold_ms, node=node, tenant=tenant
            )
        if slo.sli == "query_latency":
            return _histogram_good_bad("serve.query_ms", slo.threshold_ms, tenant=tenant)
        if slo.sli == "canary":
            if tenant != CANARY_TENANT:
                return None
            good = _reg.get_counter("probe.results", node=node, verdict="match")
            bad = _reg.get_counter("probe.results", node=node, verdict="mismatch")
            if good == 0.0 and bad == 0.0:
                return None
            return good, bad
        return None

    def evaluate_all(self, now: Optional[float] = None) -> int:
        """Evaluate every (tenant, slo) pair with data; returns the number
        of evaluations performed. ``now`` defaults to the history's last
        cut time so manually-driven cuts stay deterministic in tests."""
        if now is None:
            now = self._history._last_cut_s
        if now is None:
            import time

            now = time.time()
        now = float(now)
        evaluated = 0
        for tenant in sorted(self._aggregator.tenants()):
            for name in self.slo_names():
                if self.evaluate(tenant, name, now):
                    evaluated += 1
        self._meter_history_bytes()
        return evaluated

    def evaluate(self, tenant: str, slo_name: str, now: float) -> bool:
        """One (tenant, slo) evaluation: difference cumulative totals,
        update the budget ring, apply the dual-window burn rule with
        edge-triggered transitions. Returns True when an evaluation
        actually ran (the SLI had data)."""
        slo = self._slos[slo_name]
        totals = self._sli_totals(slo, tenant)
        if totals is None:
            return False
        armed = _reg.enabled()
        with self._lock:
            rec = self._budgets.get((tenant, slo_name))
            if rec is None:
                rec = self._budgets[(tenant, slo_name)] = ErrorBudget(
                    tenant, slo_name, generation=self._history.generation
                )
            if rec.generation != self._history.generation:
                # failover fence: the registry these baselines came from
                # belongs to a superseded generation — rebase rather than
                # difference two unrelated histories. The rebased totals
                # and consumed budget survive; only the raw baseline drops.
                rec.generation = self._history.generation
                rec.raw_good = 0.0
                rec.raw_bad = 0.0
                rec.fenced += 1
                if armed:
                    _reg.inc("slo.fenced_evaluations", tenant=tenant, slo=slo_name)
            horizon = max(slo.slow_window_s, slo.budget_window_s)
            rec.observe(now, totals[0], totals[1], horizon)
            rec.evaluations += 1
            burn_fast = rec.burn_rate(now, slo.fast_window_s, slo.budget_fraction)
            burn_slow = rec.burn_rate(now, slo.slow_window_s, slo.budget_fraction)
            firing_now = burn_fast >= slo.fast_burn and burn_slow >= slo.slow_burn
            fired_edge = firing_now and not rec.firing
            cleared_edge = rec.firing and not firing_now
            rec.firing = firing_now
            if fired_edge:
                rec.alerts += 1
            sli = rec.sli(now, slo.fast_window_s)
            remaining = rec.budget_remaining(now, slo)
        if armed:
            _reg.inc("slo.evaluations", slo=slo_name)
            _reg.set_gauge("slo.burn_rate", burn_fast, tenant=tenant, slo=slo_name, window="fast")
            _reg.set_gauge("slo.burn_rate", burn_slow, tenant=tenant, slo=slo_name, window="slow")
            _reg.set_gauge("slo.budget_remaining", remaining, tenant=tenant, slo=slo_name)
            if sli is not None:
                _reg.set_gauge("slo.sli", sli, tenant=tenant, slo=slo_name)
        if fired_edge:
            if armed:
                _reg.inc("slo.alerts", tenant=tenant, slo=slo_name)
                _reg.set_gauge("slo.alert_active", 1.0, tenant=tenant, slo=slo_name)
            key = ("alert", tenant, slo_name)
            if key not in self._warned:
                # one-shot: a clear re-arms the COUNTER (a new burn is a
                # new edge) but not the warning — log-noise discipline,
                # same stance as MetricHistory._transition
                self._warned.add(key)
                from metrics_tpu.utilities.prints import rank_zero_warn

                rank_zero_warn(
                    f"SLO BURN: tenant {tenant!r} slo {slo_name!r} is burning its"
                    f" error budget (fast {burn_fast:.1f}x >= {slo.fast_burn:g}x"
                    f" AND slow {burn_slow:.1f}x >= {slo.slow_burn:g}x;"
                    f" budget remaining {remaining:.1%}) — edge-triggered:"
                    " counted once under slo.alerts until the burn clears"
                )
        elif cleared_edge and armed:
            _reg.set_gauge("slo.alert_active", 0.0, tenant=tenant, slo=slo_name)
        return True

    def _meter_history_bytes(self) -> None:
        """Retained-ring footprint per tenant (``meter.history_bytes``):
        nbytes metadata over retained interval snapshots — no copies."""
        if not _reg.enabled():
            return
        for tenant_id, th in list(self._history._tenants.items()):
            total = 0
            for _, snap in th.retained():
                total += sum(int(leaf.nbytes) for leaf in snap.leaves)
            _reg.set_gauge("meter.history_bytes", float(total), tenant=tenant_id)

    # -- reporting (GET /slo) --------------------------------------------

    def active_alerts(self) -> List[Dict[str, Any]]:
        """Currently-firing (tenant, slo) pairs — the surfaced-not-gating
        detail ``/healthz/ready`` renders beside ``history_alerts``."""
        with self._lock:
            return [
                {"tenant": rec.tenant, "slo": rec.slo, "alerts": rec.alerts}
                for (_, _), rec in sorted(self._budgets.items())
                if rec.firing
            ]

    def report(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The JSON answer for ``GET /slo``: definitions, per-tenant SLI
        values, burn rates, budget remaining, and active alerts."""
        if now is None:
            now = self._history._last_cut_s
        if now is None:
            import time

            now = time.time()
        now = float(now)
        if _reg.enabled():
            _reg.inc("slo.queries")
        tenants: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            items = sorted(self._budgets.items())
            for (tenant, name), rec in items:
                slo = self._slos[name]
                tenants.setdefault(tenant, {})[name] = {
                    "sli": rec.sli(now, slo.fast_window_s),
                    "burn_fast": rec.burn_rate(now, slo.fast_window_s, slo.budget_fraction),
                    "burn_slow": rec.burn_rate(now, slo.slow_window_s, slo.budget_fraction),
                    "budget_remaining": rec.budget_remaining(now, slo),
                    "firing": rec.firing,
                    "alerts": rec.alerts,
                    "evaluations": rec.evaluations,
                    "fenced": rec.fenced,
                    "good": rec.good,
                    "bad": rec.bad,
                }
        return {
            "node": self._aggregator.name,
            "generation": self._history.generation,
            "slos": {name: self._slos[name].config() for name in self.slo_names()},
            "tenants": tenants,
            "active_alerts": self.active_alerts(),
        }

    # -- durability (rides Aggregator.save/restore) ----------------------

    def state_for_checkpoint(self) -> Dict[str, Any]:
        """JSON-safe budget table for the checkpoint manifest
        (``meta["slo"]``): nested ``{tenant: {slo: record}}``."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for (tenant, name), rec in sorted(self._budgets.items()):
                out.setdefault(tenant, {})[name] = rec.to_dict()
        return out

    def load_checkpoint_state(self, meta: Dict[str, Any]) -> None:
        """Adopt saved budget records wholesale (bitwise: plain JSON
        replacing the fresh table). Records for slos this engine does not
        define are ignored (the re-register-before-restore stance);
        already-firing records suppress the one-shot re-warn — the alert
        edge was announced by the node that saw it."""
        with self._lock:
            for tenant, slos in (meta or {}).items():
                for name, saved in (slos or {}).items():
                    if name not in self._slos:
                        continue
                    rec = ErrorBudget.from_dict(dict(saved, tenant=tenant, slo=name))
                    self._budgets[(str(tenant), str(name))] = rec
                    if rec.firing:
                        self._warned.add(("alert", str(tenant), str(name)))
                        if _reg.enabled():
                            _reg.set_gauge(
                                "slo.alert_active", 1.0, tenant=str(tenant), slo=str(name)
                            )

    def reset_budgets(self) -> None:
        """Drop every budget record and re-arm the one-shot warnings
        (:func:`metrics_tpu.obs.reset` clears all live engines this way)."""
        with self._lock:
            self._budgets.clear()
            self._warned.clear()


def reset() -> None:
    """Clear the budget tables of every live engine — the module-level
    hook :func:`metrics_tpu.obs.reset` calls so SLO state cannot bleed
    between measurement windows."""
    for engine in list(_ENGINES):
        engine.reset_budgets()
