"""Obs-snapshot federation: the per-node table behind the fleet view.

A serving tree spans processes, but each :mod:`metrics_tpu.obs` registry
ends at its process boundary — the root's ``/metrics`` used to show the
root's counters and nothing of the leaves where the latency actually
lives. Federation closes that gap with the same design the serving tier
already trusts end to end: **cumulative snapshots + keep-latest per
identity**.

* Every node's :func:`metrics_tpu.obs.snapshot` carries its process
  ``node`` identity and a ``captured_at`` wall timestamp.
* On each upward ship, a tree node piggybacks its current snapshot (plus
  every remote snapshot it has already collected — so leaves' telemetry
  transits intermediates) in the payload's forward-compatible ``meta``
  side-channel (``meta["obs_nodes"]``, wire minor 2). Unarmed, nothing is
  attached: zero wire bytes.
* A receiving aggregator stores each snapshot in this process-global
  table, keep-latest by ``captured_at`` per node identity. Snapshots are
  cumulative (counters monotone), so keep-latest is exact — no delta
  arithmetic, idempotent under duplicated or reordered delivery, exactly
  the watermark argument ``docs/serving.md`` makes for metric state.
* :func:`federated_snapshot` merges the local registry with every stored
  remote through :func:`metrics_tpu.obs.export.merge_snapshots` (counters
  sum, gauges keep per-node labels, histograms merge bucketwise-exact) —
  the view the root's ``/metrics`` scrape and ``/healthz/ready`` render,
  and the input :class:`~metrics_tpu.obs.health.HealthMonitor` fleet
  conditions read.

Snapshots from this process's own identity are ignored on accept (the
live registry is always fresher), which is also what keeps the in-process
:class:`~metrics_tpu.serve.tree.AggregationTree` emulation exact: all its
nodes share one registry *and one identity*, so the piggyback loop never
double-counts.

:func:`metrics_tpu.obs.reset` clears the table along with the registry so
back-to-back bench rounds and tests cannot bleed fleet state.
"""
import threading
import time
from typing import Any, Dict, List, Optional

from metrics_tpu.obs import export as _export
from metrics_tpu.obs import registry as _reg

__all__ = [
    "accept_snapshot",
    "federated_snapshot",
    "node_ages",
    "remote_count",
    "remote_snapshots",
    "reset",
    "wire_snapshots",
]

_lock = threading.Lock()
# node identity -> newest accepted snapshot (cumulative; keep-latest exact)
_remote: Dict[str, Dict[str, Any]] = {}

# hard cap on DISTINCT node identities the table will hold: snapshot
# identities arrive in client-controlled payload meta, so without a cap a
# hostile client minting a fresh identity per payload would grow this
# process-global table (and every /metrics render) without bound — the
# same cardinality class max_series_per_family guards in the registry.
# Far above any real tree's node count; overflow counts
# obs.federation_nodes_dropped so a genuinely huge fleet is visible.
MAX_FEDERATION_NODES = 1024

# reject captured_at stamps further in the future than this: keep-latest
# can never evict a forged-future entry (every sane snapshot compares
# older), so one hostile timestamp would pin a poisoned snapshot in the
# table forever. Generous enough for real cross-host clock skew.
MAX_FUTURE_SKEW_S = 3600.0


def _valid_series(snap: Dict[str, Any]) -> bool:
    """Shallow shape validation before a snapshot may enter the table.

    One malformed entry (version-skewed histogram bucket layout, non-dict
    or non-numeric series values) would otherwise be stored and make EVERY
    later ``federated_snapshot()`` — and therefore every ``/metrics``
    scrape and federated health check — raise until a process-wide reset:
    the merge is exact precisely because it refuses to guess, so the
    gatekeeping has to happen here, where the one bad sender can be
    dropped without costing the fleet view."""
    n_buckets = len(_reg.HISTOGRAM_EDGES) + 1
    for family in ("counters", "gauges"):
        for value in (snap.get(family) or {}).values():
            if not isinstance(value, (int, float)):
                return False
    for hist in (snap.get("histograms") or {}).values():
        if not isinstance(hist, dict):
            return False
        buckets = hist.get("buckets")
        if not isinstance(buckets, list) or len(buckets) != n_buckets:
            return False
        if not all(isinstance(b, (int, float)) for b in buckets):
            return False
        if not isinstance(hist.get("sum", 0.0), (int, float)):
            return False
        if not isinstance(hist.get("count", 0), (int, float)):
            return False
    return True


def accept_snapshot(snap: Dict[str, Any]) -> bool:
    """Store one remote node snapshot, keep-latest by ``captured_at``.

    Returns True when the table advanced (new node, or fresher capture).
    Snapshots without a node identity, with malformed series (non-dict
    maps, non-numeric values, a histogram whose bucket layout does not
    match this build's :data:`HISTOGRAM_EDGES` — merging would raise on
    every later render), with a ``captured_at`` forged further than
    :data:`MAX_FUTURE_SKEW_S` into the future (keep-latest could never
    evict it), from this process's own identity (the live registry is
    always fresher), or older than what is already held are dropped —
    at-least-once piggyback delivery reduces to a timestamp comparison,
    the same way payload dedup reduces to a watermark comparison. New
    identities past :data:`MAX_FEDERATION_NODES` are refused (counted
    under ``obs.federation_nodes_dropped``).
    """
    if not isinstance(snap, dict):
        return False
    node = snap.get("node")
    if not node or snap.get("federated"):
        return False
    for family in ("counters", "gauges", "histograms"):
        if not isinstance(snap.get(family, {}), dict):
            return False
    if not _valid_series(snap):
        return False
    node = str(node)
    if node == _reg.node_identity():
        return False
    try:
        captured = float(snap.get("captured_at", 0.0))
    except (TypeError, ValueError):
        return False
    if captured > time.time() + MAX_FUTURE_SKEW_S:
        return False
    with _lock:
        held = _remote.get(node)
        if held is None and len(_remote) >= MAX_FEDERATION_NODES:
            _reg.inc("obs.federation_nodes_dropped")
            return False
        if held is not None and float(held.get("captured_at", 0.0)) >= captured:
            return False
        _remote[node] = snap
    return True


def remote_snapshots() -> Dict[str, Dict[str, Any]]:
    """A copy of the per-node table (identity -> newest snapshot)."""
    with _lock:
        return dict(_remote)


def remote_count() -> int:
    """Number of remote nodes in the table — the cheap has-any-remotes
    probe for hot paths (a scrape-rate full-table copy just to test
    truthiness would be waste)."""
    with _lock:
        return len(_remote)


def wire_snapshots() -> List[Dict[str, Any]]:
    """What a tree node piggybacks on its next ship: its own compact local
    snapshot plus every remote one it holds, so telemetry from the whole
    subtree transits each hop. Histogram ``edges`` are stripped from the
    local capture (they are the shared :data:`HISTOGRAM_EDGES` constant —
    dead weight on the wire; :func:`merge_snapshots` re-derives them)."""
    local = _export.snapshot(spans=False)
    for hist in local["histograms"].values():
        hist.pop("edges", None)
    with _lock:
        return [local] + list(_remote.values())


def federated_snapshot() -> Dict[str, Any]:
    """The fleet view: local registry merged with every stored remote
    snapshot. With an empty table this is exactly the plain local
    :func:`metrics_tpu.obs.snapshot` (no relabeling a single-process
    deployment never asked for)."""
    with _lock:
        remotes = list(_remote.values())
    if not remotes:
        return _export.snapshot(spans=False)
    return _export.merge_snapshots(_export.snapshot(spans=False), *remotes)


def node_ages(now: Optional[float] = None) -> Dict[str, float]:
    """Seconds since each federated node's snapshot was captured (the
    local node reads 0.0) — the staleness signal the
    :class:`~metrics_tpu.obs.health.HealthMonitor` ``stale_node``
    condition and ``/healthz/ready`` fleet detail read. Wall-clock
    cross-process, so severe clock skew shows up here rather than hiding."""
    now = time.time() if now is None else float(now)
    ages = {_reg.node_identity(): 0.0}
    with _lock:
        for node, snap in _remote.items():
            ages[node] = max(0.0, now - float(snap.get("captured_at", 0.0)))
    return ages


def reset() -> None:
    """Clear the per-node table (:func:`metrics_tpu.obs.reset` calls this
    alongside the registry clear)."""
    with _lock:
        _remote.clear()
